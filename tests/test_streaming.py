"""Streaming (online-learning) subsystem tests (`streaming/`).

The contracts under test:

- **delta = full re-export, bit for bit**: folding published deltas into
  a running serve engine yields EXACTLY the artifact a full re-export at
  the same watermark would — f32 bit-exact, int8/fp8 quant-exact (the
  same bytes) — across raw/dedup/tiered layouts and world 1/2/4.
- **the tracker's row set is exact**: rows the batches routed advance,
  nothing else does; the delta ships exactly the advanced set.
- **chain durability**: a torn (corrupt) delta is refused and skipped
  with the failing field named; an out-of-order seq is refused; a
  base_fingerprint mismatch is refused naming the field; a publish
  killed by injected ``ckpt_write``/``ckpt_rename`` faults leaves only a
  manifest-less ``.tmp`` the subscriber never reads, and the retried
  publish converges it to the last valid delta.
- **dynvocab rides the delta**: a raw id newly admitted by training is
  servable after ONE delta cycle — no full re-export — through the
  promoted read-only snapshot.
- **live hot-set adaptation**: the publisher-shipped observed counts
  re-rank the tiered serve cache through the prefetcher's re-rank
  machinery, value-preservingly.
- **copy-on-promote never pauses traffic**: a micro-batcher keeps
  dispatching while deltas fold in; every request resolves.
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu import checkpoint
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    set_weights,
)
from distributed_embeddings_tpu.layers.embedding import TableConfig
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM
from distributed_embeddings_tpu.models.dlrm import (
    _dlrm_initializer,
    bce_loss,
)
from distributed_embeddings_tpu.models.synthetic import power_law_ids
from distributed_embeddings_tpu.dynvocab import DynVocabTranslator
from distributed_embeddings_tpu.ops.packed_table import sparse_rule
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.parallel.lookup_engine import PAD_ID
from distributed_embeddings_tpu.resilience import faultinject
from distributed_embeddings_tpu.serving import (
    MicroBatcher,
    ServeEngine,
    ServeTierConfig,
)
from distributed_embeddings_tpu.serving.export import export as serve_export
from distributed_embeddings_tpu.serving.export import load as serve_load
from distributed_embeddings_tpu.resilience import retry
from distributed_embeddings_tpu.resilience.trainer import ResilientTrainer
from distributed_embeddings_tpu.streaming import (
    ChainDivergedError,
    DeltaCompactor,
    DeltaPublisher,
    DeltaSubscriber,
    RowGenerationTracker,
    artifact_bytes,
    delta_dirname,
    published_delta_seqs,
    read_heartbeats,
    write_heartbeat,
)
from distributed_embeddings_tpu.telemetry import MetricsRegistry
from distributed_embeddings_tpu.tiering import (
    HostTierStore,
    TieredTrainer,
    TieringConfig,
    TieringPlan,
    init_tiered_state_from_params,
)
from distributed_embeddings_tpu.training import (
    init_sparse_state,
    make_sparse_train_step,
    shard_batch,
    shard_params,
)


class ActsModel:
  """Embedding-activations stub: every table's rows visible in preds."""

  def apply(self, variables, numerical, cats, emb_acts=None):
    del variables, numerical, cats
    return jnp.concatenate(list(emb_acts), axis=-1)


def loss_fn(preds, labels):
  return jnp.mean((jnp.sum(preds, axis=-1) - labels) ** 2)


SIZES = [131, 97, 53, 40, 67]
WIDTHS = [16, 16, 8, 8, 16]
HOTNESS = [3, 1, 3, 2, 1]


def _mkbatch(rng, b):
  ids = []
  for s, h in zip(SIZES, HOTNESS):
    x = rng.integers(0, s, (b, h)).astype(np.int32)
    x[rng.random(x.shape) < 0.25] = PAD_ID
    ids.append(x)
  return (rng.standard_normal((b, 4)).astype(np.float32), ids,
          rng.integers(0, 2, b).astype(np.float32))


def _device_run(tmp_path, world, quantize="f32", dedup=False,
                pre_steps=2, post_steps=2, registry=None):
  """Train, publish base, train more, publish a delta; returns the
  pieces every device-tier test compares."""
  rng = np.random.default_rng(world * 31 + (7 if dedup else 0))
  tables = [TableConfig(s, w, combiner="sum")
            for s, w in zip(SIZES, WIDTHS)]
  plan = DistEmbeddingStrategy(tables, world, "memory_balanced",
                               dense_row_threshold=0,
                               input_hotness=HOTNESS,
                               dedup_exchange=dedup)
  weights = [rng.standard_normal((s, w)).astype(np.float32)
             for s, w in zip(SIZES, WIDTHS)]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.sgd(0.01)
  mesh = create_mesh(world) if world > 1 else None
  state = shard_params(init_sparse_state(plan, params, rule, opt), mesh)
  b = 4 * world
  batch0 = _mkbatch(rng, b)
  step = make_sparse_train_step(ActsModel(), plan, loss_fn, opt, rule,
                                mesh, state, batch0, donate=False)

  pub = os.path.join(str(tmp_path), "pub")
  tracker = RowGenerationTracker(plan)
  publisher = DeltaPublisher(pub, plan, rule, tracker, quantize=quantize,
                             telemetry=registry)

  def train(state, n):
    for _ in range(n):
      batch = _mkbatch(rng, b)
      publisher.observe_batch(batch[1])
      state, _ = step(state, *shard_batch(batch, mesh))
    return state

  state = train(state, pre_steps)
  publisher.publish_base(state)
  sub = DeltaSubscriber.from_artifact(ActsModel(), plan, pub, mesh=mesh,
                                      telemetry=registry)
  state = train(state, post_steps)
  assert publisher.publish_delta(state) is not None
  return plan, rule, mesh, state, publisher, sub, rng, b


def _full_engine(tmp_path, plan, rule, mesh, state, quantize,
                 store=None, model=None, tier_config=None, vocab=None):
  full = os.path.join(str(tmp_path), "full")
  serve_export(full, plan, rule, state, quantize=quantize, store=store,
               vocab=vocab)
  art = serve_load(full, plan, mesh=mesh)
  eng = ServeEngine(model or ActsModel(), plan, art, mesh=mesh,
                    tier_config=tier_config)
  return eng, art


# ---------------------------------------------------------------------------
# the tracker: exact row accounting
# ---------------------------------------------------------------------------


def test_tracker_rows_exact_and_watermarked():
  plan = DistEmbeddingStrategy(
      [TableConfig(64, 8, combiner="sum"), TableConfig(40, 8,
                                                       combiner="sum")],
      1, "basic", dense_row_threshold=0, input_hotness=[2, 1])
  tracker = RowGenerationTracker(plan)
  cats = [np.array([[3, 5], [3, PAD_ID]], np.int32),
          np.array([[7], [7]], np.int32)]
  c1 = tracker.observe(cats)
  changed = tracker.changed_rows(0)
  (name,) = changed  # both tables share one w8 class
  rows = np.concatenate(changed[name])
  # exactly the routed valid ids (table 1 offsets by table 0's rows)
  off = {s[0]: s[1] for s in plan.routing_recipe(
      list(plan.class_keys)[0])[0]}
  want = sorted({3 + off[0], 5 + off[0], 7 + off[1]})
  assert sorted(rows.tolist()) == want
  # counts weigh occurrences (3 twice, 7 twice, 5 once)
  cnt = tracker.counts[name][0]
  assert cnt[3 + off[0]] == 2 and cnt[5 + off[0]] == 1 \
      and cnt[7 + off[1]] == 2
  # watermark filters: nothing advanced past c1
  assert tracker.changed_row_total(c1) == 0
  tracker.observe([np.array([[9, PAD_ID]], np.int32),
                   np.full((1, 1), PAD_ID, np.int32)])
  assert np.concatenate(
      tracker.changed_rows(c1)[name]).tolist() == [9 + off[0]]


# ---------------------------------------------------------------------------
# delta == full re-export: the parity matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("world", [1, 2, 4])
@pytest.mark.parametrize("dedup", [False, True])
def test_delta_parity_f32(tmp_path, world, dedup):
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, world, "f32", dedup)
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, "f32")
  assert sub.poll_once() == 1
  for name, want in art.state["serve"].items():
    np.testing.assert_array_equal(
        np.asarray(sub.engine.state["serve"][name]), np.asarray(want))
  probe = _mkbatch(rng, b)
  np.testing.assert_array_equal(sub.predict(probe[0], probe[1]),
                                engB.predict(probe[0], probe[1]))


@pytest.mark.parametrize("quantize", ["int8", "fp8"])
def test_delta_parity_quantized(tmp_path, quantize):
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 2, quantize)
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, quantize)
  assert sub.poll_once() == 1
  for name, want in art.state["serve"].items():
    got = np.asarray(sub.engine.state["serve"][name])
    # quant-exact: the same stored bytes, not merely close dequants
    np.testing.assert_array_equal(got.view(np.uint8),
                                  np.asarray(want).view(np.uint8))
  probe = _mkbatch(rng, b)
  np.testing.assert_array_equal(sub.predict(probe[0], probe[1]),
                                engB.predict(probe[0], probe[1]))


def test_multi_delta_chain(tmp_path):
  """Three consecutive deltas applied in order land on the same state
  as one full export; the chain fingerprints advance."""
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 2, "f32")
  assert sub.poll_once() == 1
  fp1 = sub.fingerprint
  step = make_sparse_train_step(ActsModel(), plan, loss_fn,
                                optax.sgd(0.01), rule, mesh, state,
                                _mkbatch(rng, b), donate=False)
  for _ in range(2):
    batch = _mkbatch(rng, b)
    publisher.observe_batch(batch[1])
    state, _ = step(state, *shard_batch(batch, mesh))
    assert publisher.publish_delta(state) is not None
  assert sub.poll_once() == 2
  assert sub.applied_seq == 3 and sub.fingerprint != fp1
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, "f32")
  for name, want in art.state["serve"].items():
    np.testing.assert_array_equal(
        np.asarray(sub.engine.state["serve"][name]), np.asarray(want))


def test_delta_bytes_far_below_full_export(tmp_path):
  """On a churn workload (few rows advance per interval) the delta
  payload is a small fraction of the full artifact."""
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 2, "f32", pre_steps=2, post_steps=1)
  base_bytes = artifact_bytes(os.path.join(sub.path, "base"))
  assert publisher.last_publish_bytes < base_bytes / 2, \
      (publisher.last_publish_bytes, base_bytes)


# ---------------------------------------------------------------------------
# tiered: images, prediction parity, hot-set adaptation
# ---------------------------------------------------------------------------

T_VOCAB = [2000, 300, 40]
T_WIDTH = 16


def _tiered_run(tmp_path, world, quantize, post_steps=2):
  tables = [TableConfig(input_dim=v, output_dim=T_WIDTH,
                        initializer=_dlrm_initializer(v)) for v in T_VOCAB]
  plan = DistEmbeddingStrategy(tables, world, "memory_balanced",
                               dense_row_threshold=0,
                               host_row_threshold=1000)
  model = DLRM(vocab_sizes=T_VOCAB, embedding_dim=T_WIDTH,
               bottom_mlp=(32, T_WIDTH), top_mlp=(32, 1),
               world_size=world, strategy="memory_balanced",
               dense_row_threshold=0)
  mesh = create_mesh(world) if world > 1 else None
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adam(1e-3)
  rng = np.random.default_rng(world)

  def batch(seed, n=32):
    r = np.random.default_rng(seed)
    return (r.standard_normal((n, 13)).astype(np.float32),
            [power_law_ids(r, n, 1, v, 1.05).astype(np.int32)[:, 0]
             for v in T_VOCAB],
            r.integers(0, 2, n).astype(np.float32))

  b0 = batch(100)
  params_b = model.init(jax.random.PRNGKey(0), b0[0], b0[1])["params"]
  # the model's own plan is untiered: remap its table weights onto the
  # tiered plan's class layout (generation assignment differs)
  from distributed_embeddings_tpu.layers.dist_model_parallel import (
      get_weights)
  plan_b = DistEmbeddingStrategy(tables, world, "memory_balanced",
                                 dense_row_threshold=0)
  tables_t = set_weights(plan, get_weights(plan_b,
                                           params_b["embeddings"]))
  params = {k: v for k, v in params_b.items() if k != "embeddings"}
  params["embeddings"] = {k: jnp.asarray(v) for k, v in tables_t.items()}
  tplan = TieringPlan(plan, rule, TieringConfig(cache_fraction=0.3,
                                                staging_grps=64))
  store = HostTierStore(tplan)
  state = shard_params(init_tiered_state_from_params(
      tplan, store, rule, params, opt, mesh=mesh), mesh)
  trainer = TieredTrainer(model, tplan, store, bce_loss, opt, rule, mesh,
                          state, b0, donate=False)
  pub = os.path.join(str(tmp_path), "pub")
  tracker = RowGenerationTracker(plan)
  publisher = DeltaPublisher(pub, plan, rule, tracker, quantize=quantize,
                             store=store)
  for i in range(2):
    bt = batch(100 + i)
    publisher.observe_batch(bt[1])
    trainer.step(*bt)
  publisher.publish_base(trainer.state)
  cfg = ServeTierConfig(cache_fraction=0.3, staging_grps=64)
  sub = DeltaSubscriber.from_artifact(model, plan, pub, mesh=mesh,
                                      tier_config=cfg, with_metrics=True)
  for i in range(post_steps):
    bt = batch(200 + i)
    publisher.observe_batch(bt[1])
    trainer.step(*bt)
  assert publisher.publish_delta(trainer.state) is not None
  return (plan, model, mesh, rule, trainer, store, publisher, sub, cfg,
          batch)


@pytest.mark.parametrize("world,quantize",
                         [(1, "f32"), (2, "f32"), (4, "f32"), (4, "int8")])
def test_delta_parity_tiered(tmp_path, world, quantize):
  (plan, model, mesh, rule, trainer, store, publisher, sub, cfg,
   batch) = _tiered_run(tmp_path, world, quantize)
  assert sub.poll_once() == 1
  full = os.path.join(str(tmp_path), "full")
  serve_export(full, plan, rule, trainer.state, quantize=quantize,
               store=store)
  art = serve_load(full, plan, mesh=mesh)
  # cold images: the delta fold reproduced the full export bit for bit
  for name, images in art.host_images.items():
    for r, img in enumerate(images):
      np.testing.assert_array_equal(
          sub.engine.store.images[name][r].view(np.uint8),
          np.asarray(img).view(np.uint8))
  engB = ServeEngine(model, plan, art, mesh=mesh, tier_config=cfg,
                     with_metrics=True)
  probe = batch(999)
  pa, ma = sub.predict(probe[0], probe[1])
  pb, _mb = engB.predict(probe[0], probe[1])
  np.testing.assert_array_equal(pa, pb)
  assert all(int(v[2]) == 0 for v in ma["tier"].values())  # no misses


def test_delta_extraction_is_flush_free(tmp_path):
  """publish_delta reads tiered classes through the store's overlay
  reader: the live host images are NOT mutated (no bulk flush, no
  device_get of the whole cache), yet the shipped bytes fold to the
  full export — so the trainer-side overlap worker can keep gathering
  cold rows from the images while a publish is extracting."""
  (plan, model, mesh, rule, trainer, store, publisher, sub, cfg,
   batch) = _tiered_run(tmp_path, 2, "f32")
  bt = batch(300)
  publisher.observe_batch(bt[1])
  trainer.step(*bt)
  before = {name: [None if img is None else img.copy()
                   for img in imgs]
            for name, imgs in store.images.items()}
  assert publisher.publish_delta(trainer.state) is not None
  for name, imgs in store.images.items():
    for r, img in enumerate(imgs):
      if img is not None:
        np.testing.assert_array_equal(img, before[name][r],
                                      err_msg=f"{name} rank {r}")
  # and the flush-free bytes still land the exact serve state
  assert sub.poll_once() == 2
  full = os.path.join(str(tmp_path), "full")
  serve_export(full, plan, rule, trainer.state, quantize="f32",
               store=store)
  art = serve_load(full, plan, mesh=mesh)
  for name, images in art.host_images.items():
    for r, img in enumerate(images):
      np.testing.assert_array_equal(
          sub.engine.store.images[name][r].view(np.uint8),
          np.asarray(img).view(np.uint8))


def test_tiered_hot_set_adapts_to_shipped_counts(tmp_path):
  """The publisher's counts re-rank the serve cache: after the fold,
  every rank's resident set is a top-count set under the shipped
  signal (the prefetcher's own re-rank machinery, now exercised on the
  serve path)."""
  (plan, model, mesh, rule, trainer, store, publisher, sub, cfg,
   batch) = _tiered_run(tmp_path, 2, "f32")
  assert sub.poll_once() == 1
  eng = sub.engine
  shipped_total = 0
  for name in eng.store.images:
    c = eng.tplan.by_name(name)
    for rank in range(plan.world_size):
      counts = eng.store.counts[name][rank]
      shipped_total += int(counts.sum())
      resident = set(eng.store.resident_grps[name][rank].tolist())
      assert len(resident) == c.spec.cache_grps
      # no non-resident row outranks the weakest resident row
      floor = min(int(counts[g]) for g in resident)
      outside = np.delete(counts, sorted(resident))
      assert outside.size == 0 or int(outside.max()) <= floor
  # the shipped signal landed somewhere (a power-law stream may leave a
  # cold rank's vocab window untouched — that rank's zeros are correct)
  assert shipped_total > 0


# ---------------------------------------------------------------------------
# dynvocab: a newly admitted raw id is servable after one delta cycle
# ---------------------------------------------------------------------------


def test_dynvocab_new_id_servable_after_one_delta(tmp_path):
  world = 2
  sizes, widths, hot = [256, 40], [16, 8], [2, 1]

  def mk(**kw):
    return DistEmbeddingStrategy(
        [TableConfig(s, w, combiner="sum") for s, w in zip(sizes, widths)],
        world, "memory_balanced", dense_row_threshold=0,
        input_hotness=hot, **kw)

  plan = mk(oov="allocate", admit_threshold=1)
  serve_plan = mk()  # same tables -> same fingerprint; serving clips
  rng = np.random.default_rng(0)
  weights = [rng.standard_normal((s, w)).astype(np.float32) * 0.1
             for s, w in zip(sizes, widths)]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.sgd(0.01)
  mesh = create_mesh(world)
  state = shard_params(init_sparse_state(plan, params, rule, opt), mesh)
  b = 8

  def mkbatch(extra_id=None):
    ids = [rng.integers(0, 10**9, (b, h)).astype(np.int64) for h in hot]
    if extra_id is not None:
      ids[0][0, 0] = extra_id
    return (rng.standard_normal((b, 4)).astype(np.float32), ids,
            rng.integers(0, 2, b).astype(np.float32))

  translator = DynVocabTranslator(plan, rule)
  b0 = mkbatch()
  cats0, _, _ = translator.translate_batch(b0[1])
  step = make_sparse_train_step(ActsModel(), plan, loss_fn, opt, rule,
                                mesh, state, (b0[0], cats0, b0[2]),
                                donate=False)
  pub = os.path.join(str(tmp_path), "pub")
  tracker = RowGenerationTracker(plan)
  publisher = DeltaPublisher(pub, plan, rule, tracker, quantize="f32",
                             vocab=translator)

  def train(state, raw):
    cats_t, _, _ = translator.translate_batch(raw[1])
    publisher.observe_batch(cats_t)  # the ids the STEP consumes
    state, _ = step(state, *shard_batch((raw[0], cats_t, raw[2]), mesh))
    return state

  state = train(state, mkbatch())
  publisher.publish_base(state)
  sub = DeltaSubscriber.from_artifact(ActsModel(), serve_plan, pub,
                                      mesh=mesh)
  assert sub.translator is not None  # snapshot rode the base artifact

  new_id = 987_654_321
  probe = mkbatch(new_id)
  assert sub.translator.translate(
      [np.asarray(c) for c in probe[1]])[0][0, 0] == PAD_ID
  p_before = sub.predict(probe[0], probe[1])

  state = train(state, probe)  # admits new_id, trains its row
  assert publisher.publish_delta(state) is not None
  assert sub.poll_once() == 1  # ONE delta cycle, no full re-export

  row = sub.translator.translate(
      [np.asarray(c) for c in probe[1]])[0][0, 0]
  assert row >= 0  # servable: the promoted snapshot maps it
  p_after = sub.predict(probe[0], probe[1])
  assert not np.array_equal(p_before[0], p_after[0])

  # and the delta-cycled engine agrees with a full re-export + readonly
  # translation of the same state
  engB, art = _full_engine(tmp_path, serve_plan, rule, mesh, state,
                           "f32", vocab=translator)
  cats_ro = art.vocab.translate([np.asarray(c) for c in probe[1]])
  np.testing.assert_array_equal(p_after, engB.predict(probe[0], cats_ro))


# ---------------------------------------------------------------------------
# chain durability: torn, out-of-order, forked, faulted
# ---------------------------------------------------------------------------


def test_torn_delta_refused_and_skipped(tmp_path):
  reg = MetricsRegistry()
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 2, "f32", registry=reg)
  dpath = os.path.join(sub.path, "delta_000001")
  victim = sorted(f for f in os.listdir(dpath)
                  if f.startswith("rows_"))[0]
  faultinject.bitflip_file(os.path.join(dpath, victim))
  probe = _mkbatch(rng, b)
  before = sub.predict(probe[0], probe[1])
  assert sub.poll_once() == 0  # refused, not applied, not crashed
  assert sub.applied_seq == 0
  assert sub.last_refusal["field"] == "checksums"
  assert victim in sub.last_refusal["reason"]
  assert reg.counter("stream/deltas_refused").value == 1
  # still serving the last valid artifact
  np.testing.assert_array_equal(sub.predict(probe[0], probe[1]), before)


def test_manifestless_tmp_dir_ignored(tmp_path):
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  assert sub.poll_once() == 1
  # a crashed publish leaves a manifest-less .tmp: never even considered
  os.makedirs(os.path.join(sub.path, "delta_000002.tmp"))
  assert sub.poll_once() == 0
  assert sub.last_refusal is None


def test_out_of_order_seq_refused(tmp_path):
  import shutil
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  step = make_sparse_train_step(ActsModel(), plan, loss_fn,
                                optax.sgd(0.01), rule, mesh, state,
                                _mkbatch(rng, b), donate=False)
  batch = _mkbatch(rng, b)
  publisher.observe_batch(batch[1])
  state, _ = step(state, *shard_batch(batch, mesh))
  publisher.publish_delta(state)
  shutil.rmtree(os.path.join(sub.path, "delta_000001"))
  assert sub.poll_once() == 0
  assert sub.last_refusal["field"] == "seq"
  assert sub.applied_seq == 0


def test_base_fingerprint_mismatch_refused_naming_field(tmp_path):
  import json
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  mpath = os.path.join(sub.path, "delta_000001", "manifest.json")
  with open(mpath) as f:
    manifest = json.load(f)
  manifest["base_fingerprint"] = "f" * 64  # a fork/replay
  with open(mpath, "w") as f:
    json.dump(manifest, f)
  assert sub.poll_once() == 0
  assert sub.last_refusal["field"] == "base_fingerprint"
  assert "base_fingerprint" in sub.last_refusal["reason"]


def test_out_of_bounds_delta_rows_refused(tmp_path):
  """A delta whose row indices fall outside the class geometry is
  refused with the field named — a silent device scatter-drop would
  break the delta==re-export invariant, and a raw host IndexError
  would loop the poll thread instead of recording a refusal. The file
  is re-sealed (manifest crc updated), so only the bounds check can
  catch it."""
  import json
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  dpath = os.path.join(sub.path, "delta_000001")
  victim = sorted(f for f in os.listdir(dpath)
                  if f.startswith("rows_"))[0]
  fpath = os.path.join(dpath, victim)
  with np.load(fpath) as z:
    idx, data = np.asarray(z["idx"]), np.asarray(z["data"])
  idx[-1] = 10**9
  np.savez(fpath, idx=idx, data=data)
  mpath = os.path.join(dpath, "manifest.json")
  with open(mpath) as f:
    manifest = json.load(f)
  manifest["checksums"][victim] = checkpoint._crc32_file(fpath)
  with open(mpath, "w") as f:
    json.dump(manifest, f)
  assert sub.poll_once() == 0
  assert sub.applied_seq == 0
  assert sub.last_refusal["field"] == "rows"
  assert "1000000000" in sub.last_refusal["reason"]


@pytest.mark.parametrize("site", ["ckpt_write", "ckpt_rename"])
def test_faulted_publish_retries_and_converges(tmp_path, site):
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 2, "f32")
  assert sub.poll_once() == 1
  step = make_sparse_train_step(ActsModel(), plan, loss_fn,
                                optax.sgd(0.01), rule, mesh, state,
                                _mkbatch(rng, b), donate=False)
  batch = _mkbatch(rng, b)
  publisher.observe_batch(batch[1])
  state, _ = step(state, *shard_batch(batch, mesh))
  seq_before = publisher.seq
  # 0-indexed: ckpt_rename fires once per publish, ckpt_write per file —
  # crash the first event either way
  inj = faultinject.FaultInjector().crash_after(site, 0)
  with faultinject.injected(inj):
    with pytest.raises(faultinject.InjectedCrash):
      publisher.publish_delta(state)
  # the chain did not advance; nothing published the subscriber can see
  assert publisher.seq == seq_before
  assert sub.poll_once() == 0
  # retry (fault cleared) publishes the SAME seq; subscriber converges
  assert publisher.publish_delta(state) is not None
  assert sub.poll_once() == 1
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, "f32")
  for name, want in art.state["serve"].items():
    np.testing.assert_array_equal(
        np.asarray(sub.engine.state["serve"][name]), np.asarray(want))


def test_publisher_rebase_resets_chain(tmp_path):
  """A restarted publisher (no tracker history) re-roots with a new
  base; the subscriber detects the fingerprint change and rebases."""
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  assert sub.poll_once() == 1
  old_base_fp = sub.base_fingerprint
  # restart: fresh tracker/publisher, one more step, publish_base anew
  tracker2 = RowGenerationTracker(plan)
  pub2 = DeltaPublisher(sub.path, plan, rule, tracker2, quantize="f32")
  step = make_sparse_train_step(ActsModel(), plan, loss_fn,
                                optax.sgd(0.01), rule, mesh, state,
                                _mkbatch(rng, b), donate=False)
  batch = _mkbatch(rng, b)
  tracker2.observe(batch[1])
  state, _ = step(state, *shard_batch(batch, mesh))
  pub2.publish_base(state)
  assert sub.poll_once() >= 1  # the rebase
  assert sub.base_fingerprint != old_base_fp
  assert sub.applied_seq == 0
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, "f32")
  probe = _mkbatch(rng, b)
  np.testing.assert_array_equal(sub.predict(probe[0], probe[1]),
                                engB.predict(probe[0], probe[1]))


# ---------------------------------------------------------------------------
# copy-on-promote under live traffic
# ---------------------------------------------------------------------------


def test_promote_under_concurrent_batcher_traffic(tmp_path):
  """Deltas fold in while a micro-batcher keeps dispatching: every
  request resolves, no dispatch ever mixes old and new state (the lock
  pairs translate+dispatch with a consistent snapshot), and the final
  state equals the full re-export."""
  reg = MetricsRegistry()  # isolated: the freshness count is asserted
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 2, "f32", registry=reg)
  step = make_sparse_train_step(ActsModel(), plan, loss_fn,
                                optax.sgd(0.01), rule, mesh, state,
                                _mkbatch(rng, b), donate=False)
  batcher = MicroBatcher(sub.dispatch, max_batch=b, max_delay_s=0.001,
                         registry=MetricsRegistry())
  # run the whole storm under the lockorder sanitizer: batcher flush/
  # complete loops, client submits, and the subscriber's fold-vs-
  # dispatch exclusion on the engine lock all record real acquisition
  # edges, checked against threadlint's static graph at the end
  from distributed_embeddings_tpu.analysis import threadlint
  from distributed_embeddings_tpu.telemetry import LockOrderMonitor
  mon = LockOrderMonitor()
  batcher._lock = mon.wrap(batcher._lock, "MicroBatcher._lock")
  batcher._nonempty = mon.wrap(batcher._nonempty, "MicroBatcher._lock")
  sub.engine.lock = mon.wrap(sub.engine.lock, "ServeEngine.lock")
  stop = threading.Event()
  failures = []

  def client():
    r = np.random.default_rng(threading.get_ident() % 2**31)
    while not stop.is_set():
      n = int(r.integers(1, b + 1))
      batch = _mkbatch(np.random.default_rng(int(r.integers(2**31))), n)
      try:
        fut = batcher.submit(batch[0], batch[1])
        fut.result(timeout=30.0)
      except Exception as e:  # noqa: BLE001 — collected for the assert
        from distributed_embeddings_tpu.serving import Rejected
        if not isinstance(e, Rejected):
          failures.append(e)

  threads = [threading.Thread(target=client) for _ in range(3)]
  for t in threads:
    t.start()
  sub.start()
  try:
    for _ in range(3):
      batch = _mkbatch(rng, b)
      publisher.observe_batch(batch[1])
      state, _ = step(state, *shard_batch(batch, mesh))
      publisher.publish_delta(state)
    # let the subscriber catch the last delta WHILE the clients still
    # hammer it — stopping right at the final publish races the poll
    # loop (the fold itself is what's under test, not the shutdown
    # timing)
    deadline = time.monotonic() + 30.0
    while sub.applied_seq < publisher.seq and time.monotonic() < deadline:
      time.sleep(0.02)
  finally:
    stop.set()
    for t in threads:
      t.join(timeout=30.0)
    sub.stop()
    batcher.close()
  assert not failures, failures
  mon.assert_consistent_with(threadlint.static_lock_edges())
  assert sub.last_error is None
  assert sub.applied_seq == publisher.seq  # converged under load
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, "f32")
  for name, want in art.state["serve"].items():
    np.testing.assert_array_equal(
        np.asarray(sub.engine.state["serve"][name]), np.asarray(want))
  assert sub.freshness.count == publisher.seq
  assert np.isfinite(sub.freshness.p99)


def test_batcher_dispatch_fn_swap_between_flushes():
  calls = []

  def d1(numerical, cats):
    calls.append(1)
    return np.zeros((numerical.shape[0], 1))

  def d2(numerical, cats):
    calls.append(2)
    return np.ones((numerical.shape[0], 1))

  mb = MicroBatcher(d1, max_batch=4, start=False,
                    registry=MetricsRegistry())
  mb.submit(np.zeros((2, 1)), [np.zeros((2, 1), np.int32)])
  mb.flush_now()
  mb.set_dispatch_fn(d2)
  fut = mb.submit(np.zeros((2, 1)), [np.zeros((2, 1), np.int32)])
  mb.flush_now()
  assert calls == [1, 2]
  np.testing.assert_array_equal(fut.result(), np.ones((2, 1)))
  mb.close()


# ---------------------------------------------------------------------------
# pubdir hygiene: the seq scan survives whatever accumulates there
# ---------------------------------------------------------------------------


def test_published_delta_seqs_ignores_stray_entries(tmp_path):
  pub = str(tmp_path)
  # a real published delta
  os.makedirs(os.path.join(pub, "delta_000002"))
  open(os.path.join(pub, "delta_000002", "manifest.json"), "w").write("{}")
  # a torn publish (killed mid-seal): dir without a manifest
  os.makedirs(os.path.join(pub, "delta_000001"))
  # a torn tmp, an .old rotation, a heartbeats dir, operator droppings
  os.makedirs(os.path.join(pub, "delta_000003.tmp"))
  os.makedirs(os.path.join(pub, "delta_000004.old"))
  os.makedirs(os.path.join(pub, "heartbeats"))
  os.makedirs(os.path.join(pub, "not_a_delta"))
  # a stray FILE named like a delta
  open(os.path.join(pub, "delta_000005"), "w").write("x")
  assert published_delta_seqs(pub) == [2]
  # a missing dir is an empty scan, never a crash
  assert published_delta_seqs(os.path.join(pub, "nope")) == []


# ---------------------------------------------------------------------------
# publisher ATTACH: chain state through the checkpoint, superset re-publish
# ---------------------------------------------------------------------------


def _train_more(plan, rule, mesh, state, publisher, rng, b, n=1):
  """Train ``n`` more observed batches on a fresh step fn; returns the
  new state and the batches consumed (for deterministic replay)."""
  step = make_sparse_train_step(ActsModel(), plan, loss_fn,
                                optax.sgd(0.01), rule, mesh, state,
                                _mkbatch(rng, b), donate=False)
  consumed = []
  for _ in range(n):
    batch = _mkbatch(rng, b)
    consumed.append(batch)
    publisher.observe_batch(batch[1])
    state, _ = step(state, *shard_batch(batch, mesh))
  return state, consumed, step


def test_publisher_attach_rejoins_chain_with_superset(tmp_path):
  """Snapshot the chain state mid-chain, publish one more delta (the
  orphan), 'kill' the publisher, restore + attach: the tail delta is
  adopted (fingerprint continuity), the next publication re-ships a
  SUPERSET of the orphan's rows at replayed values, and the folded
  subscriber equals a full re-export — no re-root anywhere."""
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 2, "f32")
  ckpt = os.path.join(str(tmp_path), "ckpt")
  checkpoint.save(ckpt, plan, rule, state, stream=publisher)
  seq_snap, fp_snap = publisher.seq, publisher.fingerprint

  # post-snapshot: one more observed batch + delta 2 (the orphan)
  state, replay, step = _train_more(plan, rule, mesh, state, publisher,
                                    rng, b)
  assert publisher.publish_delta(state) is not None
  orphan = os.path.join(str(tmp_path), "pub", delta_dirname(2))
  with np.load(os.path.join(orphan, sorted(
      f for f in os.listdir(orphan) if f.startswith("rows_"))[0])) as z:
    pass  # the orphan has row payloads; the superset check reads below

  # "kill": a fresh publisher restores the snapshot and attaches
  tracker2 = RowGenerationTracker(plan)
  pub2 = DeltaPublisher(sub.path, plan, rule, tracker2, quantize="f32")
  state2 = checkpoint.restore(ckpt, plan, rule, state, mesh=mesh,
                              stream=pub2)
  assert not pub2.attached
  with pytest.raises(RuntimeError, match="unattached"):
    pub2.publish_delta(state2)
  assert pub2.seq == seq_snap and pub2.fingerprint == fp_snap
  assert pub2.attach() == 1  # the orphan tail delta is adopted
  assert pub2.attached and pub2.seq == 2
  assert pub2.fingerprint == checkpoint.manifest_fingerprint(orphan)

  # replay the post-snapshot stream (bit-identical training), publish:
  # delta 3 must cover every row the orphan shipped (the superset rule)
  for batch in replay:
    pub2.observe_batch(batch[1])
    state2, _ = step(state2, *shard_batch(batch, mesh))
  assert pub2.publish_delta(state2) is not None
  d3 = os.path.join(str(tmp_path), "pub", delta_dirname(3))

  def rows_of(dpath):
    out = {}
    for f in os.listdir(dpath):
      if f.startswith("rows_"):
        with np.load(os.path.join(dpath, f)) as z:
          out[f] = set(np.asarray(z["idx"]).tolist())
    return out
  orphan_rows, d3_rows = rows_of(orphan), rows_of(d3)
  for f, idx in orphan_rows.items():
    assert idx <= d3_rows.get(f, set()), f

  # the subscriber folds 1..3 and lands on the replayed state exactly
  assert sub.poll_once() == 3
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state2, "f32")
  for name, want in art.state["serve"].items():
    np.testing.assert_array_equal(
        np.asarray(sub.engine.state["serve"][name]), np.asarray(want))


def test_preroot_snapshot_resumes_fresh_publisher(tmp_path):
  """A checkpoint saved BEFORE the chain was rooted (publisher
  fingerprint None) restores a FRESH publisher: the resume does not
  demand attach() (there is no chain to re-join), publish_base roots
  one, and the loop proceeds — not a permanent crash loop."""
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  fresh = DeltaPublisher(os.path.join(str(tmp_path), "pub2"), plan,
                         rule, RowGenerationTracker(plan),
                         quantize="f32")
  ckpt = os.path.join(str(tmp_path), "ckpt_preroot")
  checkpoint.save(ckpt, plan, rule, state, stream=fresh)
  pub2 = DeltaPublisher(os.path.join(str(tmp_path), "pub2"), plan,
                        rule, RowGenerationTracker(plan),
                        quantize="f32")
  checkpoint.restore(ckpt, plan, rule, state, mesh=mesh, stream=pub2)
  assert pub2.attached and pub2.fingerprint is None
  pub2.publish_base(state)  # root explicitly; no ChainDiverged crash
  assert pub2.seq == 0 and pub2.fingerprint is not None


def test_attach_refuses_forked_or_rerooted_chain(tmp_path):
  import json
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  ckpt = os.path.join(str(tmp_path), "ckpt")
  checkpoint.save(ckpt, plan, rule, state, stream=publisher)
  state, _replay, _step = _train_more(plan, rule, mesh, state,
                                      publisher, rng, b)
  assert publisher.publish_delta(state) is not None

  # fork: the tail delta chains a different predecessor
  dpath = os.path.join(sub.path, delta_dirname(2))
  mpath = os.path.join(dpath, "manifest.json")
  with open(mpath) as f:
    manifest = json.load(f)
  good = manifest["base_fingerprint"]
  manifest["base_fingerprint"] = "f" * 64
  with open(mpath, "w") as f:
    json.dump(manifest, f)
  pub2 = DeltaPublisher(sub.path, plan, rule,
                        RowGenerationTracker(plan), quantize="f32")
  checkpoint.restore(ckpt, plan, rule, state, mesh=mesh, stream=pub2)
  with pytest.raises(ChainDivergedError) as ei:
    pub2.attach()
  assert ei.value.field == "base_fingerprint"
  assert not pub2.attached
  manifest["base_fingerprint"] = good
  with open(mpath, "w") as f:
    json.dump(manifest, f)

  # re-rooted base: another publisher replaced base/ entirely
  pub3 = DeltaPublisher(sub.path, plan, rule,
                        RowGenerationTracker(plan), quantize="f32")
  tr = RowGenerationTracker(plan)
  reroot = DeltaPublisher(sub.path, plan, rule, tr, quantize="f32")
  tr.observe(_mkbatch(rng, b)[1])
  reroot.publish_base(state)
  checkpoint.restore(ckpt, plan, rule, state, mesh=mesh, stream=pub3)
  with pytest.raises(ChainDivergedError) as ei:
    pub3.attach()
  assert ei.value.field == "base_fingerprint"


def test_attach_fault_injection_crash_and_retry(tmp_path):
  """crash_after on the stream_attach site interrupts the tail walk
  mid-validation; the retried attach (fault cleared) adopts the tail —
  attach mutates nothing until the whole tail validates."""
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  ckpt = os.path.join(str(tmp_path), "ckpt")
  checkpoint.save(ckpt, plan, rule, state, stream=publisher)
  state, _replay, _step = _train_more(plan, rule, mesh, state,
                                      publisher, rng, b)
  assert publisher.publish_delta(state) is not None
  pub2 = DeltaPublisher(sub.path, plan, rule,
                        RowGenerationTracker(plan), quantize="f32")
  checkpoint.restore(ckpt, plan, rule, state, mesh=mesh, stream=pub2)
  inj = faultinject.FaultInjector().crash_after("stream_attach", 0)
  with faultinject.injected(inj):
    with pytest.raises(faultinject.InjectedCrash):
      pub2.attach()
  assert not pub2.attached and pub2.seq == 1  # nothing adopted
  assert pub2.attach() == 1
  assert pub2.attached and pub2.seq == 2


def test_resilient_trainer_stream_auto_reattach(tmp_path):
  """The full wiring: ResilientTrainer(stream=publisher) persists the
  chain state per snapshot; a fresh trainer+publisher pair auto-resumes
  AND auto-attaches, and the continued chain folds to the re-export of
  the continued state."""
  rng = np.random.default_rng(3)
  tables = [TableConfig(s, w, combiner="sum")
            for s, w in zip(SIZES, WIDTHS)]
  plan = DistEmbeddingStrategy(tables, 2, "memory_balanced",
                               dense_row_threshold=0,
                               input_hotness=HOTNESS)
  weights = [rng.standard_normal((s, w)).astype(np.float32)
             for s, w in zip(SIZES, WIDTHS)]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.sgd(0.01)
  mesh = create_mesh(2)
  state0 = shard_params(init_sparse_state(plan, params, rule, opt), mesh)
  b = 8
  stream = [_mkbatch(rng, b) for _ in range(6)]
  step = make_sparse_train_step(ActsModel(), plan, loss_fn, opt, rule,
                                mesh, state0, stream[0], donate=False,
                                guard=True)
  root = os.path.join(str(tmp_path), "ckpts")
  pubdir = os.path.join(str(tmp_path), "pub")

  tracker = RowGenerationTracker(plan)
  publisher = DeltaPublisher(pubdir, plan, rule, tracker, quantize="f32")
  t1 = ResilientTrainer(step, state0, plan, rule, root, mesh=mesh,
                        snapshot_every=2, stream=publisher)
  publisher.publish_base(t1.state)
  t1.snapshot()
  for i in range(4):  # first lifetime: 4 of 6 batches, 2 deltas
    publisher.observe_batch(stream[i][1])
    t1.step(*shard_batch(stream[i], mesh))
    if (i + 1) % 2 == 0:
      assert publisher.publish_delta(t1.state) is not None

  # lifetime 2: fresh objects, auto-resume + auto-attach
  tracker2 = RowGenerationTracker(plan)
  pub2 = DeltaPublisher(pubdir, plan, rule, tracker2, quantize="f32")
  t2 = ResilientTrainer(step, state0, plan, rule, root, mesh=mesh,
                        snapshot_every=2, stream=pub2)
  assert t2.resumed_from is not None
  assert pub2.attached
  assert pub2.seq == publisher.seq
  assert pub2.base_fingerprint == publisher.base_fingerprint
  for i in range(t2.consumed, 6):
    pub2.observe_batch(stream[i][1])
    t2.step(*shard_batch(stream[i], mesh))
  assert pub2.publish_delta(t2.state) is not None

  sub = DeltaSubscriber.from_artifact(ActsModel(), plan, pubdir,
                                      mesh=mesh)
  assert sub.poll_once() == pub2.seq
  engB, art = _full_engine(tmp_path, plan, rule, mesh, t2.state, "f32")
  for name, want in art.state["serve"].items():
    np.testing.assert_array_equal(
        np.asarray(sub.engine.state["serve"][name]), np.asarray(want))


# ---------------------------------------------------------------------------
# compaction: fold the chain into a new base, GC under the retention floor
# ---------------------------------------------------------------------------


def _chain_of(tmp_path, n_deltas, world=2, quantize="f32"):
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, world, quantize)
  for _ in range(n_deltas - 1):
    state, _c, _s = _train_more(plan, rule, mesh, state, publisher,
                                rng, b)
    assert publisher.publish_delta(state) is not None
  return plan, rule, mesh, state, publisher, sub, rng, b


def test_compaction_folds_chain_to_new_base(tmp_path):
  plan, rule, mesh, state, publisher, sub, rng, b = _chain_of(
      tmp_path, 3)
  reg = MetricsRegistry()
  res = DeltaCompactor(sub.path, telemetry=reg).compact_once(
      through_seq=2)
  assert res["through_seq"] == 2 and res["deltas_folded"] == 2
  # no registered subscriber heartbeats yet -> folded deltas are GC'd
  assert res["gc_removed"] == [1, 2]
  assert published_delta_seqs(sub.path) == [3]
  base = os.path.join(sub.path, "base")
  comp = checkpoint.read_manifest(base)["stream"]["compacted"]
  assert comp["through_seq"] == 2
  assert comp["chain_root"] == publisher.base_fingerprint

  # cold start: anchors at the compaction point, folds ONLY the tail
  cold = DeltaSubscriber.from_artifact(ActsModel(), plan, sub.path,
                                       mesh=mesh, heartbeat=False)
  assert cold.applied_seq == 2
  assert cold.poll_once() == 1
  assert cold.applied_seq == 3
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, "f32")
  for name, want in art.state["serve"].items():
    np.testing.assert_array_equal(
        np.asarray(cold.engine.state["serve"][name]), np.asarray(want))

  # a LIVE subscriber already past the compaction point only adopts the
  # new base identity — no rebase, no reload
  sreg = MetricsRegistry()
  live = DeltaSubscriber.from_artifact(ActsModel(), plan, sub.path,
                                       mesh=mesh, telemetry=sreg,
                                       heartbeat=False)
  live.poll_once()
  before = live.base_fingerprint
  res2 = DeltaCompactor(sub.path).compact_once()  # fold the tail too
  assert res2["through_seq"] == 3
  assert live.poll_once() == 0
  assert live.base_fingerprint != before
  assert sreg.counter("stream/compactions_adopted").value == 1
  assert sreg.counter("stream/rebases").value == 0


def test_compaction_quantized_cold_start_quant_exact(tmp_path):
  plan, rule, mesh, state, publisher, sub, rng, b = _chain_of(
      tmp_path, 2, quantize="int8")
  DeltaCompactor(sub.path).compact_once(through_seq=1)
  cold = DeltaSubscriber.from_artifact(ActsModel(), plan, sub.path,
                                       mesh=mesh, heartbeat=False)
  cold.poll_once()
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, "int8")
  for name, want in art.state["serve"].items():
    np.testing.assert_array_equal(
        np.asarray(cold.engine.state["serve"][name]).view(np.uint8),
        np.asarray(want).view(np.uint8))


def test_compaction_crash_mid_fold_never_corrupts_base(tmp_path):
  plan, rule, mesh, state, publisher, sub, rng, b = _chain_of(
      tmp_path, 2)
  base = os.path.join(sub.path, "base")
  fp_before = checkpoint.manifest_fingerprint(base)
  # crash_after: die between the first and second class fold
  inj = faultinject.FaultInjector().crash_after("compact_fold", 0)
  with faultinject.injected(inj):
    with pytest.raises(faultinject.InjectedCrash):
      DeltaCompactor(sub.path).compact_once()
  assert checkpoint.manifest_fingerprint(base) == fp_before
  assert checkpoint.verify(base) == []
  assert published_delta_seqs(sub.path) == [1, 2]  # GC never ran
  # fail_first: a transient fold-time error propagates the same way
  inj = faultinject.FaultInjector().fail_first("compact_fold", 1)
  with faultinject.injected(inj):
    with pytest.raises(faultinject.TransientIOError):
      DeltaCompactor(sub.path).compact_once()
  assert checkpoint.verify(base) == []
  # the retry (fault cleared) compacts; the torn tmp is replaced
  res = DeltaCompactor(sub.path).compact_once()
  assert res["through_seq"] == 2
  cold = DeltaSubscriber.from_artifact(ActsModel(), plan, sub.path,
                                       mesh=mesh, heartbeat=False)
  assert cold.applied_seq == 2
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, "f32")
  for name, want in art.state["serve"].items():
    np.testing.assert_array_equal(
        np.asarray(cold.engine.state["serve"][name]), np.asarray(want))


def test_compaction_retention_respects_live_heartbeats(tmp_path):
  import json
  import time as _time
  plan, rule, mesh, state, publisher, sub, rng, b = _chain_of(
      tmp_path, 3)
  # a live subscriber still at seq 1: deltas > 1 must survive GC
  write_heartbeat(sub.path, "laggard", 1)
  res = DeltaCompactor(sub.path).compact_once(through_seq=3)
  assert res["gc_removed"] == [1]
  assert published_delta_seqs(sub.path) == [2, 3]
  # an EXPIRED heartbeat does not hold the floor
  hb = {"id": "dead", "applied_seq": 0, "wall": _time.time() - 10_000}
  with open(os.path.join(sub.path, "heartbeats", "dead.json"),
            "w") as f:
    json.dump(hb, f)
  os.remove(os.path.join(sub.path, "heartbeats", "laggard.json"))
  comp = DeltaCompactor(sub.path)
  removed = comp.gc_deltas(3)
  assert removed == [2, 3]


# ---------------------------------------------------------------------------
# back-pressure: heartbeats, throttle-then-coalesce, expiry
# ---------------------------------------------------------------------------


def test_backpressure_throttles_then_coalesces(tmp_path):
  reg = MetricsRegistry()
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32", registry=reg)
  publisher.max_subscriber_lag = 2
  # a registered live subscriber stuck at seq 0
  write_heartbeat(sub.path, "slow", 0)
  # delta 1 already exists (lag 1 < 2); delta 2 publishes (lag then 2)
  state, _c, step = _train_more(plan, rule, mesh, state, publisher,
                                rng, b)
  assert publisher.publish_delta(state) is not None
  assert publisher.seq == 2
  # lag 2 >= 2: the next interval DEFERS — watermark holds
  wm = publisher.watermark
  state, _c, _s = _train_more(plan, rule, mesh, state, publisher,
                              rng, b)
  assert publisher.publish_delta(state) is None
  assert publisher.watermark == wm
  assert reg.counter("stream/publishes_throttled").value == 1
  # ... unless forced (operator override)
  # the laggard catches up: the deferred interval coalesces into seq 3
  write_heartbeat(sub.path, "slow", 2)
  changed_before = publisher.tracker.changed_row_total(wm)
  assert publisher.publish_delta(state) is not None
  assert publisher.seq == 3
  assert reg.counter("stream/deltas_coalesced").value == 1
  d3 = os.path.join(sub.path, delta_dirname(3))
  n_shipped = sum(
      int(np.load(os.path.join(d3, f))["idx"].size)
      for f in os.listdir(d3) if f.startswith("rows_"))
  assert n_shipped == changed_before  # both intervals' rows, one delta
  # the real subscriber still folds the whole chain exactly
  assert sub.poll_once() == 3
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, "f32")
  for name, want in art.state["serve"].items():
    np.testing.assert_array_equal(
        np.asarray(sub.engine.state["serve"][name]), np.asarray(want))


def test_backpressure_force_bypasses_throttle(tmp_path):
  reg = MetricsRegistry()
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32", registry=reg)
  publisher.max_subscriber_lag = 1
  write_heartbeat(sub.path, "slow", 0)
  state, _c, _s = _train_more(plan, rule, mesh, state, publisher,
                              rng, b)
  assert publisher.publish_delta(state) is None  # lag 1 >= 1
  assert publisher.publish_delta(state, force=True) is not None


def test_expired_heartbeat_drops_from_quorum_once(tmp_path):
  import json
  import time as _time
  reg = MetricsRegistry()
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32", registry=reg)
  publisher.max_subscriber_lag = 1
  hb = {"id": "dead", "applied_seq": 0, "wall": _time.time() - 10_000}
  os.makedirs(os.path.join(sub.path, "heartbeats"), exist_ok=True)
  with open(os.path.join(sub.path, "heartbeats", "dead.json"),
            "w") as f:
    json.dump(hb, f)
  # the dead subscriber is dropped (counted once), not a throttle vote
  state, _c, _s = _train_more(plan, rule, mesh, state, publisher,
                              rng, b)
  assert publisher.publish_delta(state) is not None
  assert reg.counter("stream/subscribers_expired").value == 1
  state, _c, _s = _train_more(plan, rule, mesh, state, publisher,
                              rng, b)
  assert publisher.publish_delta(state) is not None
  assert reg.counter("stream/subscribers_expired").value == 1  # once


def test_two_subscribers_one_chain_heartbeats_and_rollup(tmp_path):
  """Two serving processes on one chain: independent applied_seq
  heartbeats in the pubdir, per-process freshness in private
  registries, and the fleet view rolled up through the registry
  merge."""
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 2, "f32")
  regA, regB = MetricsRegistry(), MetricsRegistry()
  subA = DeltaSubscriber.from_artifact(ActsModel(), plan, sub.path,
                                       mesh=mesh, telemetry=regA,
                                       subscriber_id="serve-a")
  subB = DeltaSubscriber.from_artifact(ActsModel(), plan, sub.path,
                                       mesh=mesh, telemetry=regB,
                                       subscriber_id="serve-b")
  assert subA.poll_once() == 1 and subB.poll_once() == 1
  # one more delta; only A polls -> independent applied positions
  state, _c, _s = _train_more(plan, rule, mesh, state, publisher,
                              rng, b)
  assert publisher.publish_delta(state) is not None
  assert subA.poll_once() == 1
  live, expired = read_heartbeats(sub.path, ttl_s=30.0)
  assert not expired
  assert live["serve-a"]["applied_seq"] == 2
  assert live["serve-b"]["applied_seq"] == 1
  # publisher-side lag reads the slowest live subscriber
  publisher.max_subscriber_lag = 10
  assert publisher.subscriber_lag() == 1

  fleet = MetricsRegistry()
  fleet.merge(regA)
  fleet.merge(regB)
  assert fleet.counter("stream/deltas_applied").value == 3
  rolled = fleet.metrics()["stream/freshness_s"]
  assert rolled.count == 3
  assert rolled.count == (regA.metrics()["stream/freshness_s"].count
                          + regB.metrics()["stream/freshness_s"].count)


def test_subscriber_poll_jitter_phases_and_interleave(tmp_path):
  """N subscribers on one pubdir must not stat it in lockstep: the
  deterministic per-subscriber phase offset spreads their polls over
  the jitter window, and two jittered subscribers' poll timestamps
  INTERLEAVE instead of colliding."""
  import time as _time

  from distributed_embeddings_tpu.streaming import poll_phase

  # the phase is a pure function of the id: deterministic, in-range,
  # distinct across ids, zero when jitter is off
  pa, pb = poll_phase("serve-a", 0.04), poll_phase("serve-b", 0.04)
  assert pa != pb and 0.0 <= pa < 0.04 and 0.0 <= pb < 0.04
  assert poll_phase("serve-a", 0.04) == pa
  assert poll_phase("serve-a", 0.0) == 0.0
  # phases scale with the window (same fraction)
  assert abs(poll_phase("serve-a", 0.4) - 10 * pa) < 1e-12

  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32", pre_steps=1, post_steps=1)
  subA = DeltaSubscriber.from_artifact(ActsModel(), plan, sub.path,
                                       subscriber_id="serve-a",
                                       poll_interval_s=0.05,
                                       poll_jitter_s=0.04)
  subB = DeltaSubscriber.from_artifact(ActsModel(), plan, sub.path,
                                       subscriber_id="serve-b",
                                       poll_interval_s=0.05,
                                       poll_jitter_s=0.04)
  assert subA.poll_phase_s == pa and subB.poll_phase_s == pb
  # fold the pending delta BEFORE timing polls: the first poll compiles
  # the promote scatter; later polls are cheap directory stats — the
  # regime the jitter exists for
  assert subA.poll_once() == 1 and subB.poll_once() == 1
  subA.start()
  subB.start()
  _time.sleep(0.6)
  subA.stop()
  subB.stop()
  assert len(subA.poll_walls) >= 3 and len(subB.poll_walls) >= 3
  # interleaved: neither subscriber's polls all precede the other's —
  # the merged timeline alternates at least twice
  merged = sorted([(t, "a") for t in subA.poll_walls]
                  + [(t, "b") for t in subB.poll_walls])
  flips = sum(1 for x, y in zip(merged, merged[1:]) if x[1] != y[1])
  assert flips >= 2, merged


# ---------------------------------------------------------------------------
# transient-read retry on the subscriber's validate/fold path
# ---------------------------------------------------------------------------


def test_subscriber_retries_transient_reads(tmp_path):
  from distributed_embeddings_tpu import telemetry as _t
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  sub.retry_policy = retry.RetryPolicy(retries=3, backoff=0.0)
  before = _t.get_registry().counter("retry/attempts").value
  inj = faultinject.FaultInjector().fail_first("stream_read", 2)
  with faultinject.injected(inj):
    assert sub.poll_once() == 1  # transient faults absorbed, applied
  assert sub.last_refusal is None
  assert _t.get_registry().counter("retry/attempts").value - before == 2


def test_heartbeat_reads_retry_then_degrade_to_expired(tmp_path):
  """A heartbeat file unreadable after the bounded retries leaves that
  member EXPIRED (``unreadable: True``, counted ``retry/attempts``) —
  the publisher's lag quorum and the compactor's retention floor
  degrade to the readable set instead of crashing on a flaky NFS
  pubdir."""
  from distributed_embeddings_tpu import telemetry as _t
  from distributed_embeddings_tpu.streaming.publish import heartbeat_path

  pub = os.path.join(str(tmp_path), "pub")
  write_heartbeat(pub, "healthy", 5)
  # a permanently unreadable record: a DIRECTORY where the json should
  # be (open() raises IsADirectoryError — an OSError — every attempt)
  os.makedirs(heartbeat_path(pub, "sick"))
  before = _t.get_registry().counter("retry/attempts").value
  live, expired = read_heartbeats(pub, ttl_s=30.0)
  assert live["healthy"]["applied_seq"] == 5
  assert "sick" not in live
  assert expired["sick"]["unreadable"] is True
  assert expired["sick"]["applied_seq"] == -1
  # each unreadable file burned the policy's full retry budget
  assert _t.get_registry().counter("retry/attempts").value - before \
      == retry.DEFAULT_POLICY.retries
  # no heartbeat dir at all stays a clean empty answer
  assert read_heartbeats(os.path.join(str(tmp_path), "nope"),
                         ttl_s=30.0) == ({}, {})


def test_subscriber_exhausted_reads_surface_without_advancing(tmp_path):
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  sub.retry_policy = retry.RetryPolicy(retries=1, backoff=0.0)
  inj = faultinject.FaultInjector().fail_first("stream_read", 10_000)
  with faultinject.injected(inj):
    with pytest.raises(OSError):
      sub.poll_once()
  assert sub.applied_seq == 0  # held position, nothing half-applied
  # the fault clears (NFS came back): the same chain applies cleanly
  assert sub.poll_once() == 1
  assert sub.applied_seq == 1


# ---------------------------------------------------------------------------
# streaming chaos: the long cross-process variant
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_stream_full():
  import sys
  sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                  "tools"))
  import chaos_stream
  res = chaos_stream.run_chaos_stream(steps=16, world=2,
                                      publish_every=2, quantize="int8",
                                      smoke=False, verbose=False)
  assert res["ok"], res
