"""Streaming (online-learning) subsystem tests (`streaming/`).

The contracts under test:

- **delta = full re-export, bit for bit**: folding published deltas into
  a running serve engine yields EXACTLY the artifact a full re-export at
  the same watermark would — f32 bit-exact, int8/fp8 quant-exact (the
  same bytes) — across raw/dedup/tiered layouts and world 1/2/4.
- **the tracker's row set is exact**: rows the batches routed advance,
  nothing else does; the delta ships exactly the advanced set.
- **chain durability**: a torn (corrupt) delta is refused and skipped
  with the failing field named; an out-of-order seq is refused; a
  base_fingerprint mismatch is refused naming the field; a publish
  killed by injected ``ckpt_write``/``ckpt_rename`` faults leaves only a
  manifest-less ``.tmp`` the subscriber never reads, and the retried
  publish converges it to the last valid delta.
- **dynvocab rides the delta**: a raw id newly admitted by training is
  servable after ONE delta cycle — no full re-export — through the
  promoted read-only snapshot.
- **live hot-set adaptation**: the publisher-shipped observed counts
  re-rank the tiered serve cache through the prefetcher's re-rank
  machinery, value-preservingly.
- **copy-on-promote never pauses traffic**: a micro-batcher keeps
  dispatching while deltas fold in; every request resolves.
"""

import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu import checkpoint
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    set_weights,
)
from distributed_embeddings_tpu.layers.embedding import TableConfig
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM
from distributed_embeddings_tpu.models.dlrm import (
    _dlrm_initializer,
    bce_loss,
)
from distributed_embeddings_tpu.models.synthetic import power_law_ids
from distributed_embeddings_tpu.dynvocab import DynVocabTranslator
from distributed_embeddings_tpu.ops.packed_table import sparse_rule
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.parallel.lookup_engine import PAD_ID
from distributed_embeddings_tpu.resilience import faultinject
from distributed_embeddings_tpu.serving import (
    MicroBatcher,
    ServeEngine,
    ServeTierConfig,
)
from distributed_embeddings_tpu.serving.export import export as serve_export
from distributed_embeddings_tpu.serving.export import load as serve_load
from distributed_embeddings_tpu.streaming import (
    DeltaPublisher,
    DeltaSubscriber,
    RowGenerationTracker,
    artifact_bytes,
)
from distributed_embeddings_tpu.telemetry import MetricsRegistry
from distributed_embeddings_tpu.tiering import (
    HostTierStore,
    TieredTrainer,
    TieringConfig,
    TieringPlan,
    init_tiered_state_from_params,
)
from distributed_embeddings_tpu.training import (
    init_sparse_state,
    make_sparse_train_step,
    shard_batch,
    shard_params,
)


class ActsModel:
  """Embedding-activations stub: every table's rows visible in preds."""

  def apply(self, variables, numerical, cats, emb_acts=None):
    del variables, numerical, cats
    return jnp.concatenate(list(emb_acts), axis=-1)


def loss_fn(preds, labels):
  return jnp.mean((jnp.sum(preds, axis=-1) - labels) ** 2)


SIZES = [131, 97, 53, 40, 67]
WIDTHS = [16, 16, 8, 8, 16]
HOTNESS = [3, 1, 3, 2, 1]


def _mkbatch(rng, b):
  ids = []
  for s, h in zip(SIZES, HOTNESS):
    x = rng.integers(0, s, (b, h)).astype(np.int32)
    x[rng.random(x.shape) < 0.25] = PAD_ID
    ids.append(x)
  return (rng.standard_normal((b, 4)).astype(np.float32), ids,
          rng.integers(0, 2, b).astype(np.float32))


def _device_run(tmp_path, world, quantize="f32", dedup=False,
                pre_steps=2, post_steps=2, registry=None):
  """Train, publish base, train more, publish a delta; returns the
  pieces every device-tier test compares."""
  rng = np.random.default_rng(world * 31 + (7 if dedup else 0))
  tables = [TableConfig(s, w, combiner="sum")
            for s, w in zip(SIZES, WIDTHS)]
  plan = DistEmbeddingStrategy(tables, world, "memory_balanced",
                               dense_row_threshold=0,
                               input_hotness=HOTNESS,
                               dedup_exchange=dedup)
  weights = [rng.standard_normal((s, w)).astype(np.float32)
             for s, w in zip(SIZES, WIDTHS)]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.sgd(0.01)
  mesh = create_mesh(world) if world > 1 else None
  state = shard_params(init_sparse_state(plan, params, rule, opt), mesh)
  b = 4 * world
  batch0 = _mkbatch(rng, b)
  step = make_sparse_train_step(ActsModel(), plan, loss_fn, opt, rule,
                                mesh, state, batch0, donate=False)

  pub = os.path.join(str(tmp_path), "pub")
  tracker = RowGenerationTracker(plan)
  publisher = DeltaPublisher(pub, plan, rule, tracker, quantize=quantize,
                             telemetry=registry)

  def train(state, n):
    for _ in range(n):
      batch = _mkbatch(rng, b)
      publisher.observe_batch(batch[1])
      state, _ = step(state, *shard_batch(batch, mesh))
    return state

  state = train(state, pre_steps)
  publisher.publish_base(state)
  sub = DeltaSubscriber.from_artifact(ActsModel(), plan, pub, mesh=mesh,
                                      telemetry=registry)
  state = train(state, post_steps)
  assert publisher.publish_delta(state) is not None
  return plan, rule, mesh, state, publisher, sub, rng, b


def _full_engine(tmp_path, plan, rule, mesh, state, quantize,
                 store=None, model=None, tier_config=None, vocab=None):
  full = os.path.join(str(tmp_path), "full")
  serve_export(full, plan, rule, state, quantize=quantize, store=store,
               vocab=vocab)
  art = serve_load(full, plan, mesh=mesh)
  eng = ServeEngine(model or ActsModel(), plan, art, mesh=mesh,
                    tier_config=tier_config)
  return eng, art


# ---------------------------------------------------------------------------
# the tracker: exact row accounting
# ---------------------------------------------------------------------------


def test_tracker_rows_exact_and_watermarked():
  plan = DistEmbeddingStrategy(
      [TableConfig(64, 8, combiner="sum"), TableConfig(40, 8,
                                                       combiner="sum")],
      1, "basic", dense_row_threshold=0, input_hotness=[2, 1])
  tracker = RowGenerationTracker(plan)
  cats = [np.array([[3, 5], [3, PAD_ID]], np.int32),
          np.array([[7], [7]], np.int32)]
  c1 = tracker.observe(cats)
  changed = tracker.changed_rows(0)
  (name,) = changed  # both tables share one w8 class
  rows = np.concatenate(changed[name])
  # exactly the routed valid ids (table 1 offsets by table 0's rows)
  off = {s[0]: s[1] for s in plan.routing_recipe(
      list(plan.class_keys)[0])[0]}
  want = sorted({3 + off[0], 5 + off[0], 7 + off[1]})
  assert sorted(rows.tolist()) == want
  # counts weigh occurrences (3 twice, 7 twice, 5 once)
  cnt = tracker.counts[name][0]
  assert cnt[3 + off[0]] == 2 and cnt[5 + off[0]] == 1 \
      and cnt[7 + off[1]] == 2
  # watermark filters: nothing advanced past c1
  assert tracker.changed_row_total(c1) == 0
  tracker.observe([np.array([[9, PAD_ID]], np.int32),
                   np.full((1, 1), PAD_ID, np.int32)])
  assert np.concatenate(
      tracker.changed_rows(c1)[name]).tolist() == [9 + off[0]]


# ---------------------------------------------------------------------------
# delta == full re-export: the parity matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("world", [1, 2, 4])
@pytest.mark.parametrize("dedup", [False, True])
def test_delta_parity_f32(tmp_path, world, dedup):
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, world, "f32", dedup)
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, "f32")
  assert sub.poll_once() == 1
  for name, want in art.state["serve"].items():
    np.testing.assert_array_equal(
        np.asarray(sub.engine.state["serve"][name]), np.asarray(want))
  probe = _mkbatch(rng, b)
  np.testing.assert_array_equal(sub.predict(probe[0], probe[1]),
                                engB.predict(probe[0], probe[1]))


@pytest.mark.parametrize("quantize", ["int8", "fp8"])
def test_delta_parity_quantized(tmp_path, quantize):
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 2, quantize)
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, quantize)
  assert sub.poll_once() == 1
  for name, want in art.state["serve"].items():
    got = np.asarray(sub.engine.state["serve"][name])
    # quant-exact: the same stored bytes, not merely close dequants
    np.testing.assert_array_equal(got.view(np.uint8),
                                  np.asarray(want).view(np.uint8))
  probe = _mkbatch(rng, b)
  np.testing.assert_array_equal(sub.predict(probe[0], probe[1]),
                                engB.predict(probe[0], probe[1]))


def test_multi_delta_chain(tmp_path):
  """Three consecutive deltas applied in order land on the same state
  as one full export; the chain fingerprints advance."""
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 2, "f32")
  assert sub.poll_once() == 1
  fp1 = sub.fingerprint
  step = make_sparse_train_step(ActsModel(), plan, loss_fn,
                                optax.sgd(0.01), rule, mesh, state,
                                _mkbatch(rng, b), donate=False)
  for _ in range(2):
    batch = _mkbatch(rng, b)
    publisher.observe_batch(batch[1])
    state, _ = step(state, *shard_batch(batch, mesh))
    assert publisher.publish_delta(state) is not None
  assert sub.poll_once() == 2
  assert sub.applied_seq == 3 and sub.fingerprint != fp1
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, "f32")
  for name, want in art.state["serve"].items():
    np.testing.assert_array_equal(
        np.asarray(sub.engine.state["serve"][name]), np.asarray(want))


def test_delta_bytes_far_below_full_export(tmp_path):
  """On a churn workload (few rows advance per interval) the delta
  payload is a small fraction of the full artifact."""
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 2, "f32", pre_steps=2, post_steps=1)
  base_bytes = artifact_bytes(os.path.join(sub.path, "base"))
  assert publisher.last_publish_bytes < base_bytes / 2, \
      (publisher.last_publish_bytes, base_bytes)


# ---------------------------------------------------------------------------
# tiered: images, prediction parity, hot-set adaptation
# ---------------------------------------------------------------------------

T_VOCAB = [2000, 300, 40]
T_WIDTH = 16


def _tiered_run(tmp_path, world, quantize, post_steps=2):
  tables = [TableConfig(input_dim=v, output_dim=T_WIDTH,
                        initializer=_dlrm_initializer(v)) for v in T_VOCAB]
  plan = DistEmbeddingStrategy(tables, world, "memory_balanced",
                               dense_row_threshold=0,
                               host_row_threshold=1000)
  model = DLRM(vocab_sizes=T_VOCAB, embedding_dim=T_WIDTH,
               bottom_mlp=(32, T_WIDTH), top_mlp=(32, 1),
               world_size=world, strategy="memory_balanced",
               dense_row_threshold=0)
  mesh = create_mesh(world) if world > 1 else None
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adam(1e-3)
  rng = np.random.default_rng(world)

  def batch(seed, n=32):
    r = np.random.default_rng(seed)
    return (r.standard_normal((n, 13)).astype(np.float32),
            [power_law_ids(r, n, 1, v, 1.05).astype(np.int32)[:, 0]
             for v in T_VOCAB],
            r.integers(0, 2, n).astype(np.float32))

  b0 = batch(100)
  params_b = model.init(jax.random.PRNGKey(0), b0[0], b0[1])["params"]
  # the model's own plan is untiered: remap its table weights onto the
  # tiered plan's class layout (generation assignment differs)
  from distributed_embeddings_tpu.layers.dist_model_parallel import (
      get_weights)
  plan_b = DistEmbeddingStrategy(tables, world, "memory_balanced",
                                 dense_row_threshold=0)
  tables_t = set_weights(plan, get_weights(plan_b,
                                           params_b["embeddings"]))
  params = {k: v for k, v in params_b.items() if k != "embeddings"}
  params["embeddings"] = {k: jnp.asarray(v) for k, v in tables_t.items()}
  tplan = TieringPlan(plan, rule, TieringConfig(cache_fraction=0.3,
                                                staging_grps=64))
  store = HostTierStore(tplan)
  state = shard_params(init_tiered_state_from_params(
      tplan, store, rule, params, opt, mesh=mesh), mesh)
  trainer = TieredTrainer(model, tplan, store, bce_loss, opt, rule, mesh,
                          state, b0, donate=False)
  pub = os.path.join(str(tmp_path), "pub")
  tracker = RowGenerationTracker(plan)
  publisher = DeltaPublisher(pub, plan, rule, tracker, quantize=quantize,
                             store=store)
  for i in range(2):
    bt = batch(100 + i)
    publisher.observe_batch(bt[1])
    trainer.step(*bt)
  publisher.publish_base(trainer.state)
  cfg = ServeTierConfig(cache_fraction=0.3, staging_grps=64)
  sub = DeltaSubscriber.from_artifact(model, plan, pub, mesh=mesh,
                                      tier_config=cfg, with_metrics=True)
  for i in range(post_steps):
    bt = batch(200 + i)
    publisher.observe_batch(bt[1])
    trainer.step(*bt)
  assert publisher.publish_delta(trainer.state) is not None
  return (plan, model, mesh, rule, trainer, store, publisher, sub, cfg,
          batch)


@pytest.mark.parametrize("world,quantize",
                         [(1, "f32"), (2, "f32"), (4, "f32"), (4, "int8")])
def test_delta_parity_tiered(tmp_path, world, quantize):
  (plan, model, mesh, rule, trainer, store, publisher, sub, cfg,
   batch) = _tiered_run(tmp_path, world, quantize)
  assert sub.poll_once() == 1
  full = os.path.join(str(tmp_path), "full")
  serve_export(full, plan, rule, trainer.state, quantize=quantize,
               store=store)
  art = serve_load(full, plan, mesh=mesh)
  # cold images: the delta fold reproduced the full export bit for bit
  for name, images in art.host_images.items():
    for r, img in enumerate(images):
      np.testing.assert_array_equal(
          sub.engine.store.images[name][r].view(np.uint8),
          np.asarray(img).view(np.uint8))
  engB = ServeEngine(model, plan, art, mesh=mesh, tier_config=cfg,
                     with_metrics=True)
  probe = batch(999)
  pa, ma = sub.predict(probe[0], probe[1])
  pb, _mb = engB.predict(probe[0], probe[1])
  np.testing.assert_array_equal(pa, pb)
  assert all(int(v[2]) == 0 for v in ma["tier"].values())  # no misses


def test_tiered_hot_set_adapts_to_shipped_counts(tmp_path):
  """The publisher's counts re-rank the serve cache: after the fold,
  every rank's resident set is a top-count set under the shipped
  signal (the prefetcher's own re-rank machinery, now exercised on the
  serve path)."""
  (plan, model, mesh, rule, trainer, store, publisher, sub, cfg,
   batch) = _tiered_run(tmp_path, 2, "f32")
  assert sub.poll_once() == 1
  eng = sub.engine
  shipped_total = 0
  for name in eng.store.images:
    c = eng.tplan.by_name(name)
    for rank in range(plan.world_size):
      counts = eng.store.counts[name][rank]
      shipped_total += int(counts.sum())
      resident = set(eng.store.resident_grps[name][rank].tolist())
      assert len(resident) == c.spec.cache_grps
      # no non-resident row outranks the weakest resident row
      floor = min(int(counts[g]) for g in resident)
      outside = np.delete(counts, sorted(resident))
      assert outside.size == 0 or int(outside.max()) <= floor
  # the shipped signal landed somewhere (a power-law stream may leave a
  # cold rank's vocab window untouched — that rank's zeros are correct)
  assert shipped_total > 0


# ---------------------------------------------------------------------------
# dynvocab: a newly admitted raw id is servable after one delta cycle
# ---------------------------------------------------------------------------


def test_dynvocab_new_id_servable_after_one_delta(tmp_path):
  world = 2
  sizes, widths, hot = [256, 40], [16, 8], [2, 1]

  def mk(**kw):
    return DistEmbeddingStrategy(
        [TableConfig(s, w, combiner="sum") for s, w in zip(sizes, widths)],
        world, "memory_balanced", dense_row_threshold=0,
        input_hotness=hot, **kw)

  plan = mk(oov="allocate", admit_threshold=1)
  serve_plan = mk()  # same tables -> same fingerprint; serving clips
  rng = np.random.default_rng(0)
  weights = [rng.standard_normal((s, w)).astype(np.float32) * 0.1
             for s, w in zip(sizes, widths)]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.sgd(0.01)
  mesh = create_mesh(world)
  state = shard_params(init_sparse_state(plan, params, rule, opt), mesh)
  b = 8

  def mkbatch(extra_id=None):
    ids = [rng.integers(0, 10**9, (b, h)).astype(np.int64) for h in hot]
    if extra_id is not None:
      ids[0][0, 0] = extra_id
    return (rng.standard_normal((b, 4)).astype(np.float32), ids,
            rng.integers(0, 2, b).astype(np.float32))

  translator = DynVocabTranslator(plan, rule)
  b0 = mkbatch()
  cats0, _, _ = translator.translate_batch(b0[1])
  step = make_sparse_train_step(ActsModel(), plan, loss_fn, opt, rule,
                                mesh, state, (b0[0], cats0, b0[2]),
                                donate=False)
  pub = os.path.join(str(tmp_path), "pub")
  tracker = RowGenerationTracker(plan)
  publisher = DeltaPublisher(pub, plan, rule, tracker, quantize="f32",
                             vocab=translator)

  def train(state, raw):
    cats_t, _, _ = translator.translate_batch(raw[1])
    publisher.observe_batch(cats_t)  # the ids the STEP consumes
    state, _ = step(state, *shard_batch((raw[0], cats_t, raw[2]), mesh))
    return state

  state = train(state, mkbatch())
  publisher.publish_base(state)
  sub = DeltaSubscriber.from_artifact(ActsModel(), serve_plan, pub,
                                      mesh=mesh)
  assert sub.translator is not None  # snapshot rode the base artifact

  new_id = 987_654_321
  probe = mkbatch(new_id)
  assert sub.translator.translate(
      [np.asarray(c) for c in probe[1]])[0][0, 0] == PAD_ID
  p_before = sub.predict(probe[0], probe[1])

  state = train(state, probe)  # admits new_id, trains its row
  assert publisher.publish_delta(state) is not None
  assert sub.poll_once() == 1  # ONE delta cycle, no full re-export

  row = sub.translator.translate(
      [np.asarray(c) for c in probe[1]])[0][0, 0]
  assert row >= 0  # servable: the promoted snapshot maps it
  p_after = sub.predict(probe[0], probe[1])
  assert not np.array_equal(p_before[0], p_after[0])

  # and the delta-cycled engine agrees with a full re-export + readonly
  # translation of the same state
  engB, art = _full_engine(tmp_path, serve_plan, rule, mesh, state,
                           "f32", vocab=translator)
  cats_ro = art.vocab.translate([np.asarray(c) for c in probe[1]])
  np.testing.assert_array_equal(p_after, engB.predict(probe[0], cats_ro))


# ---------------------------------------------------------------------------
# chain durability: torn, out-of-order, forked, faulted
# ---------------------------------------------------------------------------


def test_torn_delta_refused_and_skipped(tmp_path):
  reg = MetricsRegistry()
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 2, "f32", registry=reg)
  dpath = os.path.join(sub.path, "delta_000001")
  victim = sorted(f for f in os.listdir(dpath)
                  if f.startswith("rows_"))[0]
  faultinject.bitflip_file(os.path.join(dpath, victim))
  probe = _mkbatch(rng, b)
  before = sub.predict(probe[0], probe[1])
  assert sub.poll_once() == 0  # refused, not applied, not crashed
  assert sub.applied_seq == 0
  assert sub.last_refusal["field"] == "checksums"
  assert victim in sub.last_refusal["reason"]
  assert reg.counter("stream/deltas_refused").value == 1
  # still serving the last valid artifact
  np.testing.assert_array_equal(sub.predict(probe[0], probe[1]), before)


def test_manifestless_tmp_dir_ignored(tmp_path):
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  assert sub.poll_once() == 1
  # a crashed publish leaves a manifest-less .tmp: never even considered
  os.makedirs(os.path.join(sub.path, "delta_000002.tmp"))
  assert sub.poll_once() == 0
  assert sub.last_refusal is None


def test_out_of_order_seq_refused(tmp_path):
  import shutil
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  step = make_sparse_train_step(ActsModel(), plan, loss_fn,
                                optax.sgd(0.01), rule, mesh, state,
                                _mkbatch(rng, b), donate=False)
  batch = _mkbatch(rng, b)
  publisher.observe_batch(batch[1])
  state, _ = step(state, *shard_batch(batch, mesh))
  publisher.publish_delta(state)
  shutil.rmtree(os.path.join(sub.path, "delta_000001"))
  assert sub.poll_once() == 0
  assert sub.last_refusal["field"] == "seq"
  assert sub.applied_seq == 0


def test_base_fingerprint_mismatch_refused_naming_field(tmp_path):
  import json
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  mpath = os.path.join(sub.path, "delta_000001", "manifest.json")
  with open(mpath) as f:
    manifest = json.load(f)
  manifest["base_fingerprint"] = "f" * 64  # a fork/replay
  with open(mpath, "w") as f:
    json.dump(manifest, f)
  assert sub.poll_once() == 0
  assert sub.last_refusal["field"] == "base_fingerprint"
  assert "base_fingerprint" in sub.last_refusal["reason"]


def test_out_of_bounds_delta_rows_refused(tmp_path):
  """A delta whose row indices fall outside the class geometry is
  refused with the field named — a silent device scatter-drop would
  break the delta==re-export invariant, and a raw host IndexError
  would loop the poll thread instead of recording a refusal. The file
  is re-sealed (manifest crc updated), so only the bounds check can
  catch it."""
  import json
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  dpath = os.path.join(sub.path, "delta_000001")
  victim = sorted(f for f in os.listdir(dpath)
                  if f.startswith("rows_"))[0]
  fpath = os.path.join(dpath, victim)
  with np.load(fpath) as z:
    idx, data = np.asarray(z["idx"]), np.asarray(z["data"])
  idx[-1] = 10**9
  np.savez(fpath, idx=idx, data=data)
  mpath = os.path.join(dpath, "manifest.json")
  with open(mpath) as f:
    manifest = json.load(f)
  manifest["checksums"][victim] = checkpoint._crc32_file(fpath)
  with open(mpath, "w") as f:
    json.dump(manifest, f)
  assert sub.poll_once() == 0
  assert sub.applied_seq == 0
  assert sub.last_refusal["field"] == "rows"
  assert "1000000000" in sub.last_refusal["reason"]


@pytest.mark.parametrize("site", ["ckpt_write", "ckpt_rename"])
def test_faulted_publish_retries_and_converges(tmp_path, site):
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 2, "f32")
  assert sub.poll_once() == 1
  step = make_sparse_train_step(ActsModel(), plan, loss_fn,
                                optax.sgd(0.01), rule, mesh, state,
                                _mkbatch(rng, b), donate=False)
  batch = _mkbatch(rng, b)
  publisher.observe_batch(batch[1])
  state, _ = step(state, *shard_batch(batch, mesh))
  seq_before = publisher.seq
  # 0-indexed: ckpt_rename fires once per publish, ckpt_write per file —
  # crash the first event either way
  inj = faultinject.FaultInjector().crash_after(site, 0)
  with faultinject.injected(inj):
    with pytest.raises(faultinject.InjectedCrash):
      publisher.publish_delta(state)
  # the chain did not advance; nothing published the subscriber can see
  assert publisher.seq == seq_before
  assert sub.poll_once() == 0
  # retry (fault cleared) publishes the SAME seq; subscriber converges
  assert publisher.publish_delta(state) is not None
  assert sub.poll_once() == 1
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, "f32")
  for name, want in art.state["serve"].items():
    np.testing.assert_array_equal(
        np.asarray(sub.engine.state["serve"][name]), np.asarray(want))


def test_publisher_rebase_resets_chain(tmp_path):
  """A restarted publisher (no tracker history) re-roots with a new
  base; the subscriber detects the fingerprint change and rebases."""
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 1, "f32")
  assert sub.poll_once() == 1
  old_base_fp = sub.base_fingerprint
  # restart: fresh tracker/publisher, one more step, publish_base anew
  tracker2 = RowGenerationTracker(plan)
  pub2 = DeltaPublisher(sub.path, plan, rule, tracker2, quantize="f32")
  step = make_sparse_train_step(ActsModel(), plan, loss_fn,
                                optax.sgd(0.01), rule, mesh, state,
                                _mkbatch(rng, b), donate=False)
  batch = _mkbatch(rng, b)
  tracker2.observe(batch[1])
  state, _ = step(state, *shard_batch(batch, mesh))
  pub2.publish_base(state)
  assert sub.poll_once() >= 1  # the rebase
  assert sub.base_fingerprint != old_base_fp
  assert sub.applied_seq == 0
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, "f32")
  probe = _mkbatch(rng, b)
  np.testing.assert_array_equal(sub.predict(probe[0], probe[1]),
                                engB.predict(probe[0], probe[1]))


# ---------------------------------------------------------------------------
# copy-on-promote under live traffic
# ---------------------------------------------------------------------------


def test_promote_under_concurrent_batcher_traffic(tmp_path):
  """Deltas fold in while a micro-batcher keeps dispatching: every
  request resolves, no dispatch ever mixes old and new state (the lock
  pairs translate+dispatch with a consistent snapshot), and the final
  state equals the full re-export."""
  reg = MetricsRegistry()  # isolated: the freshness count is asserted
  plan, rule, mesh, state, publisher, sub, rng, b = _device_run(
      tmp_path, 2, "f32", registry=reg)
  step = make_sparse_train_step(ActsModel(), plan, loss_fn,
                                optax.sgd(0.01), rule, mesh, state,
                                _mkbatch(rng, b), donate=False)
  batcher = MicroBatcher(sub.dispatch, max_batch=b, max_delay_s=0.001,
                         registry=MetricsRegistry())
  stop = threading.Event()
  failures = []

  def client():
    r = np.random.default_rng(threading.get_ident() % 2**31)
    while not stop.is_set():
      n = int(r.integers(1, b + 1))
      batch = _mkbatch(np.random.default_rng(int(r.integers(2**31))), n)
      try:
        fut = batcher.submit(batch[0], batch[1])
        fut.result(timeout=30.0)
      except Exception as e:  # noqa: BLE001 — collected for the assert
        from distributed_embeddings_tpu.serving import Rejected
        if not isinstance(e, Rejected):
          failures.append(e)

  threads = [threading.Thread(target=client) for _ in range(3)]
  for t in threads:
    t.start()
  sub.start()
  try:
    for _ in range(3):
      batch = _mkbatch(rng, b)
      publisher.observe_batch(batch[1])
      state, _ = step(state, *shard_batch(batch, mesh))
      publisher.publish_delta(state)
  finally:
    stop.set()
    for t in threads:
      t.join(timeout=30.0)
    sub.stop()
    batcher.close()
  assert not failures, failures
  assert sub.last_error is None
  assert sub.applied_seq == publisher.seq  # converged under load
  engB, art = _full_engine(tmp_path, plan, rule, mesh, state, "f32")
  for name, want in art.state["serve"].items():
    np.testing.assert_array_equal(
        np.asarray(sub.engine.state["serve"][name]), np.asarray(want))
  assert sub.freshness.count == publisher.seq
  assert np.isfinite(sub.freshness.p99)


def test_batcher_dispatch_fn_swap_between_flushes():
  calls = []

  def d1(numerical, cats):
    calls.append(1)
    return np.zeros((numerical.shape[0], 1))

  def d2(numerical, cats):
    calls.append(2)
    return np.ones((numerical.shape[0], 1))

  mb = MicroBatcher(d1, max_batch=4, start=False,
                    registry=MetricsRegistry())
  mb.submit(np.zeros((2, 1)), [np.zeros((2, 1), np.int32)])
  mb.flush_now()
  mb.set_dispatch_fn(d2)
  fut = mb.submit(np.zeros((2, 1)), [np.zeros((2, 1), np.int32)])
  mb.flush_now()
  assert calls == [1, 2]
  np.testing.assert_array_equal(fut.result(), np.ones((2, 1)))
  mb.close()
