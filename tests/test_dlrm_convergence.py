"""DLRM-shaped convergence rehearsal with an AUC bar (round 5, VERDICT
item 8 + the bf16-activation guard of item 1).

The reference's headline is Criteo AUC 0.80248/0.80262 (TF32/AMP,
`examples/dlrm/README.md:7-8`) — no real Criteo data exists here, so
this is the strongest available AUC-parity evidence: the REAL DLRM model
(26 Criteo-shaped tables, width 128, bottom/top MLPs, dot interaction)
at scaled vocab trains on a seeded learnable task, and the three
execution paths

1. dense-autodiff reference path (make_train_step over engine.forward),
2. fused sparse f32 (the bench path),
3. fused sparse AMP (compute_dtype=bfloat16 — bf16 activations through
   the model/interaction, the configuration BENCH_AMP measures),

must all learn, end at matching tail losses, and reach matching
rank-AUC. Identical initial weights and identical data streams.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_embeddings_tpu.layers import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM, bce_loss
from distributed_embeddings_tpu.ops.packed_table import sgd_rule
from distributed_embeddings_tpu.parallel.lookup_engine import DistributedLookup
from distributed_embeddings_tpu.training import (
    init_sparse_state_direct,
    make_sparse_train_step,
    make_train_step,
    unpack_sparse_state,
)

CRITEO_1TB_VOCAB = [
    39884406, 39043, 17289, 7420, 20263, 3, 7120, 1543, 63, 38532951,
    2953546, 403346, 10, 2208, 11938, 155, 4, 976, 14, 39979771,
    25641295, 39664984, 585935, 12972, 108, 36
]
import os

VOCAB = [max(4, min(v // 2048, 4000)) for v in CRITEO_1TB_VOCAB]
WIDTH = 128
# env-overridable: the CI run uses 400 steps; the recorded long-horizon
# rehearsal (docs/BENCHMARKS.md) runs DLRM_REHEARSAL_STEPS=2000
BATCH = int(os.environ.get("DLRM_REHEARSAL_BATCH", 1024))
STEPS = int(os.environ.get("DLRM_REHEARSAL_STEPS", 400))
LR = 4.0


def _data_stream(seed):
  rng = np.random.default_rng(seed)
  scores = [rng.standard_normal(v).astype(np.float32) * 1.2 for v in VOCAB]

  def batch(step, n=BATCH):
    r = np.random.default_rng(seed * 100003 + step)
    cats = [r.integers(0, v, n).astype(np.int32) for v in VOCAB]
    logit = sum(s[c] for s, c in zip(scores, cats)) / np.sqrt(len(VOCAB))
    labels = (r.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float32)
    numerical = r.standard_normal((n, 13)).astype(np.float32) * 0.1
    return (jnp.asarray(numerical), [jnp.asarray(c) for c in cats],
            jnp.asarray(labels))

  return batch


def _rank_auc(scores, labels):
  order = np.argsort(scores)
  ranks = np.empty_like(order, dtype=np.float64)
  ranks[order] = np.arange(1, len(scores) + 1)
  pos = labels > 0.5
  n_pos, n_neg = pos.sum(), (~pos).sum()
  if n_pos == 0 or n_neg == 0:
    return 0.5
  return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


@pytest.mark.slow
def test_dlrm_paths_converge_with_matching_auc():
  thr = 2  # bench's 4096 scaled by the same 1/2048 vocab factor
  stream = _data_stream(11)
  numerical, cats, labels = stream(0)
  rule = sgd_rule(LR)
  opt = optax.sgd(LR)

  def make_model(dtype):
    return DLRM(vocab_sizes=VOCAB, embedding_dim=WIDTH, world_size=1,
                bottom_mlp=(64, 128), top_mlp=(256, 128, 1),
                dense_row_threshold=thr, batch_hint=BATCH,
                compute_dtype=dtype)

  plan = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=WIDTH, combiner=None) for v in VOCAB],
      1, "basic", dense_row_threshold=thr, batch_hint=BATCH)

  model_f32 = make_model(jnp.float32)
  dummy = [jnp.zeros((2, WIDTH), jnp.float32) for _ in VOCAB]
  dense_params = model_f32.init(
      jax.random.PRNGKey(0), numerical[:2], [c[:2] for c in cats],
      emb_acts=dummy)["params"]

  n_eval = 4 * BATCH
  ev_num, ev_cats, ev_labels = stream(10_000, n=n_eval)

  def run_sparse(dtype):
    model = make_model(dtype)
    state = init_sparse_state_direct(plan, rule, dense_params, opt,
                                     jax.random.PRNGKey(1))
    batch0 = (numerical, cats, labels)
    step = make_sparse_train_step(model, plan, bce_loss, opt, rule, None,
                                  state, batch0, donate=False)
    losses = []
    for i in range(STEPS):
      n_, c_, l_ = stream(i)
      state, loss = step(state, n_, c_, l_)
      losses.append(float(loss))
    from distributed_embeddings_tpu.training import make_sparse_eval_step
    ev = make_sparse_eval_step(model, plan, rule, None, state,
                               (ev_num, ev_cats, ev_labels))
    logits = np.asarray(jax.device_get(ev(state, ev_num, ev_cats)))
    return losses, _rank_auc(logits, np.asarray(ev_labels))

  def run_dense():
    engine = DistributedLookup(plan)
    state0 = init_sparse_state_direct(plan, rule, dense_params, opt,
                                      jax.random.PRNGKey(1))
    emb0, _ = unpack_sparse_state(plan, rule, state0)
    params = {"mlp": dense_params, "embeddings": emb0["embeddings"]}

    def loss_fn(p, n_, c_, l_):
      acts = engine.forward(p["embeddings"], c_)
      logits = model_f32.apply({"params": p["mlp"]}, n_, c_,
                               emb_acts=acts)
      return bce_loss(logits, l_)

    opt_state = opt.init(params)
    step = make_train_step(loss_fn, opt, None, params, opt_state,
                           (numerical, cats, labels), donate=False)
    losses = []
    for i in range(STEPS):
      n_, c_, l_ = stream(i)
      params, opt_state, loss = step(params, opt_state, n_, c_, l_)
      losses.append(float(loss))
    acts = engine.forward(params["embeddings"], ev_cats)
    logits = np.asarray(model_f32.apply({"params": params["mlp"]}, ev_num,
                                        ev_cats, emb_acts=acts))
    return losses, _rank_auc(logits, np.asarray(ev_labels))

  losses_dense, auc_dense = run_dense()
  losses_f32, auc_f32 = run_sparse(jnp.float32)
  losses_amp, auc_amp = run_sparse(jnp.bfloat16)

  def tail(xs):
    return float(np.mean(xs[-25:]))

  for name, ls in (("dense", losses_dense), ("sparse_f32", losses_f32),
                   ("sparse_amp", losses_amp)):
    assert tail(ls) < np.mean(ls[:5]) - 0.03, \
        f"{name} did not learn: {np.mean(ls[:5]):.4f} -> {tail(ls):.4f}"

  t = [tail(losses_dense), tail(losses_f32), tail(losses_amp)]
  assert max(t) - min(t) < 0.03, f"tail losses diverge: {t}"

  aucs = [auc_dense, auc_f32, auc_amp]
  assert min(aucs) > 0.65, f"AUCs too weak: {aucs}"
  assert max(aucs) - min(aucs) < 0.03, f"AUCs diverge: {aucs}"
