"""Multi-controller pod protocol on REAL spawned processes (ISSUE 17).

Everything here runs across two actual ``jax.distributed`` processes
(``multiproc.spawn_world2``) — the collectives are real, the shared
tmpdir is the pod filesystem, nothing is monkeypatched:

- the **membership-change barrier** agrees on one (step, world) across
  processes;
- **multi-controller elastic resize** round-trips 8 -> 4 -> 8 through
  the shared spill directory bit-exactly, with every process writing
  only its addressable targets;
- the **checkpoint save/restore DONE-marker/barrier protocol** and the
  piggybacked **clock-offset exchange** (``pod_clock.json``) publish
  from real per-process writes;
- the **restore-choice broadcast** picks the newest VALID checkpoint on
  every process when one process's write of the newest is torn;
- the **owner-local tiered store/prefetcher** (slow variant) stages and
  writes back over genuinely non-addressable global arrays.

The fast variant packs the first four into ONE spawn (startup is the
expensive part); both run in tier-1 — the long kill/regrow cycles live
in ``tools/chaos_multiproc.py`` (``make chaos-multiproc``).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from multiproc import spawn_world2  # noqa: E402

_COMMON = r"""
import numpy as np
import jax.numpy as jnp
from jax.experimental import multihost_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_embeddings_tpu import checkpoint
from distributed_embeddings_tpu.layers.embedding import TableConfig
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.ops.packed_table import adagrad_rule
from distributed_embeddings_tpu.parallel.lookup_engine import (
    DistributedLookup, class_param_name)
from distributed_embeddings_tpu.parallel.mesh import balanced_devices
from distributed_embeddings_tpu.resilience import durable, elastic

WORLD = 8
tables = [TableConfig(input_dim=48 + 8 * t, output_dim=8, combiner="sum")
          for t in range(WORLD)]
plan8 = DistEmbeddingStrategy(tables, WORLD, "basic")
rule = adagrad_rule(0.01)
layouts = DistributedLookup(plan8).fused_layouts(rule)
mesh8 = Mesh(np.array(jax.devices()), ("mp",))
rep8 = NamedSharding(mesh8, P())


def mk_state8(step, seed=999):
  fused = {}
  for key in plan8.class_keys:
    name = class_param_name(*key)
    lay = layouts[name]

    def cb(index, lay=lay):
      r = (index[0].start or 0) // lay.phys_rows
      rng = np.random.default_rng(seed + r)
      return rng.standard_normal(
          (lay.phys_rows, lay.phys_width)).astype(np.float32)

    fused[name] = jax.make_array_from_callback(
        (WORLD * lay.phys_rows, lay.phys_width),
        NamedSharding(mesh8, P("mp", None)), cb)
    assert not fused[name].is_fully_addressable
  return {"fused": fused,
          "dense": {"w": jax.device_put(jnp.arange(6, dtype=jnp.float32),
                                        rep8)},
          "dense_opt": {}, "emb_dense": {}, "emb_dense_opt": {},
          "step": jax.device_put(jnp.asarray(step, jnp.int32), rep8)}


def shards_of(state):
  out = {}
  for name, arr in state["fused"].items():
    for shard in arr.addressable_shards:
      if shard.replica_id:
        continue
      out[(name, shard.index[0].start or 0)] = np.asarray(shard.data).copy()
  return out
"""

_FAST_BODY = _COMMON + r"""
pod = os.path.join(tmpdir, "pod")
spill = os.path.join(tmpdir, "spill")

# ---- membership-change barrier: one agreed (step, world) ------------------
agreed = elastic.membership_barrier(pod, 1, f"p{proc_id}", 2,
                                    step=7, world=8)
assert agreed == (7, 8), agreed

# ---- spill resize: 8 -> 4 -> 8 -> 4, bit-exact ----------------------------
state8 = mk_state8(7)
mesh4 = Mesh(np.array(balanced_devices(4)), ("mp",))
plan4, state4 = elastic.elastic_resize(state8, plan8, 4, rule,
                                       new_mesh=mesh4, spill_dir=spill)
assert int(np.asarray(jax.device_get(state4["step"]))) == 7
want4 = shards_of(state4)
plan8b, state8b = elastic.elastic_resize(state4, plan4, 8, rule,
                                         new_mesh=mesh8, spill_dir=spill)
_, state4b = elastic.elastic_resize(state8b, plan8b, 4, rule,
                                    new_mesh=mesh4, spill_dir=spill)
got4 = shards_of(state4b)
assert set(got4) == set(want4)
for k in want4:
    np.testing.assert_array_equal(got4[k], want4[k])
# the spill sub-directories were cleaned up after the completion fences
assert os.listdir(spill) == [], os.listdir(spill)

# ---- barrier-protocol checkpoint + piggybacked clock exchange -------------
ck = os.path.join(tmpdir, "ck_pod")
checkpoint.save(ck, plan4, rule, state4)
clocks = checkpoint.read_pod_clock(ck)
assert set(clocks) == {0, 1}, clocks
assert clocks[0]["offset_ns"] == 0 and clocks[0]["uncertainty_ns"] == 0
assert clocks[1]["rtt_ns"] >= 0 and clocks[1]["rounds"] == 8
if proc_id == 0:
    assert checkpoint.verify(ck) == []
restored = checkpoint.restore(ck, plan4, rule, state4, mesh=mesh4)
got_r = shards_of(restored)
for k in want4:
    np.testing.assert_array_equal(got_r[k], want4[k])

# ---- restore-choice broadcast: newest torn -> both pick previous ----------
root = os.path.join(tmpdir, "rot")
s10 = dict(state4)
s10["step"] = jax.device_put(jnp.asarray(10, jnp.int32),
                             NamedSharding(mesh4, P()))
durable.save_rotating(root, plan4, rule, s10)
s11 = dict(s10)
s11["step"] = jax.device_put(jnp.asarray(11, jnp.int32),
                             NamedSharding(mesh4, P()))
durable.save_rotating(root, plan4, rule, s11)
multihost_utils.sync_global_devices("test_torn_pre")
if proc_id == 0:
    name0 = sorted(s11["fused"])[0]
    torn = os.path.join(durable.step_dir(root, 11), f"fused_{name0}_r0.npy")
    sz = os.path.getsize(torn)
    with open(torn, "r+b") as f:
        f.truncate(sz // 2)
multihost_utils.sync_global_devices("test_torn_post")
got = durable.restore_latest(root, plan4, rule, state4, mesh=mesh4)
assert got is not None and got[1] == 10, got and got[1]
got_rot = shards_of(got[0])
for k in want4:
    np.testing.assert_array_equal(got_rot[k], want4[k])

print("PROC", proc_id, "OK")
"""

_TIERED_BODY = _COMMON + r"""
from distributed_embeddings_tpu.tiering import (
    HostTierStore, TieredPrefetcher, TieringConfig, TieringPlan)

# a plan whose big table goes to the host tier
T_VOCAB = (4096, 512, 64)
ttables = [TableConfig(input_dim=v, output_dim=8, combiner="sum")
           for v in T_VOCAB]
tier_plan = DistEmbeddingStrategy(ttables, WORLD, "memory_balanced",
                                  dense_row_threshold=0,
                                  host_row_threshold=1000)
tplan = TieringPlan(tier_plan, rule,
                    TieringConfig(cache_fraction=0.25, staging_grps=16))
assert tplan.tier_specs, "fixture must have host-tier classes"
owned = tuple(range(proc_id * 4, proc_id * 4 + 4))
store = HostTierStore(tplan, owned_ranks=owned)
store.init_uniform(5)  # deterministic per (seed, class, rank)
assert not store.owns_all

# owner-local fused assembly: every process contributes only its shards
fused = store.build_fused(mesh8, "mp")
for name, arr in fused.items():
    assert not arr.is_fully_addressable

# classify against replicated bookkeeping, stage with owner-local
# gathers, write back the staged rows (identity scatter) owner-locally
pf = TieredPrefetcher(tplan, store, mesh=mesh8, axis_name="mp")
rng = np.random.default_rng(42)  # the SAME batch on both processes
cats = [rng.integers(0, v, (16, 2)).astype(np.int32) for v in T_VOCAB]
staged = pf.stage(pf.classify(cats))
before = {name: [store.images[name][r].copy() for r in owned]
          for name in store.images}
pf.write_back(staged, staged.device["rows"])
for name in store.images:
    for i, r in enumerate(owned):
        np.testing.assert_array_equal(store.images[name][r],
                                      before[name][i])

# re-rank across the sharded store: flush + wholesale top-K rebuild on
# EVERY rank from the replicated counts, then a fresh global fused
for c in tplan.classes.values():
    for r in range(WORLD):
        store.counts[c.name][r][: c.spec.cache_grps] += 10
fused = pf.rerank(fused, decay=True)
for arr in fused.values():
    assert not arr.is_fully_addressable

# checkpoint the sharded store: per-process tier blocks + merged
# restore (images owner-only, resident/counts for ALL ranks). The
# device-tier classes need fused buffers too — rank-seeded like the
# sparse fixture's.
tlayouts = DistributedLookup(tier_plan).fused_layouts(rule)
tiered_names = frozenset(tplan.tier_specs)
for key in tier_plan.class_keys:
    name = class_param_name(*key)
    if name in tiered_names or tier_plan.classes[key].kind != "sparse":
        continue
    lay = tlayouts[name]

    def cb(index, lay=lay):
        r = (index[0].start or 0) // lay.phys_rows
        rng2 = np.random.default_rng(77 + r)
        return rng2.standard_normal(
            (lay.phys_rows, lay.phys_width)).astype(np.float32)

    fused[name] = jax.make_array_from_callback(
        (WORLD * lay.phys_rows, lay.phys_width),
        NamedSharding(mesh8, P("mp", None)), cb)
state = {"fused": fused,
         "dense": {"w": jax.device_put(jnp.arange(4, dtype=jnp.float32),
                                       rep8)},
         "dense_opt": {}, "emb_dense": {}, "emb_dense_opt": {},
         "step": jax.device_put(jnp.asarray(3, jnp.int32), rep8)}
ck = os.path.join(tmpdir, "ck_tier")
checkpoint.save(ck, tier_plan, rule, state, store=store)
if proc_id == 0:
    assert checkpoint.verify(ck) == []
fresh = HostTierStore(tplan, owned_ranks=owned)
checkpoint.restore(ck, tier_plan, rule, state, mesh=mesh8, store=fresh)
for name in store.images:
    for r in range(WORLD):
        np.testing.assert_array_equal(fresh.resident_grps[name][r],
                                      store.resident_grps[name][r])
        np.testing.assert_array_equal(fresh.counts[name][r],
                                      store.counts[name][r])
        if r in owned:
            np.testing.assert_array_equal(fresh.images[name][r],
                                          store.images[name][r])
        else:
            assert fresh.images[name][r] is None

print("PROC", proc_id, "OK")
"""


def test_pod_barrier_resize_checkpoint_clock(tmp_path):
  """One spawn, four protocol pins: membership barrier, spill resize
  round-trip (bit-exact), barrier checkpoint + pod clock publication,
  torn-newest restore-choice broadcast."""
  spawn_world2(tmp_path, _FAST_BODY)


def test_pod_tiered_owner_local_prefetch(tmp_path):
  """Owner-local TieredPrefetcher + sharded HostTierStore on real
  processes: stage/write_back over non-addressable staged arrays,
  sharded re-rank, per-process tier checkpoint round-trip."""
  spawn_world2(tmp_path, _TIERED_BODY)
