"""Flax DistributedEmbedding module: init outside shard_map, apply inside.

Covers the reference's layer-level usage (`dist_model_parallel.py:327-399`):
construction from table configs, local layer instantiation (here: class
buffers), forward through the wrapper, and training integration with
DistributedOptimizer in a single backward.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest
from distributed_embeddings_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distributed_embeddings_tpu.layers import (
    DistributedEmbedding,
    DistributedOptimizer,
    TableConfig,
    get_weights,
    set_weights,
)

WORLD = 8


def make_mesh():
  return Mesh(np.asarray(jax.devices()[:WORLD]), ("mp",))


def test_module_init_and_apply_under_shard_map():
  rng = np.random.default_rng(0)
  configs = tuple(TableConfig(input_dim=int(s), output_dim=8)
                  for s in rng.integers(20, 100, 10))
  dmp = DistributedEmbedding(embeddings=configs, world_size=WORLD,
                             strategy="memory_balanced")
  batch = 2 * WORLD
  inputs = [jnp.asarray(rng.integers(0, c.input_dim, batch), jnp.int32)
            for c in configs]
  variables = dmp.init(jax.random.PRNGKey(0), inputs)
  names = list(variables["params"].keys())
  assert all(n.startswith("mp_table_") for n in names)
  plan = dmp.plan
  for key in plan.class_keys:
    cp = plan.classes[key]
    arr = variables["params"][f"mp_table_w{key[0]}_cat"]
    assert arr.shape == (WORLD * cp.max_rows, cp.width)

  mesh = make_mesh()
  pspecs = {"params": {n: P("mp", None) for n in names}}

  def fwd(variables, *inputs):
    return tuple(dmp.apply(variables, list(inputs)))

  out = jax.jit(shard_map(
      fwd, mesh=mesh, in_specs=(pspecs,) + tuple(P("mp") for _ in inputs),
      out_specs=tuple(P("mp") for _ in inputs)))(variables, *inputs)
  # parity vs get_weights view
  weights = get_weights(plan, variables["params"])
  for i, o in enumerate(out):
    want = weights[i][np.asarray(inputs[i])]
    np.testing.assert_allclose(np.asarray(o), want, rtol=1e-5, atol=1e-6)


def test_module_trains_with_distributed_optimizer():
  """Hybrid single-backward: dense + embedding params in one grad, dense
  psum'd, embedding local (reference `tests/dist_model_parallel_test.py:399-440`)."""
  rng = np.random.default_rng(1)
  configs = tuple(TableConfig(input_dim=32, output_dim=4) for _ in range(8))
  dmp = DistributedEmbedding(embeddings=configs, world_size=WORLD)
  batch = 2 * WORLD
  inputs = [jnp.asarray(rng.integers(0, 32, batch), jnp.int32)
            for _ in configs]
  targets = jnp.asarray(rng.standard_normal(batch), jnp.float32)
  emb_vars = dmp.init(jax.random.PRNGKey(0), inputs)["params"]
  dense = {"w": jnp.asarray(rng.standard_normal((8 * 4,)), jnp.float32) * 0.1}
  params = {"emb": emb_vars, "dense": dense}

  opt = DistributedOptimizer(optax.sgd(0.05), axis_name="mp")
  opt_state = opt.init(params)
  mesh = make_mesh()
  emb_specs = {n: P("mp", None) for n in emb_vars}
  pspec = {"emb": emb_specs, "dense": {"w": P()}}
  ospec = jax.tree_util.tree_map(lambda _: P(), opt_state)
  # optimizer state mirrors param sharding where it has param structure
  import optax as _optax
  def state_spec(s):
    return jax.tree_util.tree_map(
        lambda leaf: pspec if isinstance(leaf, dict) else P(), s)

  def local_step(params, opt_state, targets, *inputs):
    def loss_fn(p):
      outs = dmp.apply({"params": p["emb"]}, list(inputs))
      feats = jnp.concatenate(outs, axis=-1)
      pred = feats @ p["dense"]["w"]
      return jnp.mean((pred - targets) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    loss = jax.lax.pmean(loss, "mp")
    updates, opt_state = opt.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss

  # opt_state for sgd is (EmptyState(), EmptyState()) -> replicated specs
  step = jax.jit(shard_map(
      local_step, mesh=mesh,
      in_specs=(pspec, jax.tree_util.tree_map(lambda _: P(), opt_state),
                P("mp")) + tuple(P("mp") for _ in inputs),
      out_specs=(pspec, jax.tree_util.tree_map(lambda _: P(), opt_state),
                 P())))

  losses = []
  p, s = params, opt_state
  for _ in range(5):
    p, s, loss = step(p, s, targets, *inputs)
    losses.append(float(loss))
  assert losses[-1] < losses[0], f"loss did not decrease: {losses}"
  # embedding weights actually changed
  w0 = get_weights(dmp.plan, params["emb"])
  w1 = get_weights(dmp.plan, p["emb"])
  assert any(not np.allclose(a, b) for a, b in zip(w0, w1))


def test_row_slice_accepts_int_threshold_only():
  # the reference raises NotImplementedError for ANY row_slice
  # (`dist_model_parallel.py:364-365`); this build implements integer
  # element thresholds and rejects other types
  with pytest.raises(TypeError, match="row_slice"):
    DistributedEmbedding(embeddings=(TableConfig(4, 2),), row_slice="rows")
  dmp = DistributedEmbedding(embeddings=(TableConfig(64, 2),
                                         TableConfig(64, 2)),
                             world_size=2, row_slice=64)
  assert all(sh.row_sliced for shards in dmp.plan.rank_shards
             for sh in shards)


def test_world_one_module_is_plain_layer():
  rng = np.random.default_rng(2)
  configs = (TableConfig(input_dim=16, output_dim=4),
             TableConfig(input_dim=24, output_dim=4))
  dmp = DistributedEmbedding(embeddings=configs, world_size=1)
  inputs = [jnp.asarray(rng.integers(0, 16, 4)),
            jnp.asarray(rng.integers(0, 24, 4))]
  variables = dmp.init(jax.random.PRNGKey(0), inputs)
  outs = dmp.apply(variables, inputs)
  weights = get_weights(dmp.plan, variables["params"])
  np.testing.assert_allclose(np.asarray(outs[0]),
                             weights[0][np.asarray(inputs[0])], rtol=1e-6)
  np.testing.assert_allclose(np.asarray(outs[1]),
                             weights[1][np.asarray(inputs[1])], rtol=1e-6)


def test_metrics_collection_carries_oov_counters():
  """The flax-forward path surfaces the per-class OOV counters the
  guarded step already returns — via a mutable ``'metrics'`` collection,
  absent entirely in apply-only / init (PR 2 API follow-on)."""
  rng = np.random.default_rng(0)
  configs = tuple(TableConfig(input_dim=50, output_dim=8) for _ in range(3))
  dmp = DistributedEmbedding(embeddings=configs, world_size=1)
  inputs = [jnp.asarray(rng.integers(0, 50, 8), jnp.int32) for _ in configs]
  variables = dmp.init(jax.random.PRNGKey(0), inputs)
  assert "metrics" not in variables  # init never records counters
  bad = [i.copy() for i in inputs]
  bad[0] = bad[0].at[:3].set(99)     # 3 OOV occurrences on input 0
  bad[2] = bad[2].at[0].set(10 ** 6)  # 1 on input 2
  outs, mut = dmp.apply(variables, bad, mutable=["metrics"])
  counts = {k: int(np.asarray(jax.tree_util.tree_leaves(v)[0]))
            for k, v in mut["metrics"].items()}
  assert all(k.startswith("oov_mp_table_") for k in counts)
  assert sum(counts.values()) == 4
  # numerics identical to the metric-less apply (clip semantics)
  outs_plain = dmp.apply(variables, bad)
  for a, b in zip(outs, outs_plain):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
  # clean batch: counters present but zero
  _, mut0 = dmp.apply(variables, inputs, mutable=["metrics"])
  assert sum(int(np.asarray(jax.tree_util.tree_leaves(v)[0]))
             for v in mut0["metrics"].values()) == 0


def test_metrics_collection_psums_across_mesh():
  rng = np.random.default_rng(1)
  configs = tuple(TableConfig(input_dim=50, output_dim=8) for _ in range(3))
  dmp = DistributedEmbedding(embeddings=configs, world_size=WORLD)
  inputs = [jnp.asarray(rng.integers(0, 50, 2 * WORLD), jnp.int32)
            for _ in configs]
  variables = dmp.init(jax.random.PRNGKey(0), inputs)
  names = list(variables["params"].keys())
  bad = [i.copy() for i in inputs]
  bad[1] = bad[1].at[:5].set(77)  # spread across devices' batch shards
  mesh = make_mesh()
  pspecs = {"params": {n: P("mp", None) for n in names}}

  def fwd(variables, *inputs):
    outs, mut = dmp.apply(variables, list(inputs), mutable=["metrics"])
    flat = {k: jax.tree_util.tree_leaves(v)[0]
            for k, v in mut["metrics"].items()}
    return tuple(outs), flat

  metric_keys = [f"oov_{n}" for n in names]
  _, flat = jax.jit(shard_map(
      fwd, mesh=mesh,
      in_specs=(pspecs,) + tuple(P("mp") for _ in inputs),
      out_specs=(tuple(P("mp") for _ in inputs),
                 {k: P() for k in metric_keys})))(variables, *bad)
  # psum'd global counts, replicated — same convention as the train step
  assert sum(int(np.asarray(v)) for v in flat.values()) == 5


def test_hybrid_partition_specs_for_adagrad_state():
  from distributed_embeddings_tpu.layers import hybrid_partition_specs
  import optax
  configs = tuple(TableConfig(input_dim=16, output_dim=8) for _ in range(8))
  dmp = DistributedEmbedding(embeddings=configs, world_size=WORLD)
  inputs = [jnp.zeros((WORLD,), jnp.int32) for _ in configs]
  emb = dmp.init(jax.random.PRNGKey(0), inputs)["params"]
  params = {"emb": emb, "dense": {"w": jnp.zeros((4,))}}
  state = optax.adagrad(0.1).init(params)
  specs = hybrid_partition_specs(state)
  leaves = jax.tree_util.tree_leaves_with_path(specs)
  for path, spec in leaves:
    names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
    if any(str(n).startswith("mp_table_") for n in names):
      assert spec == P("mp", None), (names, spec)
    else:
      assert spec == P(), (names, spec)
