"""Property tests: the Pallas apply cache algorithm vs np.add.at.

The hardware kernel (`ops/pallas_apply.py`) cannot run in CI (interpret
mode breaks its input/output aliasing), so its claim/evict/flush state
machine is validated here through the statement-for-statement numpy
simulator (`ops/pallas_apply_sim.py`). Any divergence from np.add.at on
these streams is a real logic bug in the shared algorithm.

The DIRECTED state-machine corners (duplicate hits, slot-collision
chains, OOB drops, alternating evictions, full sweeps) live in the
shared golden vectors (`tests/pallas_goldens.py`, run by
`tests/test_pallas_goldens.py` and replayed on hardware by
`tools/smoke_pallas_apply.py`); this file keeps the RANDOMIZED property
sweeps that would bloat a fixed vector list.
"""

import numpy as np
import pytest

from distributed_embeddings_tpu.ops.pallas_apply_sim import (
    apply_rows_cached_sim,
)


def reference(buf, ids, delta):
  out = np.array(buf, np.float32)
  ok = (ids >= 0) & (ids < buf.shape[0])
  np.add.at(out, ids[ok], delta[ok])
  return out


def check(buf, ids, delta, slots=16):
  got = apply_rows_cached_sim(buf, ids, delta, slots=slots)
  want = reference(buf, ids, delta)
  np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("seed", range(25))
@pytest.mark.parametrize("slots", [1, 2, 16, 128])
def test_random_duplicate_streams(seed, slots):
  rng = np.random.default_rng(seed)
  rows, width = 64, 8
  n = int(rng.integers(1, 400))
  buf = rng.standard_normal((rows, width)).astype(np.float32)
  # heavy duplication: ids drawn from a tiny range so slots collide a lot
  ids = rng.integers(0, rows, n).astype(np.int64)
  delta = rng.standard_normal((n, width)).astype(np.float32)
  check(buf, ids, delta, slots=slots)


@pytest.mark.parametrize("seed", range(10))
def test_power_law_streams(seed):
  rng = np.random.default_rng(100 + seed)
  rows, width = 256, 4
  n = 2000
  buf = rng.standard_normal((rows, width)).astype(np.float32)
  r = rng.random(n)
  gamma = -0.05
  ids = ((r * (float(rows + 1) ** gamma - 1.0) + 1.0) ** (1.0 / gamma)
         ).astype(np.int64) - 1
  ids = np.clip(ids, 0, rows - 1)
  delta = rng.standard_normal((n, width)).astype(np.float32)
  check(buf, ids, delta, slots=8)


def test_chunk_edge_equivalence():
  """The kernel processes ids in chunk-sized grid steps with a persistent
  cache; the simulator has no chunk boundary at all. Running the stream
  split at an arbitrary point with the SAME live cache must equal one
  pass — the simulator is sequential so this is trivially true; what we
  pin here is that the reference semantics do not depend on split points
  (guards future chunked-simulator refactors)."""
  rng = np.random.default_rng(11)
  buf = rng.standard_normal((64, 4)).astype(np.float32)
  ids = rng.integers(0, 64, 333).astype(np.int64)
  delta = rng.standard_normal((333, 4)).astype(np.float32)
  whole = reference(buf, ids, delta)
  part = reference(reference(buf, ids[:100], delta[:100]),
                   ids[100:], delta[100:])
  np.testing.assert_allclose(whole, part, rtol=1e-5, atol=1e-5)
  check(buf, ids, delta, slots=8)


def test_fuzz_big():
  """Thousands of mixed cases: random sizes, slots, OOB rates, dup rates."""
  rng = np.random.default_rng(12)
  for _ in range(60):
    rows = int(rng.integers(1, 200))
    width = int(rng.choice([1, 3, 8]))
    slots = int(rng.choice([1, 2, 4, 32]))
    n = int(rng.integers(0, 600))
    buf = rng.standard_normal((rows, width)).astype(np.float32)
    span = int(rng.integers(1, 2 * rows + 2))
    ids = rng.integers(-3, span, n).astype(np.int64)
    delta = rng.standard_normal((n, width)).astype(np.float32)
    check(buf, ids, delta, slots=slots)
