"""Elastic pods: world-shape-portable checkpoints + sharded cold stores.

The acceptance contract (ISSUE 6): a checkpoint written at world N
restores onto a world-M mesh with **every logical row bit-exact** —
device-tier packed blocks, host-tier cold images, and the interleaved
optimizer lanes alike — and an N -> M -> N round trip reproduces the
source state exactly on those rows. Padding rows (rank-block tail rows
and unused lane windows, which no id can ever address) re-initialize to
zero on an elastic move; ``test_padding_reinit_is_training_neutral``
pins that this changes no training numerics.

The sharded-cold-store half: ``HostTierStore(owned_ranks=...)`` holds
only its ranks' blocks, ``checkpoint.save`` writes per-owner cold files
(no more multi-controller ``NotImplementedError``), and the DONE-marker
publication protocol seals every owner's files into one crc32 manifest.

The cross-run SIGKILL chaos harness (``tools/chaos_kill.py``, ``make
chaos-kill``) is the end-to-end proof; its long multi-cycle variant is
the ``@pytest.mark.slow`` test at the bottom.
"""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu import checkpoint
from distributed_embeddings_tpu.layers.embedding import TableConfig
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM, bce_loss
from distributed_embeddings_tpu.models.dlrm import _dlrm_initializer
from distributed_embeddings_tpu.ops.packed_table import (
    PackedLayout,
    sparse_rule,
)
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.parallel.lookup_engine import (
    class_param_name,
    padded_rows,
)
from distributed_embeddings_tpu.tiering import (
    HostTierStore,
    TieredTrainer,
    TieringConfig,
    TieringPlan,
)
from distributed_embeddings_tpu.tiering.train import init_tiered_state
from distributed_embeddings_tpu.training import (
    init_sparse_state,
    make_sparse_train_step,
    shard_batch,
    shard_params,
)

VOCAB = [300, 200, 150, 20]
RULE = sparse_rule("adagrad", 0.05)


def build(world):
  model = DLRM(vocab_sizes=VOCAB, embedding_dim=16, bottom_mlp=(32, 16),
               top_mlp=(32, 1), world_size=world, dense_row_threshold=32)
  plan = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=16,
            initializer={"name": "uniform", "scale": 0.05}) for v in VOCAB],
      world, "basic", dense_row_threshold=32)
  return model, plan, optax.adagrad(0.05)


def make_batch(seed=0):
  rng = np.random.default_rng(seed)
  b = 16  # divisible by every world size used here
  return (rng.standard_normal((b, 13)).astype(np.float32),
          [rng.integers(0, v, b).astype(np.int32) for v in VOCAB],
          rng.integers(0, 2, b).astype(np.float32))


def init(world, mesh):
  model, plan, opt = build(world)
  b = make_batch()
  params = model.init(jax.random.PRNGKey(0), b[0], b[1])["params"]
  state = shard_params(init_sparse_state(plan, params, RULE, opt), mesh)
  return model, plan, opt, b, state


def logical_tables(plan, rule, state):
  """Every logical table row (weights + optimizer lanes) of a host
  state: ``{table_id: [1 + n_aux, input_dim, output_dim]}``. Device-tier
  fused classes are unpacked per rank; dense-kind classes read from
  ``emb_dense`` (their aux slots stay zero — optax owns that state)."""
  n_aux = rule.n_aux
  cfgs = plan.global_configs
  out = {t: np.zeros((1 + n_aux, c.input_dim, c.output_dim), np.float32)
         for t, c in enumerate(cfgs)}
  for key in plan.class_keys:
    cp = plan.classes[key]
    name = class_param_name(*key)
    rows = padded_rows(plan, key)
    if cp.kind == "sparse":
      lay = PackedLayout(rows=rows, width=cp.width, n_aux=n_aux)
      buf = np.asarray(state["fused"][name])
      for rank in range(plan.world_size):
        blk = buf[rank * lay.phys_rows:(rank + 1) * lay.phys_rows]
        tbl, aux = lay.unpack(blk)
        parts = [tbl] + list(aux)
        for s in cp.slots_per_rank[rank]:
          sh = s.shard
          for a, p in enumerate(parts):
            out[sh.table_id][a, sh.row_start:sh.row_start + sh.input_dim,
                             sh.col_start:sh.col_end] = \
                p[s.row_offset:s.row_offset + sh.input_dim]
    else:
      arr = np.asarray(state["emb_dense"][name])
      for rank in range(plan.world_size):
        for s in cp.slots_per_rank[rank]:
          sh = s.shard
          base = rank * rows + s.row_offset
          out[sh.table_id][0, sh.row_start:sh.row_start + sh.input_dim,
                           sh.col_start:sh.col_end] = \
              arr[base:base + sh.input_dim]
  return out


def assert_tables_equal(ta, tb):
  for t in ta:
    np.testing.assert_array_equal(ta[t], tb[t], err_msg=f"table {t}")


def trained_checkpoint(tmp_path, world=4, steps=3):
  mesh = create_mesh(world)
  model, plan, opt, b, state = init(world, mesh)
  step = make_sparse_train_step(model, plan, bce_loss, opt, RULE, mesh,
                                state, b, donate=False)
  sb = shard_batch(b, mesh)
  for _ in range(steps):
    state, _ = step(state, *sb)
  path = os.path.join(tmp_path, f"ck_w{world}")
  checkpoint.save(path, plan, RULE, state)
  return path, plan, state, step, sb


# ---------------------------------------------------------------------------
# ACCEPTANCE: world-N save -> world-M restore, every logical row bit-exact
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("src,dst", [(4, 2), (2, 4)])
def test_elastic_restore_bit_exact(tmp_path, src, dst):
  path, plan_src, s_src, _, _ = trained_checkpoint(tmp_path, world=src)
  mesh_dst = create_mesh(dst)
  _, plan_dst, _, _, s_like = init(dst, mesh_dst)
  s_dst = checkpoint.restore(path, plan_dst, RULE, s_like, mesh=mesh_dst)
  assert int(jax.device_get(s_dst["step"])) == 3
  assert_tables_equal(logical_tables(plan_src, RULE, jax.device_get(s_src)),
                      logical_tables(plan_dst, RULE, jax.device_get(s_dst)))


def test_elastic_roundtrip_4_2_4(tmp_path):
  """N -> M -> N: the round trip reproduces every logical row exactly,
  and the repacked fused buffers are byte-identical (their padding was
  zero to begin with — direct draws zero dead rows)."""
  path, plan4, s4, _, _ = trained_checkpoint(tmp_path, world=4)
  mesh2, mesh4 = create_mesh(2), create_mesh(4)
  _, plan2, _, _, s2_like = init(2, mesh2)
  s2 = checkpoint.restore(path, plan2, RULE, s2_like, mesh=mesh2)
  path2 = os.path.join(tmp_path, "ck_back")
  checkpoint.save(path2, plan2, RULE, s2)
  s4b = checkpoint.restore(path2, plan4, RULE, s4, mesh=mesh4)
  assert_tables_equal(logical_tables(plan4, RULE, jax.device_get(s4)),
                      logical_tables(plan4, RULE, jax.device_get(s4b)))
  a, b = jax.device_get(s4), jax.device_get(s4b)
  for part in ("dense", "dense_opt", "emb_dense", "step"):
    fa = jax.tree_util.tree_leaves(a[part])
    fb = jax.tree_util.tree_leaves(b[part])
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
      np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                    err_msg=part)


def test_padding_reinit_is_training_neutral(tmp_path):
  """Padding rows/lanes re-initialize to zero on an elastic move (e.g.
  a pack_chunked-initialized buffer carries the adagrad 0.1 fill on
  padding lanes; the re-packed one does not). No id can address them,
  so continued training from the round-trip state must match the
  original run bit-for-bit."""
  path, plan4, s4, step4, sb = trained_checkpoint(tmp_path, world=4)
  mesh2, mesh4 = create_mesh(2), create_mesh(4)
  _, plan2, _, _, s2_like = init(2, mesh2)
  s2 = checkpoint.restore(path, plan2, RULE, s2_like, mesh=mesh2)
  path2 = os.path.join(tmp_path, "ck_back")
  checkpoint.save(path2, plan2, RULE, s2)
  s4b = checkpoint.restore(path2, plan4, RULE, s4, mesh=mesh4)
  s4c, l_a = step4(s4, *sb)
  s4bc, l_b = step4(s4b, *sb)
  assert float(l_a) == float(l_b)
  assert_tables_equal(logical_tables(plan4, RULE, jax.device_get(s4c)),
                      logical_tables(plan4, RULE, jax.device_get(s4bc)))


def test_elastic_restore_then_train_at_new_world(tmp_path):
  """The re-sharded state is a live train state at the new world, not
  just a readable one."""
  path, _, _, _, _ = trained_checkpoint(tmp_path, world=4)
  mesh2 = create_mesh(2)
  model2, plan2, opt2, b, s2_like = init(2, mesh2)
  s2 = checkpoint.restore(path, plan2, RULE, s2_like, mesh=mesh2)
  step2 = make_sparse_train_step(model2, plan2, bce_loss, opt2, RULE, mesh2,
                                 s2, b, donate=False)
  s2, loss = step2(s2, *shard_batch(b, mesh2))
  assert np.isfinite(float(loss))
  assert int(jax.device_get(s2["step"])) == 4


def test_manifest_world_section(tmp_path):
  path, plan, _, _, _ = trained_checkpoint(tmp_path, world=4)
  world = checkpoint.read_manifest(path)["world"]
  assert world["ranks"] == 4
  for key in plan.class_keys:
    meta = world["classes"][class_param_name(*key)]
    assert meta["kind"] == plan.classes[key].kind
    assert meta["tier"] == "device"
    assert meta["rows"] == padded_rows(plan, key)


def test_elastic_refuses_different_tables(tmp_path):
  path, _, _, _, _ = trained_checkpoint(tmp_path, world=4)
  mesh2 = create_mesh(2)
  other = DistEmbeddingStrategy(
      [dict(input_dim=v + 1, output_dim=16,
            initializer={"name": "uniform", "scale": 0.05}) for v in VOCAB],
      2, "basic", dense_row_threshold=32)
  _, _, _, _, s_like = init(2, mesh2)
  with pytest.raises(ValueError, match="cannot be elastically"):
    checkpoint.restore(path, other, RULE, s_like, mesh=mesh2)


def test_elastic_refuses_kind_flip(tmp_path):
  """A dense_row_threshold change that flips a table between the packed
  sparse format and the MXU-dense format is a format conversion, not a
  row move — it must refuse with the reason named, not KeyError."""
  path, _, _, _, _ = trained_checkpoint(tmp_path, world=4)
  plan_flip = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=16,
            initializer={"name": "uniform", "scale": 0.05}) for v in VOCAB],
      2, "basic", dense_row_threshold=0)  # vocab-20 table now sparse-kind
  mesh2 = create_mesh(2)
  _, _, _, _, s_like = init(2, mesh2)
  with pytest.raises(ValueError, match="kind"):
    checkpoint.restore(path, plan_flip, RULE, s_like, mesh=mesh2)


def test_elastic_refuses_cross_tier_move(tmp_path):
  """A table saved on the device tier cannot restore host-tiered (or
  vice versa): that is a format conversion, and the refusal must say
  so rather than corrupt."""
  path, _, _, _, _ = trained_checkpoint(tmp_path, world=4)
  plan_t = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=16,
            initializer={"name": "uniform", "scale": 0.05}) for v in VOCAB],
      2, "basic", dense_row_threshold=32, host_row_threshold=250)
  mesh2 = create_mesh(2)
  _, _, _, _, s_like = init(2, mesh2)
  tplan = TieringPlan(plan_t, RULE, TieringConfig(cache_fraction=0.3,
                                                  staging_grps=8))
  with pytest.raises(ValueError, match="cross-tier"):
    checkpoint.restore(path, plan_t, RULE, s_like, mesh=mesh2,
                       store=HostTierStore(tplan))


# ---------------------------------------------------------------------------
# tiered elastic: cold images re-shard, resident sets re-derive
# ---------------------------------------------------------------------------

T_VOCAB = [5000, 300, 40]
T_WIDTH = 16
T_CFG = TieringConfig(cache_fraction=0.3, staging_grps=64)


def tiered_build(world):
  plan = DistEmbeddingStrategy(
      [TableConfig(input_dim=v, output_dim=T_WIDTH,
                   initializer=_dlrm_initializer(v)) for v in T_VOCAB],
      world, "memory_balanced", dense_row_threshold=0,
      host_row_threshold=1000)
  model = DLRM(vocab_sizes=T_VOCAB, embedding_dim=T_WIDTH,
               bottom_mlp=(32, T_WIDTH), top_mlp=(32, 1), world_size=world,
               strategy="memory_balanced", dense_row_threshold=0)
  return plan, model


def tiered_batch(seed, b=32):
  r = np.random.default_rng(seed)
  return (r.standard_normal((b, 13)).astype(np.float32),
          [r.integers(0, v, b).astype(np.int32) for v in T_VOCAB],
          r.integers(0, 2, b).astype(np.float32))


def partial_store(world, owned_ranks, seed=5):
  """A rank-owner-sharded store (one multi-controller process's view) —
  built standalone: device state at a partial store needs the owning
  process's mesh slice, which a single-process test cannot have."""
  plan, _ = tiered_build(world)
  tplan = TieringPlan(plan, RULE, T_CFG)
  store = HostTierStore(tplan, owned_ranks=owned_ranks)
  store.init_uniform(seed)
  return plan, tplan, store


def tiered_fresh(world, mesh, seed=5):
  plan, model = tiered_build(world)
  tplan = TieringPlan(plan, RULE, T_CFG)
  store = HostTierStore(tplan)
  b0 = tiered_batch(100)
  params = model.init(jax.random.PRNGKey(0), b0[0], b0[1])["params"]
  dense = {k: v for k, v in params.items() if k != "embeddings"}
  state = init_tiered_state(tplan, store, RULE, dense, optax.adam(1e-3),
                            jax.random.PRNGKey(seed), mesh=mesh)
  return plan, model, tplan, store, b0, state


def host_logical_tables(plan, tplan, store):
  out = {}
  for key, c in tplan.classes.items():
    cp = plan.classes[key]
    lay = c.layout_logical
    for rank in store.owned_ranks:
      tbl, aux = lay.unpack(store.images[c.name][rank])
      parts = [tbl] + list(aux)
      for s in cp.slots_per_rank[rank]:
        sh = s.shard
        cfg = plan.global_configs[sh.table_id]
        dst = out.setdefault(sh.table_id, np.zeros(
            (1 + RULE.n_aux, cfg.input_dim, cfg.output_dim), np.float32))
        for a, p in enumerate(parts):
          dst[a, sh.row_start:sh.row_start + sh.input_dim,
              sh.col_start:sh.col_end] = \
              p[s.row_offset:s.row_offset + sh.input_dim]
  return out


def test_tiered_elastic_restore_4_to_2(tmp_path):
  mesh4, mesh2 = create_mesh(4), create_mesh(2)
  plan4, model4, tplan4, store4, b0, state4 = tiered_fresh(4, mesh4)
  tr4 = TieredTrainer(model4, tplan4, store4, bce_loss, optax.adam(1e-3),
                      RULE, mesh4, shard_params(state4, mesh4), b0,
                      donate=False)
  tr4.run([tiered_batch(100 + i) for i in range(4)])
  tr4.flush()
  path = os.path.join(tmp_path, "ck_t4")
  checkpoint.save(path, plan4, RULE, tr4.state, store=store4)

  plan2, model2, tplan2, store2, _, s2_like = tiered_fresh(2, mesh2, seed=9)
  s2 = checkpoint.restore(path, plan2, RULE, s2_like, mesh=mesh2,
                          store=store2)
  assert int(jax.device_get(s2["step"])) == 4
  # every host-tier logical row (weights + optimizer lanes) bit-exact
  assert_tables_equal(host_logical_tables(plan4, tplan4, store4),
                      host_logical_tables(plan2, tplan2, store2))
  # the re-derived resident set serves continued training with no misses
  tr2 = TieredTrainer(model2, tplan2, store2, bce_loss, optax.adam(1e-3),
                      RULE, mesh2, shard_params(s2, mesh2), b0,
                      donate=False)
  losses = tr2.run([tiered_batch(200 + i) for i in range(2)])
  assert all(np.isfinite(l) for l in losses)
  assert all(v["missed"] == 0
             for v in tr2.metrics_summary()["per_class"].values())


def _logical_counts(plan, tplan, store):
  """Table-space view of the per-group observed counts: per logical
  table row, the count of the GROUP (physical row) holding it."""
  out = {}
  for key, c in tplan.classes.items():
    cp = plan.classes[key]
    rpp = c.layout_logical.rows_per_phys
    for rank in store.owned_ranks:
      cnt = store.counts[c.name][rank]
      for sh, off in zip(cp.shards_per_rank[rank],
                         cp.row_offsets_per_rank[rank]):
        cfg = plan.global_configs[sh.table_id]
        dst = out.setdefault(sh.table_id,
                             np.zeros((cfg.input_dim,), np.int64))
        rows = np.arange(sh.input_dim)
        win = dst[sh.row_start:sh.row_start + sh.input_dim]
        np.maximum(win, cnt[(off + rows) // rpp], out=win)
  return out


def test_elastic_reshard_remaps_observed_counts(tmp_path):
  """ROADMAP carried item: host-tier observed counts route WINDOW-WISE
  through the elastic re-shard (they used to re-derive from zero,
  costing one re-rank interval of hot-set warmup after every resize).
  Pins: counts are nonzero after the re-shard, the hottest rows'
  counts survive exactly, the warm-start resident set already contains
  the top-counted groups, and continued training serves with no
  misses."""
  mesh4, mesh2 = create_mesh(4), create_mesh(2)
  plan4, model4, tplan4, store4, b0, state4 = tiered_fresh(4, mesh4)
  tr4 = TieredTrainer(model4, tplan4, store4, bce_loss, optax.adam(1e-3),
                      RULE, mesh4, shard_params(state4, mesh4), b0,
                      donate=False)
  tr4.run([tiered_batch(100 + i) for i in range(4)])
  tr4.flush()
  path = os.path.join(tmp_path, "ck_counts")
  checkpoint.save(path, plan4, RULE, tr4.state, store=store4)
  want = _logical_counts(plan4, tplan4, store4)
  assert sum(int(v.sum()) for v in want.values()) > 0

  plan2, model2, tplan2, store2, _, s2_like = tiered_fresh(2, mesh2,
                                                           seed=9)
  s2 = checkpoint.restore(path, plan2, RULE, s2_like, mesh=mesh2,
                          store=store2)
  got = _logical_counts(plan2, tplan2, store2)
  for t in want:
    assert int(got[t].sum()) > 0, \
        f"table {t}: counts re-derived from zero (old behavior)"
    # the re-map max-pools each new group over its logical rows' old
    # group counts: per row it can only round UP to its new group's
    # peak, never lose signal — and each table's peak survives exactly
    assert int(got[t].max()) == int(want[t].max())
    assert np.all(got[t] >= want[t])
  # warm start ranked by the re-mapped counts: every rank's hottest
  # group is already resident (no re-rank interval of warmup)
  for key, c in tplan2.classes.items():
    for rank in store2.owned_ranks:
      cnt = store2.counts[c.name][rank]
      if cnt.max() == 0:
        continue
      hottest = int(np.argmax(cnt))
      assert hottest in store2.resident_grps[c.name][rank]
  tr2 = TieredTrainer(model2, tplan2, store2, bce_loss, optax.adam(1e-3),
                      RULE, mesh2, shard_params(s2, mesh2), b0,
                      donate=False)
  losses = tr2.run([tiered_batch(200 + i) for i in range(2)])
  assert all(np.isfinite(l) for l in losses)
  assert all(v["missed"] == 0
             for v in tr2.metrics_summary()["per_class"].values())


# ---------------------------------------------------------------------------
# rank-owner-sharded cold stores + multi-controller save protocol
# ---------------------------------------------------------------------------


def test_sharded_store_owner_access():
  _, tplan, store = partial_store(4, owned_ranks=[1, 2])
  assert store.owned_ranks == (1, 2) and not store.owns_all
  name = next(iter(tplan.tier_specs))
  rows = store.gather(name, 1, np.array([0, 1], np.int64))
  assert rows.shape[0] == 2
  with pytest.raises(ValueError, match="not owned"):
    store.gather(name, 0, np.array([0], np.int64))
  with pytest.raises(ValueError, match="not owned"):
    store.set_image(name, 3, np.zeros((1, 1), np.float32))
  # a sharded store with no mesh cannot fabricate un-owned device blocks
  with pytest.raises(ValueError, match="needs the global mesh"):
    store.build_fused(mesh=None)


def test_sharded_store_writes_only_owned_ranks(tmp_path, monkeypatch):
  """Two complementary owners' write phases compose one full cold set
  with disjoint per-owner tier-state files — the per-process half of
  the multi-controller save protocol, driven directly."""
  _, tplan, full = partial_store(4, owned_ranks=range(4))
  halves = []
  for pidx, ranks in enumerate([(0, 1), (2, 3)]):
    _, tp, half = partial_store(4, owned_ranks=ranks)
    for name in tp.tier_specs:
      for r in ranks:
        half.set_image(name, r, full.images[name][r])
    halves.append(half)
  tmp = os.path.join(tmp_path, "compose")
  os.makedirs(tmp)
  sealed = []
  for pidx, half in enumerate(halves):
    monkeypatch.setattr(jax, "process_index", lambda pidx=pidx: pidx)
    checkpoint._write_tier_blocks(tmp, half, sealed.append)
  monkeypatch.undo()
  files = sorted(os.listdir(tmp))
  names = sorted(tplan.tier_specs)
  assert [f for f in files if f.startswith("cold_")] == sorted(
      f"cold_{n}_r{r}.npy" for n in names for r in range(4))
  assert [f for f in files if f.startswith("tiering")] == [
      "tiering_p0.npz", "tiering_p1.npz"]
  with np.load(os.path.join(tmp, "tiering_p0.npz")) as z0, \
       np.load(os.path.join(tmp, "tiering_p1.npz")) as z1:
    k0, k1 = set(z0.keys()), set(z1.keys())
  assert not (k0 & k1)
  assert all("/r0/" in k or "/r1/" in k for k in k0)
  assert all("/r2/" in k or "/r3/" in k for k in k1)
  for name in names:
    for r in range(4):
      np.testing.assert_array_equal(
          np.load(os.path.join(tmp, f"cold_{name}_r{r}.npy")),
          full.images[name][r])


def test_multicontroller_tiered_save_publishes(tmp_path, monkeypatch):
  """The multi-controller tiered save no longer raises: with the
  barriers stubbed and a second process's DONE marker planted, the full
  protocol — per-owner writes, marker merge, manifest-last publication
  — runs end to end and the result verifies and restores."""
  mesh4 = create_mesh(4)
  plan, model, tplan, store, b0, state = tiered_fresh(4, mesh4)
  path = os.path.join(tmp_path, "ck_mc")
  monkeypatch.setattr(checkpoint, "_barrier", lambda tag: None)
  # the clock handshake is a real collective (broadcast_one_to_all) —
  # stubbed here like the barriers; the spawned-process tests exercise
  # the real one
  monkeypatch.setattr(
      checkpoint, "_pod_clock_record",
      lambda rounds=8: {"process": 0, "offset_ns": 0, "uncertainty_ns": 0,
                        "rtt_ns": 0, "rounds": rounds})
  monkeypatch.setattr(jax, "process_count", lambda: 2)

  done = {}

  def plant_marker():
    tmp = path + ".tmp"
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
      if os.path.exists(os.path.join(tmp, "DONE_p0")):
        with open(os.path.join(tmp, "clock_p1.json"), "w") as f:
          f.write('{"process": 1, "offset_ns": 1234, '
                  '"uncertainty_ns": 7, "rtt_ns": 14, "rounds": 8}')
        with open(os.path.join(tmp, "DONE_p1"), "w") as f:
          f.write("{}")
        done["planted"] = True
        return
      time.sleep(0.02)

  t = threading.Thread(target=plant_marker)
  t.start()
  try:
    checkpoint.save(path, plan, RULE, state, store=store)
  finally:
    t.join()
  monkeypatch.undo()
  assert done.get("planted")
  assert checkpoint.verify(path) == []
  # the piggybacked clock records merged into pod_clock.json (and the
  # per-process transport files vanished with the markers)
  clocks = checkpoint.read_pod_clock(path)
  assert clocks[1]["offset_ns"] == 1234 and clocks[0]["offset_ns"] == 0
  assert not [f for f in os.listdir(path) if f.startswith("clock_p")]
  _, _, tplan_c, store_c, _, s_like = tiered_fresh(4, mesh4, seed=11)
  restored = checkpoint.restore(path, plan, RULE, s_like, mesh=mesh4,
                                store=store_c)
  assert_tables_equal(host_logical_tables(plan, tplan, store),
                      host_logical_tables(plan, tplan_c, store_c))
  assert int(jax.device_get(restored["step"])) == 0


def test_restore_reads_per_owner_tierstate(tmp_path):
  """A checkpoint whose tier state arrived as per-owner
  ``tiering_p<k>.npz`` files (sharded save) restores exactly like the
  single-file form."""
  mesh4 = create_mesh(4)
  plan, model, tplan, store, b0, state = tiered_fresh(4, mesh4)
  path = os.path.join(tmp_path, "ck")
  checkpoint.save(path, plan, RULE, state, store=store)
  os.rename(os.path.join(path, "tiering.npz"),
            os.path.join(path, "tiering_p0.npz"))
  mpath = os.path.join(path, "manifest.json")
  with open(mpath) as f:
    manifest = json.load(f)
  manifest["checksums"]["tiering_p0.npz"] = \
      manifest["checksums"].pop("tiering.npz")
  with open(mpath, "w") as f:
    json.dump(manifest, f)
  assert checkpoint.verify(path) == []
  _, _, tplan_c, store_c, _, s_like = tiered_fresh(4, mesh4, seed=13)
  checkpoint.restore(path, plan, RULE, s_like, mesh=mesh4, store=store_c)
  for name in tplan.tier_specs:
    for r in range(4):
      np.testing.assert_array_equal(store_c.images[name][r],
                                    store.images[name][r])
      np.testing.assert_array_equal(store_c.resident_grps[name][r],
                                    store.resident_grps[name][r])


# ---------------------------------------------------------------------------
# guarded tiered step (PR 2 carried follow-on)
# ---------------------------------------------------------------------------


def test_guarded_tiered_skip_bit_exact_incl_host_images():
  """A guarded tiered run fed poison batches commits the SAME state —
  device buffers AND host-tier images — as a run that never saw them."""
  from distributed_embeddings_tpu.resilience import faultinject
  mesh = create_mesh(4)

  def fresh():
    plan, model, tplan, store, b0, state = tiered_fresh(4, mesh, seed=7)
    tr = TieredTrainer(model, tplan, store, bce_loss, optax.adam(1e-3),
                       RULE, mesh, shard_params(state, mesh), b0,
                       donate=False, guard=True)
    return store, tr

  batches = [tiered_batch(100 + i) for i in range(5)]
  poison = list(faultinject.nan_batches(batches, at_steps={1, 3}))
  s1, t1 = fresh()
  losses = t1.run(poison)
  assert np.isnan(losses[1]) and np.isnan(losses[3])
  assert t1.bad_steps == 2
  assert int(np.asarray(jax.device_get(t1.state["step"]))) == 3

  s2, t2 = fresh()
  t2.run([batches[i] for i in (0, 2, 4)])
  t1.flush()
  t2.flush()
  fa = jax.tree_util.tree_leaves(jax.device_get(t1.state))
  fb = jax.tree_util.tree_leaves(jax.device_get(t2.state))
  for a, b in zip(fa, fb):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
  for name in s1.images:
    for r in range(4):
      np.testing.assert_array_equal(s1.images[name][r], s2.images[name][r])


def test_tiered_guard_validation():
  from distributed_embeddings_tpu.training import make_tiered_train_step
  mesh = create_mesh(4)
  plan, model, tplan, store, b0, state = tiered_fresh(4, mesh)
  with pytest.raises(NotImplementedError, match="guard"):
    make_tiered_train_step(model, tplan, bce_loss, optax.adam(1e-3), RULE,
                           mesh, state, b0, guard=True, exact=True)


def test_tiered_oov_error_requires_guard_and_counts():
  from distributed_embeddings_tpu.training import make_tiered_train_step
  mesh = create_mesh(4)
  plan = DistEmbeddingStrategy(
      [TableConfig(input_dim=v, output_dim=T_WIDTH,
                   initializer=_dlrm_initializer(v)) for v in T_VOCAB],
      4, "memory_balanced", dense_row_threshold=0,
      host_row_threshold=1000, oov="error")
  _, model = tiered_build(4)
  tplan = TieringPlan(plan, RULE, T_CFG)
  store = HostTierStore(tplan)
  b0 = tiered_batch(100)
  params = model.init(jax.random.PRNGKey(0), b0[0], b0[1])["params"]
  dense = {k: v for k, v in params.items() if k != "embeddings"}
  state = init_tiered_state(tplan, store, RULE, dense, optax.adam(1e-3),
                            jax.random.PRNGKey(3), mesh=mesh)
  with pytest.raises(ValueError, match="guard=True"):
    make_tiered_train_step(model, tplan, bce_loss, optax.adam(1e-3), RULE,
                           mesh, state, b0, guard=False)
  tr = TieredTrainer(model, tplan, store, bce_loss, optax.adam(1e-3),
                     RULE, mesh, shard_params(state, mesh), b0,
                     donate=False, guard=True)
  tr.step(*b0)  # clean batch passes
  before = jax.device_get(tr.state)
  bad = [c.copy() for c in b0[1]]
  bad[1][0] = T_VOCAB[1] + 5
  with pytest.raises(ValueError, match="OOV policy 'error'"):
    tr.step(b0[0], bad, b0[2])
  # commit-gated: the raise fires with the state bit-identical
  fa = jax.tree_util.tree_leaves(before)
  fb = jax.tree_util.tree_leaves(jax.device_get(tr.state))
  for a, b in zip(fa, fb):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
  assert sum(tr.oov_totals.values()) == 1


# ---------------------------------------------------------------------------
# cross-run SIGKILL chaos: the long multi-cycle variant
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_kill_long():
  import sys
  sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
  import chaos_kill
  res = chaos_kill.run_chaos_kill(steps=24, resize_world=2, verbose=False,
                                  extra_cycles=True)
  assert res["ok"], res
