"""Tests for the apply-scatter dispatch (`ops/packed_table.scatter_add_fused`
regime selection + `ops/pallas_apply` wrapper contracts).

The Pallas kernel itself needs a real TPU (its input/output aliasing has no
faithful interpret-mode equivalent) — `tools/smoke_pallas_apply.py` /
`make tpu-smoke` covers it on hardware. Here we pin:
- the XLA fallback stays numerically exact for both regimes on CPU;
- wrapper argument validation;
- the env-var override logic.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.ops.packed_table import (
    PackedLayout,
    scatter_add_fused,
)
from distributed_embeddings_tpu.ops.pallas_apply import apply_rows_cached


@pytest.mark.parametrize("prefer_pallas", [False, True])
@pytest.mark.parametrize("n_aux", [0, 1])
def test_scatter_add_fused_regimes_match(prefer_pallas, n_aux):
  """Both dispatch regimes must produce the same result (on CPU both lower
  to XLA scatter; on TPU one runs the Pallas kernel — tools/smoke covers
  that equivalence on hardware)."""
  layout = PackedLayout(rows=64, width=128, n_aux=n_aux)
  rng = np.random.default_rng(0)
  buf = jnp.asarray(rng.standard_normal(layout.shape), jnp.float32)
  ids = jnp.asarray(rng.integers(-2, layout.rows + 2, 200), jnp.int32)
  delta = jnp.asarray(rng.standard_normal((200, layout.stride)), jnp.float32)
  got = scatter_add_fused(layout, buf, ids, delta,
                          prefer_pallas=prefer_pallas)
  want = scatter_add_fused(layout, buf, ids, delta, prefer_pallas=False)
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_dispatch_logic(monkeypatch):
  """Pin the env-override + regime selection by spying on the kernel entry
  (on the CPU CI backend the kernel can't run, so capability is stubbed)."""
  import distributed_embeddings_tpu.ops.packed_table as pt

  calls = []
  monkeypatch.setattr(pt, "_use_pallas_apply", lambda: True)
  monkeypatch.setattr(
      "distributed_embeddings_tpu.ops.pallas_apply.apply_rows_cached",
      lambda buf, ids, delta, **kw: calls.append(len(ids)) or buf)

  layout = PackedLayout(rows=32, width=128)       # rpp == 1
  narrow = PackedLayout(rows=32, width=16)        # rpp > 1
  buf = jnp.zeros(layout.shape, jnp.float32)
  nbuf = jnp.zeros(narrow.shape, jnp.float32)
  ids = jnp.asarray([1, 1, 5], jnp.int32)
  delta = jnp.ones((3, 128), jnp.float32)
  ndelta = jnp.ones((3, narrow.stride), jnp.float32)

  scatter_add_fused(layout, buf, ids, delta, prefer_pallas=True)
  assert len(calls) == 1, "prefer_pallas + rpp==1 must take the kernel"
  scatter_add_fused(layout, buf, ids, delta, prefer_pallas=False)
  assert len(calls) == 1, "prefer_pallas=False must keep XLA scatter"
  scatter_add_fused(narrow, nbuf, ids, ndelta, prefer_pallas=True)
  assert len(calls) == 2, ("rpp > 1 takes the kernel too: the lane "
                           "expansion feeds it physical-row updates")
  monkeypatch.setenv("DE_TPU_PALLAS_APPLY", "1")
  scatter_add_fused(layout, buf, ids, delta, prefer_pallas=False)
  assert len(calls) == 3, "DE_TPU_PALLAS_APPLY=1 must force the kernel"
  monkeypatch.setenv("DE_TPU_PALLAS_APPLY", "0")
  out = scatter_add_fused(layout, buf, ids, delta, prefer_pallas=True)
  assert len(calls) == 3, "DE_TPU_PALLAS_APPLY=0 must force XLA"
  assert float(out[1, 0]) == 2.0 and float(out[5, 0]) == 1.0


def test_apply_rows_cached_validates():
  buf = jnp.zeros((16, 128), jnp.float32)
  ids = jnp.zeros((4,), jnp.int32)
  with pytest.raises(ValueError, match="delta shape"):
    apply_rows_cached(buf, ids, jnp.zeros((4, 64), jnp.float32))
  with pytest.raises(ValueError, match="power of two"):
    apply_rows_cached(buf, ids, jnp.zeros((4, 128), jnp.float32), slots=48)
