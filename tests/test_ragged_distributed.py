"""Ragged inputs through the DISTRIBUTED engine (VERDICT item 5).

The engine routes ragged inputs as their value stream (static capacity)
plus per-sample lengths — true variable hotness, the reference's uneven-
split alltoall (`dist_model_parallel.py:407-429`) — instead of requiring
pre-padding to a static max hotness. These tests pin parity of the
value-stream path against the padded path and the single-device op, on an
8-virtual-device mesh, for forward and fused training.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_embeddings_tpu.layers import DistEmbeddingStrategy, TableConfig
from distributed_embeddings_tpu.ops import embedding_lookup
from distributed_embeddings_tpu.ops.packed_table import adagrad_rule, sgd_rule
from distributed_embeddings_tpu.ops.ragged import RaggedIds
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.parallel.lookup_engine import (
    DistributedLookup,
    ragged_to_padded,
)
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    get_weights,
    set_weights,
)
from distributed_embeddings_tpu.training import shard_batch, shard_params

WORLD = 8


def _make_ragged(rng, b, vocab, max_hot, capacity):
  """Random per-sample variable hotness, total ids <= capacity."""
  lengths = rng.integers(0, max_hot + 1, b)
  while lengths.sum() > capacity:
    lengths[rng.integers(0, b)] = max(0, lengths[rng.integers(0, b)] - 1)
  total = int(lengths.sum())
  values = rng.integers(0, vocab, total).astype(np.int32)
  # static capacity: pad the value buffer (slack past row_splits[-1])
  values = np.concatenate(
      [values, np.zeros(capacity - total, np.int32)])
  splits = np.concatenate([[0], np.cumsum(lengths)]).astype(np.int32)
  return RaggedIds(jnp.asarray(values), jnp.asarray(splits)), lengths


def _stack_ragged(parts):
  """Per-device ragged blocks -> one global RaggedIds whose values and
  row_splits shard evenly over the mesh batch axis."""
  return RaggedIds(
      jnp.concatenate([p.values for p in parts]),
      jnp.concatenate([p.row_splits for p in parts]))


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_distributed_ragged_matches_padded_and_single(combiner):
  rng = np.random.default_rng(0)
  tables = [TableConfig(50, 16, combiner=combiner),
            TableConfig(80, 16, combiner=combiner)] + \
           [TableConfig(20 + i, 16, combiner=combiner) for i in range(7)]
  plan = DistEmbeddingStrategy(tables, WORLD, "basic",
                               dense_row_threshold=0)
  engine = DistributedLookup(plan)
  weights = [rng.standard_normal((c.input_dim, c.output_dim))
             .astype(np.float32) for c in tables]
  params = set_weights(plan, weights)
  params = {k: jnp.asarray(v) for k, v in params.items()}

  b_local, max_hot, cap = 4, 5, 16
  # per-device ragged blocks, stacked (values [world*cap], splits
  # [world*(b+1)] shard evenly over the mesh)
  per_dev = [_make_ragged(rng, b_local, 50, max_hot, cap)
             for _ in range(WORLD)]
  ragged_blocks = [p[0] for p in per_dev]
  global_ragged = _stack_ragged(ragged_blocks)
  dense_inputs = [jnp.asarray(
      rng.integers(0, c.input_dim, (WORLD * b_local, 1)), jnp.int32)
      for c in tables[1:]]

  mesh = create_mesh(WORLD)
  from jax.sharding import NamedSharding, PartitionSpec as P
  from distributed_embeddings_tpu.compat import shard_map

  def fwd(params, rg_values, rg_splits, *dense):
    rg = RaggedIds(rg_values, rg_splits)
    return engine.forward(params, [rg] + list(dense))

  pspec = jax.tree_util.tree_map(lambda _: P("mp", None), params)
  outs = jax.jit(shard_map(
      fwd, mesh=mesh,
      in_specs=(pspec, P("mp"), P("mp")) + (P("mp"),) * len(dense_inputs),
      out_specs=P("mp")))(
          shard_params(params, mesh),
          jax.device_put(global_ragged.values,
                         NamedSharding(mesh, P("mp"))),
          jax.device_put(global_ragged.row_splits,
                         NamedSharding(mesh, P("mp"))),
          *[jax.device_put(d, NamedSharding(mesh, P("mp")))
            for d in dense_inputs])

  # single-device reference for table 0 (concatenate per-device blocks)
  want_blocks = []
  for rg in ragged_blocks:
    want_blocks.append(np.asarray(
        embedding_lookup(jnp.asarray(weights[0]), rg, combiner=combiner)))
  want0 = np.concatenate(want_blocks)
  np.testing.assert_allclose(np.asarray(outs[0]), want0, rtol=1e-5,
                             atol=1e-5)

  # padded-path parity for the same ragged input
  padded_blocks = [ragged_to_padded(rg, max_hot) for rg in ragged_blocks]
  padded = jnp.concatenate(padded_blocks)

  def fwd_padded(params, x0, *dense):
    return engine.forward(params, [x0] + list(dense))

  outs_p = jax.jit(shard_map(
      fwd_padded, mesh=mesh,
      in_specs=(pspec, P("mp")) + (P("mp"),) * len(dense_inputs),
      out_specs=P("mp")))(
          shard_params(params, mesh),
          jax.device_put(padded, NamedSharding(mesh, P("mp"))),
          *[jax.device_put(d, NamedSharding(mesh, P("mp")))
            for d in dense_inputs])
  np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(outs_p[0]),
                             rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("rulename", ["sgd", "adagrad"])
def test_fused_training_ragged_matches_padded(rulename):
  """One fused train step with ragged cats must update the tables exactly
  like the same step with the equivalent padded-dense cats."""
  from distributed_embeddings_tpu.models import bce_loss
  from distributed_embeddings_tpu.training import (
      init_sparse_state_direct, make_sparse_train_step)
  import flax.linen as nn

  class TinyModel(nn.Module):
    """Minimal model consuming precomputed embedding activations."""

    @nn.compact
    def __call__(self, numerical, cats, emb_acts=None):
      x = jnp.concatenate([numerical] + list(emb_acts), axis=1)
      return jnp.squeeze(nn.Dense(1)(x), -1)

  rng = np.random.default_rng(1)
  vocab = [60, 90]
  tables = [TableConfig(v, 16, combiner="sum",
                        initializer="uniform") for v in vocab]
  b, max_hot, cap = 16, 4, 48

  def build(cats):
    plan = DistEmbeddingStrategy(tables, 1, "basic",
                                 dense_row_threshold=0)
    model = TinyModel()
    numerical = jnp.asarray(rng2.standard_normal((b, 4)), jnp.float32)
    labels = jnp.asarray(rng2.integers(0, 2, b), jnp.float32)
    rule = sgd_rule(0.5) if rulename == "sgd" else adagrad_rule(0.5)
    opt = optax.sgd(0.5)
    dummy = [jnp.zeros((2, 16), jnp.float32) for _ in vocab]
    dp = model.init(jax.random.PRNGKey(0), numerical[:2],
                    None, emb_acts=dummy)["params"]
    state = init_sparse_state_direct(plan, rule, dp, opt,
                                     jax.random.PRNGKey(1))
    step = make_sparse_train_step(model, plan, bce_loss, opt, rule, None,
                                  state, (numerical, cats, labels),
                                  donate=False)
    state, loss = step(state, numerical, cats, labels)
    from distributed_embeddings_tpu.training import unpack_sparse_state
    params, _ = unpack_sparse_state(plan, rule, state)
    return get_weights(plan, params["embeddings"]), float(loss)

  rng2 = np.random.default_rng(2)
  ragged = []
  padded = []
  for v in vocab:
    rg, _ = _make_ragged(rng, b, v, max_hot, cap)
    ragged.append(rg)
    padded.append(ragged_to_padded(rg, max_hot))

  rng2 = np.random.default_rng(2)
  w_ragged, loss_r = build(ragged)
  rng2 = np.random.default_rng(2)
  w_padded, loss_p = build(padded)
  assert abs(loss_r - loss_p) < 1e-5
  for a, b_ in zip(w_ragged, w_padded):
    np.testing.assert_allclose(a, b_, rtol=1e-4, atol=1e-5)


def test_ragged_mean_ignores_negative_ids_like_padded():
  """A negative id inside a sample's length window must be excluded from
  BOTH the sum and the mean divisor, matching the padded path's
  valid-count semantics."""
  tables = [TableConfig(12, 8, combiner="mean")]
  plan = DistEmbeddingStrategy(tables, 1, "basic", dense_row_threshold=0)
  engine = DistributedLookup(plan)
  rng = np.random.default_rng(3)
  w = rng.standard_normal((12, 8)).astype(np.float32)
  params = {k: jnp.asarray(v)
            for k, v in set_weights(plan, [w]).items()}
  # sample 0: ids [3, -1, 5] (one invalid); sample 1: [7]
  rg = RaggedIds(jnp.asarray([3, -1, 5, 7], jnp.int32),
                 jnp.asarray([0, 3, 4], jnp.int32))
  out = engine.forward(params, [rg])[0]
  want0 = (w[3] + w[5]) / 2.0  # divisor counts the 2 VALID ids, not 3
  want1 = w[7]
  np.testing.assert_allclose(np.asarray(out[0]), want0, rtol=1e-5)
  np.testing.assert_allclose(np.asarray(out[1]), want1, rtol=1e-5)
  # padded path agrees
  padded = ragged_to_padded(rg, 3)
  out_p = engine.forward(params, [padded])[0]
  np.testing.assert_allclose(np.asarray(out), np.asarray(out_p), rtol=1e-5)


def test_zero_capacity_ragged_is_handled():
  tables = [TableConfig(12, 8, combiner="sum")]
  plan = DistEmbeddingStrategy(tables, 1, "basic", dense_row_threshold=0)
  engine = DistributedLookup(plan)
  params = {k: jnp.zeros(s, jnp.float32) + 1.0
            for k, s in engine.param_shapes().items()}
  rg = RaggedIds(jnp.zeros((0,), jnp.int32), jnp.zeros((3,), jnp.int32))
  out = engine.forward(params, [rg])[0]
  np.testing.assert_allclose(np.asarray(out), 0.0)


def test_ragged_rejects_unsupported_combos():
  tables = [TableConfig(50, 16)]  # combiner None
  plan = DistEmbeddingStrategy(tables, 1, "basic", dense_row_threshold=0)
  engine = DistributedLookup(plan)
  rg = RaggedIds(jnp.asarray([1, 2, 3], jnp.int32),
                 jnp.asarray([0, 2, 3], jnp.int32))
  with pytest.raises(ValueError, match="combiner"):
    engine.forward({k: jnp.zeros(s, jnp.float32)
                    for k, s in engine.param_shapes().items()}, [rg])

  small = [TableConfig(10, 16, combiner="sum")]
  plan2 = DistEmbeddingStrategy(small, 1, "basic", dense_row_threshold=2048)
  engine2 = DistributedLookup(plan2)
  with pytest.raises(NotImplementedError, match="dense-class"):
    engine2.forward({k: jnp.zeros(s, jnp.float32)
                     for k, s in engine2.param_shapes().items()}, [rg])


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_ragged_row_sliced_matches_padded(combiner):
  """Ragged value-stream inputs into a ROW-SLICED table (round 3): the
  vocab-window routing must partial-sum across shards exactly like the
  padded path, with the mean division deferred to assemble."""
  rng = np.random.default_rng(3)
  # one big table forced into row slices + a few plain tables
  tables = [TableConfig(64, 16, combiner=combiner)] + \
           [TableConfig(24 + i, 16, combiner=combiner) for i in range(7)]
  plan = DistEmbeddingStrategy(tables, WORLD, "basic",
                               dense_row_threshold=0,
                               row_slice_threshold=16 * 16)
  assert any(sh.row_sliced for shards in plan.rank_shards for sh in shards)
  engine = DistributedLookup(plan)
  weights = [rng.standard_normal((c.input_dim, c.output_dim))
             .astype(np.float32) for c in tables]
  params = set_weights(plan, weights)
  params = {k: jnp.asarray(v) for k, v in params.items()}

  b_local, max_hot, cap = 4, 5, 16
  per_dev = [_make_ragged(rng, b_local, 64, max_hot, cap)
             for _ in range(WORLD)]
  ragged_blocks = [p[0] for p in per_dev]
  global_ragged = _stack_ragged(ragged_blocks)
  dense_inputs = [jnp.asarray(
      rng.integers(0, c.input_dim, (WORLD * b_local, 1)), jnp.int32)
      for c in tables[1:]]

  mesh = create_mesh(WORLD)
  from jax.sharding import NamedSharding, PartitionSpec as P
  from distributed_embeddings_tpu.compat import shard_map

  def fwd(params, rg_values, rg_splits, *dense):
    rg = RaggedIds(rg_values, rg_splits)
    return engine.forward(params, [rg] + list(dense))

  pspec = jax.tree_util.tree_map(lambda _: P("mp", None), params)
  outs = jax.jit(shard_map(
      fwd, mesh=mesh,
      in_specs=(pspec, P("mp"), P("mp")) + (P("mp"),) * len(dense_inputs),
      out_specs=P("mp")))(
          shard_params(params, mesh),
          jax.device_put(global_ragged.values,
                         NamedSharding(mesh, P("mp"))),
          jax.device_put(global_ragged.row_splits,
                         NamedSharding(mesh, P("mp"))),
          *[jax.device_put(d, NamedSharding(mesh, P("mp")))
            for d in dense_inputs])

  # single-device reference on the unsliced table
  want_blocks = [np.asarray(
      embedding_lookup(jnp.asarray(weights[0]), rg, combiner=combiner))
      for rg in ragged_blocks]
  np.testing.assert_allclose(np.asarray(outs[0]),
                             np.concatenate(want_blocks),
                             rtol=1e-5, atol=1e-5)

  # padded-path parity
  padded = jnp.concatenate(
      [ragged_to_padded(rg, max_hot) for rg in ragged_blocks])

  def fwd_padded(params, x0, *dense):
    return engine.forward(params, [x0] + list(dense))

  outs_p = jax.jit(shard_map(
      fwd_padded, mesh=mesh,
      in_specs=(pspec, P("mp")) + (P("mp"),) * len(dense_inputs),
      out_specs=P("mp")))(
          shard_params(params, mesh),
          jax.device_put(padded, NamedSharding(mesh, P("mp"))),
          *[jax.device_put(d, NamedSharding(mesh, P("mp")))
            for d in dense_inputs])
  np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(outs_p[0]),
                             rtol=1e-5, atol=1e-5)


def test_ragged_into_small_table_demoted_to_sparse():
  """A small-vocab table that would ride the MXU one-hot path is demoted
  to the sparse path when its input is declared ragged (negative
  input_hotness), and the lookup matches the single-device op."""
  rng = np.random.default_rng(4)
  tables = [TableConfig(40, 16, combiner="sum")] + \
           [TableConfig(30 + i, 16, combiner="sum") for i in range(7)]
  # without the hint, vocab 40 <= threshold 2048 would be dense
  plan = DistEmbeddingStrategy(tables, WORLD, "basic",
                               dense_row_threshold=2048,
                               input_hotness=[-5] + [1] * 7)
  kinds = {plan.classes[k].kind for k in plan.class_keys
           if any(s.shard.table_id == 0
                  for slots in plan.classes[k].slots_per_rank
                  for s in slots)}
  assert kinds == {"sparse"}, kinds
  engine = DistributedLookup(plan)
  weights = [rng.standard_normal((c.input_dim, c.output_dim))
             .astype(np.float32) for c in tables]
  params = set_weights(plan, weights)
  params = {k: jnp.asarray(v) for k, v in params.items()}

  b_local, max_hot, cap = 4, 5, 16
  per_dev = [_make_ragged(rng, b_local, 40, max_hot, cap)
             for _ in range(WORLD)]
  global_ragged = _stack_ragged([p[0] for p in per_dev])
  dense_inputs = [jnp.asarray(
      rng.integers(0, c.input_dim, (WORLD * b_local, 1)), jnp.int32)
      for c in tables[1:]]

  mesh = create_mesh(WORLD)
  from jax.sharding import NamedSharding, PartitionSpec as P
  from distributed_embeddings_tpu.compat import shard_map

  def fwd(params, rg_values, rg_splits, *dense):
    rg = RaggedIds(rg_values, rg_splits)
    return engine.forward(params, [rg] + list(dense))

  pspec = jax.tree_util.tree_map(lambda _: P("mp", None), params)
  outs = jax.jit(shard_map(
      fwd, mesh=mesh,
      in_specs=(pspec, P("mp"), P("mp")) + (P("mp"),) * len(dense_inputs),
      out_specs=P("mp")))(
          shard_params(params, mesh),
          jax.device_put(global_ragged.values,
                         NamedSharding(mesh, P("mp"))),
          jax.device_put(global_ragged.row_splits,
                         NamedSharding(mesh, P("mp"))),
          *[jax.device_put(d, NamedSharding(mesh, P("mp")))
            for d in dense_inputs])
  want = np.concatenate([np.asarray(
      embedding_lookup(jnp.asarray(weights[0]), p[0], combiner="sum"))
      for p in per_dev])
  np.testing.assert_allclose(np.asarray(outs[0]), want, rtol=1e-5,
                             atol=1e-5)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_fused_training_ragged_row_sliced_matches_padded(combiner):
  """Fused train step with ragged cats into a ROW-SLICED table must update
  the table exactly like the padded-equivalent step (round 3: value-stream
  routing through vocab windows, mean division in assemble, apply skips
  the double division)."""
  from distributed_embeddings_tpu.models import bce_loss
  from distributed_embeddings_tpu.training import (
      init_sparse_state_direct, make_sparse_train_step, shard_batch,
      unpack_sparse_state)
  import flax.linen as nn

  class TinyModel(nn.Module):
    @nn.compact
    def __call__(self, numerical, cats, emb_acts=None):
      x = jnp.concatenate([numerical] + list(emb_acts), axis=1)
      return jnp.squeeze(nn.Dense(1)(x), -1)

  rng = np.random.default_rng(5)
  # 8 tables so every rank owns one; table 0 row-slices across ranks
  tables = [TableConfig(64, 16, combiner=combiner,
                        initializer="uniform")] + \
           [TableConfig(24 + i, 16, combiner=combiner,
                        initializer="uniform") for i in range(7)]
  world, b_local, max_hot, cap = WORLD, 2, 4, 8
  b = world * b_local

  per_dev = [_make_ragged(rng, b_local, 64, max_hot, cap)
             for _ in range(world)]
  global_ragged = _stack_ragged([p[0] for p in per_dev])
  padded = jnp.concatenate(
      [ragged_to_padded(p[0], max_hot) for p in per_dev])
  dense_cats = [jnp.asarray(rng.integers(0, c.input_dim, (b, 1)), jnp.int32)
                for c in tables[1:]]

  def build(cats):
    plan = DistEmbeddingStrategy(tables, world, "basic",
                                 dense_row_threshold=0,
                                 row_slice_threshold=16 * 16)
    assert any(sh.row_sliced for shards in plan.rank_shards
               for sh in shards)
    model = TinyModel()
    rng2 = np.random.default_rng(6)
    numerical = jnp.asarray(rng2.standard_normal((b, 4)), jnp.float32)
    labels = jnp.asarray(rng2.integers(0, 2, b), jnp.float32)
    rule = sgd_rule(0.5)
    opt = optax.sgd(0.5)
    dummy = [jnp.zeros((2, 16), jnp.float32) for _ in tables]
    dp = model.init(jax.random.PRNGKey(0), numerical[:2],
                    None, emb_acts=dummy)["params"]
    mesh = create_mesh(world)
    state = init_sparse_state_direct(plan, rule, dp, opt,
                                     jax.random.PRNGKey(1))
    step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                  state, (numerical, tuple(cats), labels),
                                  donate=False)
    batch = shard_batch((numerical, tuple(cats), labels), mesh)
    from distributed_embeddings_tpu.training import (
        hybrid_partition_specs)
    from jax.sharding import NamedSharding
    sspec = hybrid_partition_specs(state, "mp")
    state_sh = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state, sspec)
    state_sh, loss = step(state_sh, *batch)
    params, _ = unpack_sparse_state(plan, rule, state_sh)
    return get_weights(plan, params["embeddings"]), float(loss)

  w_r, loss_r = build([global_ragged] + dense_cats)
  w_p, loss_p = build([padded] + dense_cats)
  assert abs(loss_r - loss_p) < 1e-5
  for a, b_ in zip(w_r, w_p):
    np.testing.assert_allclose(a, b_, rtol=1e-4, atol=1e-5)
