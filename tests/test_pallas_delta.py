"""Parity for the Pallas delta-build kernel (`ops/pallas_delta.py`).

Two layers: (1) every rule's ``delta_lanes`` twin computes exactly what
``delta`` computes; (2) the kernel (interpret mode — no aliasing/RMW, so
interpret is valid) reproduces the engine's XLA delta chain (hotness
broadcast + aux extraction + rule math + window expansion) bit-for-bit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_embeddings_tpu.ops.packed_table import (
    PackedLayout,
    sparse_rule,
)
from distributed_embeddings_tpu.ops.pallas_delta import build_delta_rows


@pytest.mark.parametrize("name", ["adagrad", "momentum", "adam"])
def test_delta_lanes_matches_delta(name):
  rule = sparse_rule(name, 0.07)
  rng = np.random.default_rng(0)
  g = jnp.asarray(rng.standard_normal((64, 16)), jnp.float32)
  aux = jnp.asarray(rng.random((64, rule.n_aux, 16)) + 0.01, jnp.float32)
  step = jnp.asarray(3, jnp.int32)
  want = rule.delta(g, aux, step)
  parts = rule.delta_lanes(g, [aux[:, a, :] for a in range(rule.n_aux)],
                           step)
  got = jnp.concatenate(parts, axis=-1)
  np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def _xla_chain(layout, rule, dz, sub, aux, h, step):
  """The engine's XLA delta path, restated (broadcast + aux lanes +
  delta + one-hot window expansion), producing [n, phys] rows."""
  w = layout.width
  k = dz.shape[0]
  n = k * h
  g = jnp.broadcast_to(dz[:, None, :], (k, h, w)).reshape(n, w)
  if rule.n_aux:
    last = aux.shape[-1]
    flat = aux.reshape(-1, last)
    if last == layout.stride:
      lanes = flat[:, w:]
    else:
      lanes = None
      for s in range(layout.rows_per_phys):
        part = flat[:, s * layout.stride + w:(s + 1) * layout.stride]
        lanes = part if lanes is None else lanes + part
    aux_r = lanes.reshape(-1, rule.n_aux, w)
  else:
    aux_r = None
  delta = rule.delta(g, aux_r, step)  # [n, stride]
  rpp = layout.rows_per_phys
  oh = jax.nn.one_hot(sub, rpp, dtype=delta.dtype)
  upd = jnp.einsum("ns,nr->nrs", delta, oh).reshape(n, rpp * layout.stride)
  pad = layout.phys_width - rpp * layout.stride
  if pad:
    upd = jnp.concatenate([upd, jnp.zeros((n, pad), upd.dtype)], axis=1)
  return upd


@pytest.mark.parametrize("name,w,n_aux_aux_last", [
    ("adagrad", 16, "stride"),   # w16+acc: stride 32, rpp 4
    ("adagrad", 16, "phys"),     # masked-phys residual layout
    ("adagrad", 8, "stride"),    # stride 16, rpp 8
    ("momentum", 16, "stride"),
    ("adam", 16, "stride"),      # stride 48 -> rpp 2, lane pad 32
    ("adagrad", 64, "stride"),   # stride 128, rpp 1
])
@pytest.mark.parametrize("h", [1, 5])
def test_kernel_matches_xla_chain(name, w, n_aux_aux_last, h):
  rule = sparse_rule(name, 0.03)
  layout = PackedLayout(rows=1000, width=w, n_aux=rule.n_aux)
  if layout.phys_width != 128:
    pytest.skip("kernel serves 128-lane layouts")
  rng = np.random.default_rng(1)
  k = 64
  n = k * h
  dz = jnp.asarray(rng.standard_normal((k, w)), jnp.float32)
  sub = jnp.asarray(rng.integers(0, layout.rows_per_phys, n), jnp.int32)
  last = layout.stride if n_aux_aux_last == "stride" else layout.phys_width
  aux = jnp.asarray(rng.random((n, last)) + 0.01, jnp.float32)
  if last == layout.phys_width:
    # masked-phys: zero all but one window per occurrence (the layout's
    # invariant the window-sum extraction relies on)
    rpp = layout.rows_per_phys
    mask = np.zeros((n, last), np.float32)
    win = rng.integers(0, rpp, n)
    for i in range(n):
      mask[i, win[i] * layout.stride:(win[i] + 1) * layout.stride] = 1.0
    aux = aux * jnp.asarray(mask)
  step = jnp.asarray(2, jnp.int32)

  got = build_delta_rows(layout, rule, dz, sub, aux, h, step,
                         interpret=True)
  want = _xla_chain(layout, rule, dz, sub, aux, h, step)
  # 1-ulp differences only: interpret-mode fuses the rsqrt chains
  # differently than the XLA form (and 0.0 vs -0.0 under the where)
  np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                             rtol=1e-6, atol=2e-7)
