"""Static-analysis suite: AST lint rules + trace-time jaxpr audit.

Pins the three contracts `make lint` rests on:

- every GL1xx rule FIRES on a seeded violation and is SILENCED by a
  ``# graftlint: disable=...`` suppression;
- the repo at HEAD is clean (so lint failures always mean a regression,
  never noise);
- the jaxpr audit proves the structural invariants on the REAL step
  builders — exactly one scatter-add per fused class (sparse and
  tiered), guard ``pmin`` present iff guarded, eval writes nothing —
  and its fingerprints are stable across traces and match the committed
  baseline in ``tests/data/``.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_embeddings_tpu.analysis import astlint
from distributed_embeddings_tpu.analysis import jaxpr_audit
from distributed_embeddings_tpu.analysis.astlint import (
    LintContext,
    lint_paths,
    lint_source,
)
from distributed_embeddings_tpu.analysis.jaxpr_audit import (
    Expectation,
    audit_summary,
    diff_fingerprints,
    fingerprint,
    summarize,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CTX = LintContext(registered_markers=frozenset({"slow"}),
                  fault_sites=frozenset({"ckpt_write", "host_gather"}))


def _rules(findings):
  return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# AST rules: seeded violations fire; suppressions silence
# ---------------------------------------------------------------------------


def test_gl101_host_sync_in_step_builder():
  src = """
def make_train_step(opt):
  def local_step(state, batch):
    loss = jax.device_get(state)
    state.block_until_ready()
    return loss
  return local_step
"""
  assert _rules(lint_source(src, "m.py", CTX, ["GL101"])) == [
      "GL101", "GL101"]


def test_gl101_ignores_host_side_code():
  src = """
def trainer_loop(step, state):
  return jax.device_get(step(state))

def make_train_step(opt):
  setup = jax.device_get(opt)  # builder body itself runs at build time?
  def local_step(state):
    return state
  return local_step
"""
  # only functions NESTED in a builder are traced scope; the trainer
  # and the builder's own top-level body are host-side
  findings = lint_source(src, "m.py", CTX, ["GL101"])
  assert findings == []


def test_gl101_suppression():
  src = """
def make_eval_step(opt):
  def local_eval(state):
    return jax.device_get(state)  # graftlint: disable=GL101
  return local_eval
"""
  assert lint_source(src, "m.py", CTX, ["GL101"]) == []


def test_gl102_numpy_in_traced_scope():
  src = """
def make_sparse_train_step(plan):
  def body(carry, mb):
    return np.asarray(carry), None
  return body
"""
  assert _rules(lint_source(src, "m.py", CTX, ["GL102"])) == ["GL102"]
  ok = """
def build_plan(plan):
  return np.zeros((4, 4))
"""
  assert lint_source(ok, "m.py", CTX, ["GL102"]) == []


def test_gl103_bare_except():
  src = """
def load(path):
  try:
    return open(path)
  except:
    return None
"""
  assert _rules(lint_source(src, "m.py", CTX, ["GL103"])) == ["GL103"]
  assert lint_source(src.replace("except:", "except OSError:"),
                     "m.py", CTX, ["GL103"]) == []


def test_gl104_unfsynced_rename_in_durable_module():
  bad = """
import os
def publish(tmp, live):
  with open(tmp, 'w') as f:
    f.write('data')
  os.rename(tmp, live)
"""
  assert _rules(lint_source(bad, "checkpoint.py", CTX, ["GL104"])) == [
      "GL104"]
  # same code outside a durable module: out of scope
  assert lint_source(bad, "loader.py", CTX, ["GL104"]) == []
  good = """
import os
def publish(tmp, live):
  with open(tmp, 'w') as f:
    f.write('data')
    os.fsync(f.fileno())
  os.rename(tmp, live)
"""
  assert lint_source(good, "checkpoint.py", CTX, ["GL104"]) == []


def test_gl105_wallclock_in_durable_module():
  src = """
import time
def build_manifest(files):
  return {"written_at": time.time(), "files": files}
"""
  assert _rules(lint_source(src, "durable.py", CTX, ["GL105"])) == [
      "GL105"]
  assert lint_source(src, "trainer.py", CTX, ["GL105"]) == []


def test_gl106_int32_narrowing():
  bad = """
def row_offset(rank, rows):
  return np.int32(rank * rows)
"""
  assert _rules(lint_source(bad, "m.py", CTX, ["GL106"])) == ["GL106"]
  # astype flavor, through a value-propagating call
  bad2 = """
def starts(n, cp, pr):
  return np.minimum(np.arange(n) * cp, pr - cp).astype(np.int32)
"""
  assert _rules(lint_source(bad2, "m.py", CTX, ["GL106"])) == ["GL106"]
  # a narrowed VALUE (no arithmetic) and the varying-zero idiom are fine
  ok = """
def f(ids, carry):
  a = ids.astype(jnp.int32)
  b = (carry * 0).astype(jnp.int32)
  c = jnp.asarray(rng.integers(0, rows + 2, 16), jnp.int32)
  return a, b, c
"""
  assert lint_source(ok, "m.py", CTX, ["GL106"]) == []
  sup = """
def row_offset(rank, rows):
  return np.int32(rank * rows)  # graftlint: disable=GL106
"""
  assert lint_source(sup, "m.py", CTX, ["GL106"]) == []


def test_gl107_unregistered_marker():
  src = """
import pytest
@pytest.mark.sloow
def test_x():
  pass
"""
  assert _rules(lint_source(src, "test_m.py", CTX, ["GL107"])) == ["GL107"]
  assert lint_source(src.replace("sloow", "slow"), "test_m.py", CTX,
                     ["GL107"]) == []
  # builtin marks are always registered
  assert lint_source(src.replace("sloow", "parametrize"), "test_m.py",
                     CTX, ["GL107"]) == []


def test_gl109_raw_all_to_all_in_step_builder():
  src = """
def make_sparse_train_step(plan):
  def local_step(state, batch):
    y = lax.all_to_all(batch, "mp", split_axis=0, concat_axis=0)
    return y
  return local_step
"""
  out = lint_source(src, "m.py", CTX, ["GL109"])
  assert _rules(out) == ["GL109"]
  assert "wire module" in out[0].message
  # the sanctioned wire module itself is exempt — by its REAL path only
  # (an unrelated wire.py elsewhere gets no blanket pass)
  wire_path = "distributed_embeddings_tpu/parallel/wire.py"
  assert lint_source(src, wire_path, CTX, ["GL109"]) == []
  assert _rules(lint_source(src, "serving/wire.py", CTX,
                            ["GL109"])) == ["GL109"]
  # host-side (non-step-builder) code outside the library is out of
  # scope... but INSIDE the library package every function is covered —
  # the engine's methods are where the real exchanges live
  host = """
def pack_inputs(x):
  return lax.all_to_all(x, "mp", split_axis=0, concat_axis=0)
"""
  assert lint_source(host, "m.py", CTX, ["GL109"]) == []
  assert _rules(lint_source(
      host, "distributed_embeddings_tpu/parallel/lookup_engine.py", CTX,
      ["GL109"])) == ["GL109"]


def test_gl109_suppression():
  src = """
def make_eval_step(plan):
  def local_eval(state, batch):
    return lax.all_to_all(batch, "mp", split_axis=0, concat_axis=0)  # graftlint: disable=GL109
  return local_eval
"""
  assert lint_source(src, "m.py", CTX, ["GL109"]) == []


def test_gl109_raw_ppermute_in_step_builder():
  """The round-7 extension: ppermute joined the guarded exchange set —
  a raw round in step code bypasses the wire knobs and the audit's
  (world-1) x chunks pins exactly like a raw all_to_all."""
  src = """
def make_sparse_train_step(plan):
  def local_step(state, batch):
    return lax.ppermute(batch, "mp", [(0, 1), (1, 0)])
  return local_step
"""
  out = lint_source(src, "m.py", CTX, ["GL109"])
  assert _rules(out) == ["GL109"]
  assert "ppermute" in out[0].message
  # the sanctioned wire module stays exempt; library modules covered
  wire_path = "distributed_embeddings_tpu/parallel/wire.py"
  assert lint_source(src, wire_path, CTX, ["GL109"]) == []
  host = """
def shuffle(x):
  return lax.ppermute(x, "mp", [(0, 1), (1, 0)])
"""
  assert lint_source(host, "m.py", CTX, ["GL109"]) == []
  assert _rules(lint_source(
      host, "distributed_embeddings_tpu/parallel/lookup_engine.py", CTX,
      ["GL109"])) == ["GL109"]
  # suppression works for the ppermute form too
  sup = """
def make_eval_step(plan):
  def local_eval(state, batch):
    return lax.ppermute(batch, "mp", [(0, 1)])  # graftlint: disable=GL109
  return local_eval
"""
  assert lint_source(sup, "m.py", CTX, ["GL109"]) == []


def test_gl108_unknown_fault_site():
  src = """
def chaos(inj):
  inj.crash_after("ckpt_writ", 3)
  fire("host_gather", rank=0)
"""
  out = lint_source(src, "test_m.py", CTX, ["GL108"])
  assert _rules(out) == ["GL108"]
  assert "ckpt_writ" in out[0].message
  assert lint_source(src.replace("ckpt_writ", "ckpt_write"), "test_m.py",
                     CTX, ["GL108"]) == []


def test_gl110_world_constant_in_durable_module():
  bad = """
import jax
def pick():
  if jax.process_count() == 4:
    return "the benchmark pod"
  if 2 < jax.process_index():
    return "tail"
"""
  out = lint_source(bad, "checkpoint.py", CTX, ["GL110"])
  assert _rules(out) == ["GL110", "GL110"]
  assert "hardcoded constant 4" in out[0].message
  # the world-shape-free idioms stay legal: controller check, multi-
  # controller check, and world facts derived from the plan
  ok = """
import jax
def pick(plan):
  if jax.process_index() == 0:
    pass
  if jax.process_count() > 1:
    pass
  if jax.process_count() == plan.world_size:
    pass
"""
  assert lint_source(ok, "durable.py", CTX, ["GL110"]) == []
  # scope: durable modules only; trainers may pin worlds for tests
  assert lint_source(bad, "trainer.py", CTX, ["GL110"]) == []


def test_gl110_suppression():
  src = """
import jax
def f():
  return jax.process_count() == 4  # graftlint: disable=GL110
"""
  assert lint_source(src, "checkpoint.py", CTX, ["GL110"]) == []


SERVING_PATH = "distributed_embeddings_tpu/serving/engine.py"


def test_gl111_optax_import_in_serving_module():
  src = """
import optax
def f(params, grads):
  return optax.apply_updates(params, grads)
"""
  out = lint_source(src, SERVING_PATH, CTX, ["GL111"])
  assert _rules(out) and all(r == "GL111" for r in _rules(out))
  assert "optax" in out[0].message
  # outside serving/, optax is business as usual
  assert lint_source(src, "distributed_embeddings_tpu/training.py", CTX,
                     ["GL111"]) == []


def test_gl111_guards_and_builders_in_serving_module():
  src = """
from distributed_embeddings_tpu.resilience import guards
from distributed_embeddings_tpu.training import make_sparse_train_step
"""
  out = lint_source(src, SERVING_PATH, CTX, ["GL111"])
  assert len(out) == 2 and set(_rules(out)) == {"GL111"}
  # references by name fire too (a scatter emitter smuggled via alias)
  ref = """
def serve(engine, state, layouts, dz, residuals, rule, step):
  return engine.apply_sparse(state, layouts, dz, residuals, rule, step)
"""
  out = lint_source(ref, SERVING_PATH, CTX, ["GL111"])
  assert _rules(out) == ["GL111"]
  assert "apply_sparse" in out[0].message
  # the same reference is fine outside serving/
  assert lint_source(ref, "distributed_embeddings_tpu/tiering/train.py",
                     CTX, ["GL111"]) == []


def test_gl111_allows_serving_legitimate_imports():
  # the export path rides the durable checkpoint machinery — its
  # faultinject sites and the lookup-engine surfaces are NOT train-only
  src = """
from distributed_embeddings_tpu.resilience import faultinject
from distributed_embeddings_tpu.parallel.lookup_engine import (
    DistributedLookup,
    class_param_name,
)
def f(plan):
  return DistributedLookup(plan)
"""
  assert lint_source(src, SERVING_PATH, CTX, ["GL111"]) == []


def test_gl111_suppression():
  src = """
import optax  # graftlint: disable=GL111
"""
  assert lint_source(src, SERVING_PATH, CTX, ["GL111"]) == []


FLEET_PATH = "distributed_embeddings_tpu/fleet/router.py"


def test_gl114_train_surfaces_in_fleet_module():
  """The fleet tier is the serving engine spread over processes — the
  same inference-only contract (GL111) at fleet scope."""
  src = """
import optax
def f(params, grads):
  return optax.apply_updates(params, grads)
"""
  out = lint_source(src, FLEET_PATH, CTX, ["GL114"])
  assert _rules(out) and all(r == "GL114" for r in _rules(out))
  assert "optax" in out[0].message
  # outside fleet/, optax is business as usual (and GL111 owns serving/)
  assert lint_source(src, "distributed_embeddings_tpu/training.py", CTX,
                     ["GL114"]) == []
  assert lint_source(src, SERVING_PATH, CTX, ["GL114"]) == []
  # guard/builder imports and by-name references fire too
  imp = """
from distributed_embeddings_tpu.resilience import guards
from distributed_embeddings_tpu.training import make_sparse_train_step
"""
  out = lint_source(imp, FLEET_PATH, CTX, ["GL114"])
  assert len(out) == 2 and set(_rules(out)) == {"GL114"}
  ref = """
def serve(engine, state, layouts, dz, residuals, rule, step):
  return engine.apply_sparse(state, layouts, dz, residuals, rule, step)
"""
  out = lint_source(ref, FLEET_PATH, CTX, ["GL114"])
  assert _rules(out) == ["GL114"]
  assert "apply_sparse" in out[0].message


def test_gl114_allows_fleet_legitimate_imports_and_suppression():
  # the fleet rides retry/faultinject and the serving engine by design
  src = """
from distributed_embeddings_tpu.resilience import faultinject, retry
from distributed_embeddings_tpu.serving.engine import ServeEngine
from distributed_embeddings_tpu.parallel.lookup_engine import (
    class_param_name,
)
"""
  assert lint_source(src, FLEET_PATH, CTX, ["GL114"]) == []
  sup = """
import optax  # graftlint: disable=GL114
"""
  assert lint_source(sup, FLEET_PATH, CTX, ["GL114"]) == []


def test_gl112_translator_call_in_step_builder():
  """The dynamic-vocab invariant: translation-state mutation lives in
  dynvocab/ host paths — a translator call inside a trace-reachable
  step closure would break tracing or freeze one translation into the
  compiled step."""
  src = """
def make_sparse_train_step(plan, translator):
  def local_step(state, cats, labels):
    cats, _, _ = translator.translate_batch(cats)
    return state
  return local_step
"""
  out = lint_source(src, "m.py", CTX, ["GL112"])
  assert _rules(out) == ["GL112"]
  assert "host state" in out[0].message
  # the dynvocab package itself is the sanctioned home
  assert lint_source(
      src, "distributed_embeddings_tpu/dynvocab/trainer.py", CTX,
      ["GL112"]) == []
  # host-side code (trainers, tools, tests) is unrestricted: the hook
  # itself lives OUTSIDE any step builder
  host = """
def drive(engine, translator, cats):
  return engine.translate_dynamic_ids(cats, translator)
"""
  assert lint_source(host, "m.py", CTX, ["GL112"]) == []
  assert lint_source(
      host, "distributed_embeddings_tpu/parallel/lookup_engine.py", CTX,
      ["GL112"]) == []


def test_gl112_constructors_and_suppression():
  src = """
def make_eval_step(plan):
  def local_eval(state, cats):
    table = IdTranslationTable(100)
    return state
  return local_eval
"""
  assert _rules(lint_source(src, "m.py", CTX, ["GL112"])) == ["GL112"]
  sup = """
def make_eval_step(plan):
  def local_eval(state, cats):
    table = IdTranslationTable(100)  # graftlint: disable=GL112
    return state
  return local_eval
"""
  assert lint_source(sup, "m.py", CTX, ["GL112"]) == []


def test_gl113_raw_timing_in_library_module():
  """Raw perf_counter/monotonic timing in a library module: spans (or
  the telemetry histogram type) are the sanctioned form — one trace,
  one registry, instead of ~30 hand-rolled timing loops."""
  src = """
import time

def stage(store):
  t0 = time.perf_counter()
  store.gather()
  return time.perf_counter() - t0

def deadline():
  return time.monotonic() + 30.0
"""
  out = lint_source(src, "distributed_embeddings_tpu/tiering/prefetch.py",
                    CTX, ["GL113"])
  assert _rules(out) == ["GL113", "GL113", "GL113"]
  assert "telemetry.span" in out[0].message


def test_gl113_from_import_and_alias_forms():
  """A from-import (or module alias) must not be a bypass: the rule
  tracks `from time import perf_counter [as pc]` and `import time as
  t` and flags the bare-name calls the same way."""
  src = """
from time import perf_counter as pc
import time as clk

def stage():
  t0 = pc()
  return clk.monotonic() - t0
"""
  out = lint_source(src, "distributed_embeddings_tpu/tiering/store.py",
                    CTX, ["GL113"])
  assert _rules(out) == ["GL113", "GL113"]
  assert "perf_counter" in out[0].message
  # an unrelated bare name is not flagged
  ok = """
def stage(perf_counter_like):
  return perf_counter_like()
"""
  assert lint_source(ok, "distributed_embeddings_tpu/tiering/store.py",
                     CTX, ["GL113"]) == []


def test_gl113_scope_and_suppression():
  src = """
import time

def stage():
  return time.perf_counter()
"""
  # telemetry/ is the sanctioned home of the clock reads themselves
  assert lint_source(
      src, "distributed_embeddings_tpu/telemetry/trace.py", CTX,
      ["GL113"]) == []
  # tools/tests drive their own harnesses — library-package scope only
  assert lint_source(src, "tools/profile_thing.py", CTX, ["GL113"]) == []
  assert lint_source(src, "tests/test_thing.py", CTX, ["GL113"]) == []
  # non-timing uses of the time module stay legal
  ok = """
import time

def backoff():
  time.sleep(0.1)
"""
  assert lint_source(
      ok, "distributed_embeddings_tpu/resilience/retry.py", CTX,
      ["GL113"]) == []
  sup = """
import time

def deadline():
  return time.monotonic() + 30.0  # graftlint: disable=GL113 (deadline)
"""
  assert lint_source(
      sup, "distributed_embeddings_tpu/checkpoint.py", CTX,
      ["GL113"]) == []


def test_gl115_raw_minting_in_request_path_packages():
  """Raw uuid/epoch minting in serving/fleet/streaming: ids minted
  outside telemetry never land on one trace, and a second clock-epoch
  source cannot be correlated into the merged timeline."""
  src = """
import os
import time
import uuid

def subscriber_id():
  return uuid.uuid4().hex[:8]

def epoch():
  return time.time_ns()

def token():
  return os.urandom(8).hex()
"""
  for path in ("distributed_embeddings_tpu/streaming/subscribe.py",
               "distributed_embeddings_tpu/fleet/stream.py",
               "distributed_embeddings_tpu/serving/batcher.py"):
    out = lint_source(src, path, CTX, ["GL115"])
    assert _rules(out) == ["GL115", "GL115", "GL115"], path
    assert "mint_id" in out[0].message


def test_gl115_from_import_and_alias_forms():
  src = """
from uuid import uuid4 as u4
from time import time_ns

def mint():
  return u4().hex, time_ns()
"""
  out = lint_source(src, "distributed_embeddings_tpu/fleet/router.py",
                    CTX, ["GL115"])
  assert _rules(out) == ["GL115", "GL115"]
  # a module alias is not a bypass either
  aliased = """
import uuid as u
import time as clk

def mint():
  return u.uuid4().hex, clk.time_ns()
"""
  out = lint_source(aliased,
                    "distributed_embeddings_tpu/fleet/router.py",
                    CTX, ["GL115"])
  assert _rules(out) == ["GL115", "GL115"]


def test_gl115_scope_and_suppression():
  src = """
import uuid

def mint():
  return uuid.uuid4().hex
"""
  # telemetry/ is the sanctioned mint; trainers/tools/tests mint freely
  for path in ("distributed_embeddings_tpu/telemetry/trace.py",
               "distributed_embeddings_tpu/resilience/trainer.py",
               "distributed_embeddings_tpu/dynvocab/table.py",
               "tools/profile_fleet.py", "tests/test_fleet.py"):
    assert lint_source(src, path, CTX, ["GL115"]) == [], path
  # non-minting uses of the modules stay legal (time.time wall anchors)
  ok = """
import time

def anchor():
  return time.time()
"""
  assert lint_source(ok, "distributed_embeddings_tpu/streaming/publish.py",
                     CTX, ["GL115"]) == []
  sup = """
import uuid

def legacy():
  return uuid.uuid4().hex  # graftlint: disable=GL115 (external id)
"""
  assert lint_source(sup, "distributed_embeddings_tpu/fleet/stream.py",
                     CTX, ["GL115"]) == []


def test_gl116_flags_raw_signaling_in_library_modules():
  src = """
import os
import signal

def hook():
  signal.signal(signal.SIGTERM, lambda s, f: None)

def reap(pid):
  os.kill(pid, 9)
  os.killpg(pid, 15)
"""
  for path in ("distributed_embeddings_tpu/serving/batcher.py",
               "distributed_embeddings_tpu/training.py",
               "distributed_embeddings_tpu/tiering/prefetch.py"):
    out = lint_source(src, path, CTX, ["GL116"])
    assert _rules(out) == ["GL116", "GL116", "GL116"], path
    assert "resilience" in out[0].message


def test_gl116_from_import_and_alias_forms():
  src = """
from signal import signal as sig
from os import kill

def hook():
  sig(15, None)
  kill(123, 0)
"""
  out = lint_source(src, "distributed_embeddings_tpu/fleet/owner.py",
                    CTX, ["GL116"])
  assert _rules(out) == ["GL116", "GL116"]
  aliased = """
import signal as sg
import os as o

def hook():
  sg.signal(15, None)
  o.kill(123, 9)
"""
  out = lint_source(aliased, "distributed_embeddings_tpu/fleet/owner.py",
                    CTX, ["GL116"])
  assert _rules(out) == ["GL116", "GL116"]


def test_gl116_scope_and_suppression():
  src = """
import os
import signal

def hook():
  signal.signal(signal.SIGTERM, lambda s, f: None)
  os.kill(os.getpid(), 0)
"""
  # resilience/ is the sanctioned home (the drain path, chaos kill_at,
  # membership probes); tools and tests drive their own processes
  for path in ("distributed_embeddings_tpu/resilience/trainer.py",
               "distributed_embeddings_tpu/resilience/elastic.py",
               "distributed_embeddings_tpu/resilience/faultinject.py",
               "tools/chaos_preempt.py", "tests/test_preempt.py"):
    assert lint_source(src, path, CTX, ["GL116"]) == [], path
  # non-signaling uses of the modules stay legal
  ok = """
import os
import signal

def fine():
  return os.getpid(), signal.getsignal(signal.SIGTERM)
"""
  assert lint_source(ok, "distributed_embeddings_tpu/serving/engine.py",
                     CTX, ["GL116"]) == []
  sup = """
import os

def probe(pid):
  os.kill(pid, 0)  # graftlint: disable=GL116 (liveness probe, reviewed)
"""
  assert lint_source(sup, "distributed_embeddings_tpu/fleet/owner.py",
                     CTX, ["GL116"]) == []


# ---------------------------------------------------------------------------
# repo-context parsing + HEAD cleanliness
# ---------------------------------------------------------------------------


def test_repo_context_parses_markers_and_sites():
  ctx = LintContext.for_repo(REPO)
  assert "slow" in ctx.registered_markers
  # SITES literal members plus register_site-registered extensions
  # ("sigkill" in faultinject.py, the streaming sites in
  # streaming/publish.py|subscribe.py|compact.py, the fleet RPC site in
  # fleet/transport.py, the in-run resize site in resilience/elastic.py —
  # all registered at module level) — test files' ad-hoc registrations
  # are deliberately NOT scanned
  assert ctx.fault_sites == frozenset(
      {"ckpt_write", "ckpt_rename", "host_gather", "ckpt_owner_write",
       "reshard_gather", "sigkill", "delta_extract", "delta_seal",
       "stream_attach", "stream_read", "delta_promote", "compact_fold",
       "fleet_rpc", "resize_gather"})
  assert "test_extension_site" not in ctx.fault_sites


def test_gl108_accepts_register_site_extensions():
  """A site registered through register_site (parsed from the repo)
  lints clean; a near-miss typo of it still fails."""
  ctx = LintContext.for_repo(REPO)
  src = """
from distributed_embeddings_tpu.resilience import faultinject
def marker():
  faultinject.fire("sigkill", batch=0)
"""
  assert lint_source(src, "tools/x.py", ctx, ["GL108"]) == []
  out = lint_source(src.replace('"sigkill"', '"sigkil"'), "tools/x.py",
                    ctx, ["GL108"])
  assert _rules(out) == ["GL108"]


def test_repo_is_lint_clean_at_head():
  paths = [os.path.join(REPO, p) for p in
           ("distributed_embeddings_tpu", "tests", "tools", "examples")]
  findings = lint_paths(paths, root=REPO)
  assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_exit_codes(tmp_path):
  bad = tmp_path / "m.py"
  bad.write_text("def f():\n  try:\n    pass\n  except:\n    pass\n")
  env = {**os.environ, "JAX_PLATFORMS": "cpu"}
  r = subprocess.run(
      [sys.executable, os.path.join(REPO, "tools", "graftlint.py"),
       "--ast-only", str(bad)], env=env, capture_output=True, text=True)
  assert r.returncode == 1, r.stdout + r.stderr
  assert "GL103" in r.stdout
  r = subprocess.run(
      [sys.executable, os.path.join(REPO, "tools", "graftlint.py"),
       "--ast-only"], env=env, capture_output=True, text=True)
  assert r.returncode == 0, r.stdout + r.stderr


# ---------------------------------------------------------------------------
# jaxpr audit: structural invariants on the REAL artifacts
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def artifacts():
  return jaxpr_audit.build_artifacts()


def test_sparse_step_exactly_one_scatter_per_class(artifacts):
  for name in ("sparse_step", "sparse_step_guard", "tiered_step"):
    jaxpr, expect = artifacts[name]
    s = summarize(jaxpr)
    assert expect.class_shapes, name
    assert audit_summary(name, s, expect) == []
    # each class's local packed buffer shape receives exactly ONE scatter
    for cname, shape in expect.class_shapes.items():
      hits = [sh for sh in s.scatter_shapes if sh == tuple(shape)]
      assert len(hits) == 1, (name, cname, s.scatter_shapes)


def test_guard_pmin_present_iff_guarded(artifacts):
  s_plain = summarize(artifacts["sparse_step"][0])
  s_guard = summarize(artifacts["sparse_step_guard"][0])
  assert s_plain.counts.get("pmin", 0) == 0
  assert s_guard.counts.get("pmin", 0) == 1
  assert s_guard.counts.get("is_finite", 0) > 0


def test_eval_step_writes_nothing(artifacts):
  s = summarize(artifacts["eval_step"][0])
  assert s.scatter_shapes == []
  assert audit_summary("eval_step", s, artifacts["eval_step"][1]) == []


def test_serve_steps_write_nothing_anywhere(artifacts):
  """Round-12 pins: the serve artifacts carry ZERO scatter ops of any
  operand shape (reverse mode through a gather lowers to a scatter —
  this is the no-reverse-mode pin), zero host callbacks, and the same
  2-exchanges-per-bucket wire structure as eval."""
  nb_eval = summarize(artifacts["eval_step"][0]).counts["all_to_all"]
  for name in ("serve_step_f32", "serve_step_int8"):
    jaxpr, expect = artifacts[name]
    s = summarize(jaxpr)
    assert expect.scatter_total == 0
    assert s.scatter_shapes == [], (name, s.scatter_shapes)
    assert s.callback_prims == [], name
    assert s.counts.get("all_to_all", 0) == nb_eval, name
    assert audit_summary(name, s, expect) == []


def test_serve_int8_dequant_convert_present(artifacts):
  """The int8 artifact must really widen int8 -> f32 on device (the
  dequantize-on-gather evidence); the f32 artifact must NOT touch int8
  anywhere."""
  s8 = summarize(artifacts["serve_step_int8"][0])
  assert ("int8", "float32") in set(s8.convert_pairs)
  s32 = summarize(artifacts["serve_step_f32"][0])
  assert all("int8" not in p for pair in s32.convert_pairs for p in pair)


def test_collectives_ride_mesh_axes_only(artifacts):
  for name, (jaxpr, expect) in artifacts.items():
    s = summarize(jaxpr)
    for prim, axes in s.collective_axes:
      assert set(axes) <= set(expect.mesh_axes), (name, prim, axes)
    assert s.f64_prims == [], name
    assert s.callback_prims == [], name


def test_wire_dtype_per_mode(artifacts):
  """Round-6 wire invariants: float all_to_all payloads travel f32 on
  default plans and bf16 (every one of them) on the bf16-wire artifact;
  integer (id) payloads stay int32 everywhere."""
  for name in ("sparse_step", "sparse_step_guard", "eval_step",
               "tiered_step"):
    s = summarize(artifacts[name][0])
    floats = [d for d in s.a2a_dtypes if "float" in d]
    assert floats and set(floats) == {"float32"}, (name, s.a2a_dtypes)
  s = summarize(artifacts["sparse_step_wire"][0])
  floats = [d for d in s.a2a_dtypes if "float" in d]
  assert floats and set(floats) == {"bfloat16"}, s.a2a_dtypes
  assert all(d == "int32" for d in s.a2a_dtypes if "int" in d)


def test_all_to_all_count_per_mode(artifacts):
  """Exchange counts are pinned per mode: a train step exchanges exactly
  3x per padded bucket (ids dp->mp, activations mp->dp, the reverse
  cotangent exchange), eval 2x — and the dedup'd wire adds NO extra
  exchange (the inverse maps never cross)."""
  for name, (jaxpr, expect) in artifacts.items():
    assert expect.a2a_count is not None, name
    s = summarize(jaxpr)
    assert s.counts.get("all_to_all", 0) == expect.a2a_count, name
  n_plain = summarize(artifacts["sparse_step"][0]).counts["all_to_all"]
  n_wire = summarize(artifacts["sparse_step_wire"][0]).counts["all_to_all"]
  assert n_plain == n_wire


def test_ppermute_rounds_per_pipelined_mode(artifacts):
  """Round-7 pins: each pipelined artifact flies ZERO all_to_alls and
  exactly ``3 buckets x (world-1) x chunks`` ppermute rounds, with every
  float round payload in the mode's wire dtype (the fp8 artifact's
  blocks really are float8_e4m3 on the wire — scales ride inside them);
  monolithic artifacts fly zero ppermutes."""
  for wname, dtype in (("f32", "float32"), ("bf16", "bfloat16"),
                       ("fp8", "float8_e4m3fn")):
    name = f"sparse_step_pipe_{wname}"
    jaxpr, expect = artifacts[name]
    s = summarize(jaxpr)
    assert audit_summary(name, s, expect) == []
    assert s.counts.get("all_to_all", 0) == 0, name
    assert expect.ppermute_count and \
        s.counts.get("ppermute", 0) == expect.ppermute_count, name
    floats = [d for d in s.ppermute_dtypes if "float" in d]
    assert floats and set(floats) == {dtype}, (name, s.ppermute_dtypes)
    ints = [d for d in s.ppermute_dtypes if "int" in d]
    assert ints and set(ints) == {"int32"}, (name, s.ppermute_dtypes)
  for name in ("sparse_step", "sparse_step_guard", "sparse_step_wire",
               "eval_step", "tiered_step", "tiered_step_guard"):
    assert summarize(artifacts[name][0]).counts.get("ppermute", 0) == 0, \
        name


def test_audit_flags_ppermute_round_drift(artifacts):
  """A drifting round count (a chunk falling out of — or sneaking into
  — the schedule) must be a named violation."""
  name = "sparse_step_pipe_f32"
  jaxpr, expect = artifacts[name]
  s = summarize(jaxpr)
  import dataclasses
  bad = dataclasses.replace(expect,
                            ppermute_count=expect.ppermute_count + 3)
  out = audit_summary(name, s, bad)
  assert len(out) == 1 and "ppermute round" in out[0]


def test_audit_flags_wire_violations():
  import jax.numpy as _jnp
  from distributed_embeddings_tpu.compat import shard_map
  from distributed_embeddings_tpu.parallel import create_mesh
  from jax.sharding import PartitionSpec as P

  mesh = create_mesh(4)
  f = shard_map(
      lambda x: jax.lax.all_to_all(x, "mp", split_axis=0, concat_axis=0),
      mesh=mesh, in_specs=(P("mp"),), out_specs=P("mp"))
  jx = jax.make_jaxpr(f)(jnp.ones((16, 2), jnp.float32))
  s = summarize(jx.jaxpr)
  # f32 payload under a bf16-wire expectation
  out = audit_summary("seed", s, Expectation({}, ("mp",),
                                             wire_float_dtype="bfloat16"))
  assert len(out) == 1 and "wire_dtype contract" in out[0]
  # count drift (expected 2 exchanges, traced 1)
  out = audit_summary("seed", s, Expectation({}, ("mp",), a2a_count=2))
  assert len(out) == 1 and "all_to_all" in out[0]
  # clean under the matching expectation
  assert audit_summary("seed", s, Expectation(
      {}, ("mp",), a2a_count=1, wire_float_dtype="float32")) == []
  del _jnp


def test_fingerprints_match_committed_baseline(artifacts):
  path = os.path.join(REPO, jaxpr_audit.FINGERPRINT_PATH)
  assert os.path.exists(path), (
      "run `python tools/graftlint.py --update-fingerprints` and commit")
  with open(path) as f:
    baseline = json.load(f)
  prints = {name: fingerprint(summarize(jaxpr))
            for name, (jaxpr, _) in artifacts.items()}
  drift = diff_fingerprints(baseline, prints)
  assert drift == [], "\n".join(drift)


def test_fingerprint_stable_across_two_traces(artifacts):
  fresh = jaxpr_audit.build_artifacts()
  for name, (jaxpr, _) in artifacts.items():
    a = fingerprint(summarize(jaxpr))
    b = fingerprint(summarize(fresh[name][0]))
    assert a == b, name


# ---------------------------------------------------------------------------
# jaxpr audit: seeded violations are detected
# ---------------------------------------------------------------------------


def test_audit_flags_scatter_chain():
  def chained(buf, ids, upd):
    return buf.at[ids].add(upd).at[ids].add(upd)

  jx = jax.make_jaxpr(chained)(
      jnp.zeros((8, 4)), jnp.arange(3), jnp.ones((3, 4)))
  s = summarize(jx.jaxpr)
  out = audit_summary("seed", s, Expectation({"c": (8, 4)}, ("mp",)))
  assert len(out) == 1 and "2 scatter-adds" in out[0]


def test_audit_flags_missing_update():
  def nothing(buf):
    return buf * 2.0

  jx = jax.make_jaxpr(nothing)(jnp.zeros((8, 4)))
  out = audit_summary("seed", summarize(jx.jaxpr),
                      Expectation({"c": (8, 4)}, ("mp",)))
  assert len(out) == 1 and "0 scatter-adds" in out[0]


def test_audit_flags_missing_guard_pmin():
  def no_pmin(x):
    return x + 1

  jx = jax.make_jaxpr(no_pmin)(jnp.zeros(()))
  out = audit_summary("seed", summarize(jx.jaxpr),
                      Expectation({}, ("mp",), guard=True))
  assert len(out) == 1 and "pmin" in out[0]


def test_audit_flags_foreign_collective_axis():
  from distributed_embeddings_tpu.compat import shard_map
  from distributed_embeddings_tpu.parallel import create_mesh
  from jax.sharding import PartitionSpec as P

  mesh = create_mesh(4)
  f = shard_map(lambda x: jax.lax.psum(x, "mp"), mesh=mesh,
                in_specs=(P("mp"),), out_specs=P())
  jx = jax.make_jaxpr(f)(jnp.ones(4))
  out = audit_summary("seed", summarize(jx.jaxpr),
                      Expectation({}, ("other_axis",)))
  assert out and "unknown axis" in out[0]


def test_audit_flags_f64_leak():
  from distributed_embeddings_tpu.compat import enable_x64
  with enable_x64():
    jx = jax.make_jaxpr(lambda x: x * 2.0)(jnp.zeros((2,), jnp.float64))
  out = audit_summary("seed", summarize(jx.jaxpr), Expectation({}, ("mp",)))
  assert len(out) == 1 and "float64" in out[0]


def test_audit_flags_host_callback():
  def cb(x):
    return jax.pure_callback(
        lambda v: np.asarray(v) * 2, jax.ShapeDtypeStruct((2,), jnp.float32),
        x)

  jx = jax.make_jaxpr(cb)(jnp.ones(2, jnp.float32))
  out = audit_summary("seed", summarize(jx.jaxpr), Expectation({}, ("mp",)))
  assert len(out) == 1 and "callback" in out[0]


def test_audit_flags_serve_scatter_and_missing_dequant():
  """Seeded serve violations: ANY scatter under scatter_total=0 fires,
  and a missing int8 -> f32 convert under require_convert fires."""
  def writes(buf, ids, upd):
    return buf.at[ids].add(upd)

  jx = jax.make_jaxpr(writes)(
      jnp.zeros((8, 4)), jnp.arange(3), jnp.ones((3, 4)))
  out = audit_summary("seed", summarize(jx.jaxpr),
                      Expectation({}, ("mp",), scatter_total=0))
  assert len(out) == 1 and "forward-only" in out[0]

  def no_dequant(x):
    return x * 2.0

  jx = jax.make_jaxpr(no_dequant)(jnp.ones((4,), jnp.float32))
  out = audit_summary("seed", summarize(jx.jaxpr),
                      Expectation({}, ("mp",),
                                  require_convert=("int8", "float32")))
  assert len(out) == 1 and "dequantize-on-gather" in out[0]

  def dequants(x):
    return x.astype(jnp.float32) * 2.0

  jx = jax.make_jaxpr(dequants)(jnp.ones((4,), jnp.int8))
  out = audit_summary("seed", summarize(jx.jaxpr),
                      Expectation({}, ("mp",),
                                  require_convert=("int8", "float32")))
  assert out == []


def test_fingerprint_drift_detected():
  base = {"sparse_step": {"scatter-add": 3, "all_to_all": 9}}
  cur = {"sparse_step": {"scatter-add": 4, "all_to_all": 9}}
  out = diff_fingerprints(base, cur)
  assert len(out) == 1 and "scatter-add: 3 -> 4" in out[0]
  assert diff_fingerprints(base, dict(base)) == []
  # vanished artifact and missing baseline both report
  assert diff_fingerprints(base, {}) != []
  assert diff_fingerprints({}, cur) != []


# ---------------------------------------------------------------------------
# GL117: fleet mutation surfaces are control-plane actuations
# ---------------------------------------------------------------------------


def test_gl117_flags_mutation_surfaces_in_library_modules():
  """A data-path module that can reshard the fleet, edit the replica
  set, or fold the chain is an accidental operator — mutations route
  through control/ daemons or operator tools."""
  src = """
from distributed_embeddings_tpu.fleet import reshard

def on_pressure(router, fplan):
  router.apply_fleet(fplan)

def on_idle(compactor):
  compactor.compact_once()
"""
  out = lint_source(src, "distributed_embeddings_tpu/serving/engine.py",
                    CTX, ["GL117"])
  assert _rules(out) == ["GL117", "GL117", "GL117"]
  assert "control" in out[0].message


def test_gl117_home_packages_and_control_are_exempt():
  fleet_src = """
def set_fleet(self, fplan, transport=None):
  self.fplan = fplan

def promote(store, fplan):
  store.set_fleet(fplan)
"""
  # the home package keeps its definitions and internal plumbing
  assert lint_source(fleet_src,
                     "distributed_embeddings_tpu/fleet/router.py",
                     CTX, ["GL117"]) == []
  stream_src = """
def daemon_tick(compactor, k):
  return compactor.compact_once(through_seq=k)
"""
  assert lint_source(stream_src,
                     "distributed_embeddings_tpu/streaming/compact.py",
                     CTX, ["GL117"]) == []
  # control/ is the sanctioned caller of EVERY surface
  control_src = """
def actuate(router, fplan, compactor, k):
  router.apply_fleet(fplan)
  compactor.compact_once(through_seq=k)
  compactor.gc_deltas(k)
"""
  assert lint_source(control_src,
                     "distributed_embeddings_tpu/control/autoscaler.py",
                     CTX, ["GL117"]) == []
  # but fleet/ calling the STREAMING surfaces is still a violation —
  # the exemption is per-surface, not package-wide
  cross = """
def tidy(compactor):
  compactor.compact_once()
"""
  out = lint_source(cross, "distributed_embeddings_tpu/fleet/stream.py",
                    CTX, ["GL117"])
  assert _rules(out) == ["GL117"]


def test_gl117_scope_and_suppression():
  src = """
from distributed_embeddings_tpu.fleet import reshard

def main(path, world):
  reshard(path, world)
"""
  # operator tools and tests live outside the library package
  assert lint_source(src, "tools/fleet_reshard.py", CTX, ["GL117"]) == []
  assert lint_source(src, "tests/test_fleet.py", CTX, ["GL117"]) == []
  sup = """
def drain(router, fplan):
  router.apply_fleet(fplan)  # graftlint: disable=GL117 (drain hook, reviewed)
"""
  assert lint_source(sup, "distributed_embeddings_tpu/serving/engine.py",
                     CTX, ["GL117"]) == []
  # unrelated same-shape names stay legal
  ok = """
def apply_fleet_discount(prices):
  return [p * 0.9 for p in prices]
"""
  assert lint_source(ok, "distributed_embeddings_tpu/serving/engine.py",
                     CTX, ["GL117"]) == []


# GL118: multi-controller refusals must name a reason and be inventoried
def test_gl118_flags_uninventoried_refusal():
  src = """
import jax

def publish(path):
  if jax.process_count() > 1:
    raise NotImplementedError(
        "frobnication is a single-controller operation: run it from a "
        "restored checkpoint.")
"""
  out = lint_source(src, "distributed_embeddings_tpu/streaming/frob.py",
                    CTX, ["GL118"])
  assert _rules(out) == ["GL118"]
  assert "REFUSAL_INVENTORY" in out[0].message
  # the same refusal in an INVENTORIED file+snippet is the sanctioned form
  inv = src.replace(
      "frobnication is a single-controller operation",
      "delta publication is a single-controller operation")
  assert lint_source(inv, "distributed_embeddings_tpu/streaming/publish.py",
                     CTX, ["GL118"]) == []


def test_gl118_requires_literal_reason():
  src = """
import jax

def save(msg):
  if jax.process_count() > 1:
    raise NotImplementedError(msg)
"""
  out = lint_source(src, "distributed_embeddings_tpu/streaming/frob.py",
                    CTX, ["GL118"])
  assert _rules(out) == ["GL118"]
  assert "reason string" in out[0].message


def test_gl118_scope_and_suppression():
  src = """
import jax

def run():
  if jax.process_count() > 1:
    raise NotImplementedError("tools do their own thing")
"""
  # tools and tests live outside the library package
  assert lint_source(src, "tools/chaos_thing.py", CTX, ["GL118"]) == []
  # behavior branches (no raise) and other exception types are not refusals
  ok = """
import jax

def save():
  if jax.process_count() > 1:
    barrier()
  if jax.process_count() > 1:
    raise RuntimeError("a real error, not a refusal")
"""
  assert lint_source(ok, "distributed_embeddings_tpu/streaming/frob.py",
                     CTX, ["GL118"]) == []
  sup = """
import jax

def run():
  if jax.process_count() > 1:  # graftlint: disable=GL118 (migration shim)
    raise NotImplementedError("temporary refusal under review")
"""
  assert lint_source(sup, "distributed_embeddings_tpu/streaming/frob.py",
                     CTX, ["GL118"]) == []


# GL126: Pallas kernel calls and env gates are registered and homed
def test_gl126_kernel_call_outside_home():
  src = """
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def fancy(x):
  return pl.pallas_call(lambda r, o: None, out_shape=x)(x)

def ship(src, dst, sems):
  pltpu.make_async_remote_copy(src, dst, *sems, device_id=(1,)).start()
"""
  out = lint_source(src, "distributed_embeddings_tpu/parallel/fast.py",
                    CTX, ["GL126"])
  assert _rules(out) == ["GL126", "GL126"]
  assert "ops/pallas_" in out[0].message
  # the kernel modules themselves are the sanctioned home
  assert lint_source(src, "distributed_embeddings_tpu/ops/pallas_fast.py",
                     CTX, ["GL126"]) == []
  # tools/tests live outside the library package
  assert lint_source(src, "tools/smoke_thing.py", CTX, ["GL126"]) == []


def test_gl126_unregistered_gate_fires_registered_is_clean():
  src = """
import os

def _use_pallas_frob():
  return os.environ.get("DE_TPU_PALLAS_FROB", "0") == "1"
"""
  out = lint_source(src, "distributed_embeddings_tpu/ops/pallas_frob.py",
                    CTX, ["GL126"])
  assert _rules(out) == ["GL126"]
  assert "PALLAS_GATE_REGISTRY" in out[0].message
  # a docstring MENTIONING a gate is not a read
  doc = '''
def helper():
  """Gated by DE_TPU_PALLAS_FROB on real TPUs."""
  return 0
'''
  assert lint_source(doc, "distributed_embeddings_tpu/ops/pallas_frob.py",
                     CTX, ["GL126"]) == []
  # the registered (file, env, predicate) triple is the sanctioned form
  reg = """
import os
import jax

def _use_pallas_exchange():
  if os.environ.get("DE_TPU_PALLAS_EXCHANGE", "0") != "1":
    return False
  return jax.default_backend() == "tpu"
"""
  assert lint_source(reg, "distributed_embeddings_tpu/ops/pallas_exchange.py",
                     CTX, ["GL126"]) == []


def test_gl126_stale_registry_entry_fails():
  # the registered file without the env read: stale (gate moved/removed)
  out = lint_source("def gather_rows():\n  return 1\n",
                    "distributed_embeddings_tpu/ops/pallas_exchange.py",
                    CTX, ["GL126"])
  assert [f.rule for f in out] == ["GL126", "GL126"]
  assert all("stale" in f.message for f in out)
  # env read present but the registered predicate renamed away: stale
  src = """
import os

def _kernel_enabled():
  return os.environ.get("DE_TPU_PALLAS_EXCHANGE", "0") == "1"
"""
  out = lint_source(src, "distributed_embeddings_tpu/ops/pallas_exchange.py",
                    CTX, ["GL126"])
  assert _rules(out) == ["GL126"]
  assert "_use_pallas_exchange" in out[0].message


def test_gl126_suppression():
  src = """
import os

def probe():
  # transition shim reviewed in round 20
  return os.environ.get("DE_TPU_PALLAS_LEGACY")  # graftlint: disable=GL126
"""
  assert lint_source(src, "distributed_embeddings_tpu/ops/pallas_x.py",
                     CTX, ["GL126"]) == []


def test_gl118_stale_inventory_entry_fails(tmp_path):
  # a file that IS named by an inventory entry but no longer carries the
  # refusal must produce the stale-inventory finding from lint_paths
  pkg = tmp_path / "distributed_embeddings_tpu" / "streaming"
  pkg.mkdir(parents=True)
  (tmp_path / "pyproject.toml").write_text("")
  f = pkg / "publish.py"
  f.write_text("def publish():\n  return 1\n")
  out = [x for x in lint_paths([str(f)], root=str(tmp_path),
                               rules=["GL118"]) if x.rule == "GL118"]
  assert len(out) == 1 and "stale" in out[0].message
  # restore the inventoried refusal: the staleness finding clears
  f.write_text("""
import jax

def publish():
  if jax.process_count() > 1:
    raise NotImplementedError(
        "delta publication is a single-controller operation")
""")
  assert lint_paths([str(f)], root=str(tmp_path), rules=["GL118"]) == []


# ---------------------------------------------------------------------------
# GL119: raw thread/executor construction next to the step loop
# ---------------------------------------------------------------------------


def test_gl119_raw_thread_in_step_adjacent_module():
  """threading.Thread construction in the training packages that sit
  next to the step loop: pipeline.HostWorker is the one sanctioned
  overlap surface (one worker, joined before accounting, failures
  re-raised as step failures, spans on the shared trace)."""
  src = """
import threading

def start(self):
  t = threading.Thread(target=self._loop, daemon=True)
  t.start()
"""
  out = lint_source(src, "distributed_embeddings_tpu/tiering/prefetch.py",
                    CTX, ["GL119"])
  assert _rules(out) == ["GL119"]
  assert "pipeline.HostWorker" in out[0].message
  assert "threading.Thread" in out[0].message


def test_gl119_alias_and_executor_forms():
  """Renames and from-imports are not a bypass, and executors count the
  same as bare threads."""
  src = """
import threading as thr
from threading import Thread as T
from concurrent.futures import ThreadPoolExecutor
from concurrent import futures

def overlap():
  a = thr.Thread(target=work)
  b = T(target=work)
  c = ThreadPoolExecutor(max_workers=2)
  d = futures.ProcessPoolExecutor()
  return a, b, c, d
"""
  out = lint_source(src, "distributed_embeddings_tpu/dynvocab/trainer.py",
                    CTX, ["GL119"])
  assert _rules(out) == ["GL119"] * 4
  assert "concurrent.futures.ThreadPoolExecutor" in out[2].message


def test_gl119_scope_and_suppression():
  src = """
import threading

def start(self):
  return threading.Thread(target=self._poll)
"""
  # pipeline.py IS the sanctioned home of the worker thread
  assert lint_source(src, "distributed_embeddings_tpu/pipeline.py",
                     CTX, ["GL119"]) == []
  # training.py sits next to the step loop: in scope
  assert _rules(lint_source(src, "distributed_embeddings_tpu/training.py",
                            CTX, ["GL119"])) == ["GL119"]
  # serving/fleet run their own audited pools; layers never thread;
  # tools and tests drive their own harnesses
  for path in ("distributed_embeddings_tpu/serving/batcher.py",
               "distributed_embeddings_tpu/fleet/transport.py",
               "distributed_embeddings_tpu/layers/embedding.py",
               "tools/chaos_thing.py", "tests/test_thing.py"):
    assert lint_source(src, path, CTX, ["GL119"]) == [], path
  # a long-lived service thread suppresses with its reason
  sup = """
import threading

def start(self):
  self._writer = threading.Thread(target=self._write,  # graftlint: disable=GL119
                                  daemon=True)
"""
  assert lint_source(sup, "distributed_embeddings_tpu/resilience/trainer.py",
                     CTX, ["GL119"]) == []
  # a Thread ATTRIBUTE access (isinstance checks, current_thread) is use,
  # not construction — only the constructor call is flagged
  ok = """
import threading

def is_worker():
  return threading.current_thread().name == "host-pipeline"
"""
  assert lint_source(ok, "distributed_embeddings_tpu/tiering/prefetch.py",
                     CTX, ["GL119"]) == []


# ---------------------------------------------------------------------------
# threadlint (GL120-GL123, GL125): the concurrency pass
# ---------------------------------------------------------------------------

from distributed_embeddings_tpu.analysis import threadlint as tlint  # noqa: E402
from distributed_embeddings_tpu.telemetry.lockorder import (  # noqa: E402
    LockOrderError,
    LockOrderMonitor,
)


def test_gl120_guarded_attribute_fires_and_locked_access_clean():
  src = """
import threading

class Box:
  def __init__(self):
    self._lock = threading.Lock()
    self._items = []  # guarded-by: _lock

  def good(self):
    with self._lock:
      self._items.append(1)
      return len(self._items)

  def bad_write(self):
    self._items.append(1)

  def bad_read(self):
    return len(self._items)
"""
  out = tlint.lint_source(src, "x.py", rules=["GL120"])
  assert _rules(out) == ["GL120", "GL120"]
  assert "written" in out[0].message and "read" in out[1].message
  assert "'with self._lock:'" in out[0].message


def test_gl120_init_exempt_and_suppression():
  src = """
import threading

class Box:
  def __init__(self):
    self._lock = threading.Lock()
    self._n = 0  # guarded-by: _lock
    self._n = 1  # construction writes need no lock (pre-start)

  def bump(self):
    self._n += 1  # graftlint: disable=GL120 (single-writer by contract)
"""
  assert tlint.lint_source(src, "x.py", rules=["GL120"]) == []


def test_gl120_writes_mode_exempts_reads():
  """[writes]: locked-write/racy-read state (metric values, the
  subscriber's engine binding) needs no read-side suppressions."""
  src = """
import threading

class Metric:
  def __init__(self):
    self._lock = threading.RLock()
    self._value = 0  # guarded-by: _lock [writes]

  def inc(self):
    with self._lock:
      self._value += 1

  @property
  def value(self):
    return self._value

  def reset(self):
    self._value = 0
"""
  out = tlint.lint_source(src, "x.py", rules=["GL120"])
  assert [(f.rule, f.line) for f in out] == [("GL120", 18)]


def test_gl120_requires_lock_contract_and_condition_alias():
  """A requires-lock method is checked as lock-held, and holding a
  Condition built over the lock IS holding the lock (the batcher's
  _nonempty/_lock pair)."""
  src = """
import threading

class Q:
  def __init__(self):
    self._lock = threading.Lock()
    self._nonempty = threading.Condition(self._lock)
    self._pending = []  # guarded-by: _lock

  def _take_locked(self):  # requires-lock: _lock
    return self._pending.pop()

  def submit(self, x):
    with self._nonempty:
      self._pending.append(x)
      self._nonempty.notify()

  def broken_helper(self):
    return self._pending.pop()
"""
  out = tlint.lint_source(src, "x.py", rules=["GL120"])
  assert [(f.rule, f.line) for f in out] == [("GL120", 19)]


def test_gl120_dotted_guard_via_local_alias():
  """guarded-by: engine.lock is satisfied through the racy-read-verify
  idiom: a local bound from self.engine, then `with eng.lock:`."""
  src = """
class Sub:
  def __init__(self, engine):
    self.engine = engine  # guarded-by: engine.lock [writes]

  def rebase(self, new):
    old = self.engine
    with old.lock:
      self.engine = new

  def broken(self, new):
    self.engine = new
"""
  out = tlint.lint_source(src, "x.py", rules=["GL120"])
  assert [(f.rule, f.line) for f in out] == [("GL120", 12)]


def test_gl121_seeded_deadlock_cycle():
  """Two methods nesting the same pair of locks in opposite orders:
  the classic two-lock deadlock, one finding per knot."""
  src = """
import threading

class AB:
  def __init__(self):
    self._a = threading.Lock()
    self._b = threading.Lock()

  def fwd(self):
    with self._a:
      with self._b:
        pass

  def rev(self):
    with self._b:
      with self._a:
        pass
"""
  out = tlint.lint_source(src, "x.py", rules=["GL121"])
  assert _rules(out) == ["GL121"]
  assert "cycle" in out[0].message
  assert "AB._a" in out[0].message and "AB._b" in out[0].message
  # one consistent global order: no cycle, no finding
  ok = src.replace("with self._b:\n      with self._a:",
                   "with self._a:\n      with self._b:")
  assert tlint.lint_source(ok, "x.py", rules=["GL121"]) == []


def test_gl121_plain_lock_reacquire_deadlocks_rlock_does_not():
  src = """
import threading

class R:
  def __init__(self):
    self._lock = threading.{KIND}()

  def outer(self):
    with self._lock:
      self.inner()

  def inner(self):
    with self._lock:  {SUP}
      pass
"""
  bad = src.replace("{KIND}", "Lock").replace("{SUP}", "")
  # lexical nesting of the SAME plain Lock (via a requires-lock-less
  # helper there is none — seed a direct nest)
  direct = """
import threading

class R:
  def __init__(self):
    self._lock = threading.Lock()

  def outer(self):
    with self._lock:
      with self._lock:
        pass
"""
  out = tlint.lint_source(direct, "x.py", rules=["GL121"])
  assert _rules(out) == ["GL121"]
  assert "re-acquired" in out[0].message
  # an RLock is reentrant: same shape, no finding
  assert tlint.lint_source(
      direct.replace("threading.Lock()", "threading.RLock()"),
      "x.py", rules=["GL121"]) == []
  # and the suppression silences the plain-Lock form
  sup = direct.replace("with self._lock:\n        pass",
                       "with self._lock:  # graftlint: disable=GL121\n"
                       "        pass")
  assert tlint.lint_source(sup, "x.py", rules=["GL121"]) == []
  del bad


def test_gl122_multi_root_unsynchronized_mutation():
  src = """
import threading

class W:
  def __init__(self):
    self._lock = threading.Lock()
    self.items = []
    self._t1 = threading.Thread(target=self._produce)
    self._t2 = threading.Thread(target=self._consume)

  def _produce(self):
    self.items.append(1)

  def _consume(self):
    self.items.pop()
"""
  out = tlint.lint_source(src, "x.py", rules=["GL122"])
  assert _rules(out) == ["GL122"]
  assert "_produce" in out[0].message and "_consume" in out[0].message
  # locking every mutation clears it ...
  locked = src.replace(
      "def _produce(self):\n    self.items.append(1)",
      "def _produce(self):\n    with self._lock:\n      self.items.append(1)"
  ).replace(
      "def _consume(self):\n    self.items.pop()",
      "def _consume(self):\n    with self._lock:\n      self.items.pop()")
  assert tlint.lint_source(locked, "x.py", rules=["GL122"]) == []
  # ... and so does annotating (GL120 then owns the discipline)
  annotated = src.replace("self.items = []",
                          "self.items = []  # guarded-by: _lock")
  assert tlint.lint_source(annotated, "x.py", rules=["GL122"]) == []
  # suppression on the first unsynced mutation line silences
  sup = src.replace("self.items.append(1)",
                    "self.items.append(1)  # graftlint: disable=GL122")
  assert tlint.lint_source(sup, "x.py", rules=["GL122"]) == []


def test_gl122_single_root_is_not_a_race():
  """One thread root mutating freely is thread-confined state (the
  subscriber's poll-thread fields), not a race."""
  src = """
import threading

class S:
  def __init__(self):
    self._t = threading.Thread(target=self._loop)
    self.seen = 0

  def _loop(self):
    self.seen += 1
"""
  assert tlint.lint_source(src, "x.py", rules=["GL122"]) == []


def test_gl123_wait_outside_while_and_notify_without_lock():
  src = """
import threading

class C:
  def __init__(self):
    self._lock = threading.Lock()
    self._cv = threading.Condition(self._lock)
    self.ready = False

  def bad_wait(self):
    with self._cv:
      if not self.ready:
        self._cv.wait()

  def bad_notify(self):
    self._cv.notify()

  def good(self):
    with self._cv:
      while not self.ready:
        self._cv.wait()
      self._cv.notify_all()
"""
  out = tlint.lint_source(src, "x.py", rules=["GL123"])
  assert [(f.rule, f.line) for f in out] == [("GL123", 13), ("GL123", 16)]
  assert "while" in out[0].message
  assert "notify" in out[1].message
  # suppressions silence both
  sup = src.replace("self._cv.wait()\n\n",
                    "self._cv.wait()  # graftlint: disable=GL123\n\n", 1
                    ).replace("self._cv.notify()",
                              "self._cv.notify()  # graftlint: disable=GL123")
  assert tlint.lint_source(sup, "x.py", rules=["GL123"]) == []


def test_gl123_wait_for_and_events_exempt():
  """wait_for loops internally; Event.wait has no predicate to re-test
  — neither is condvar misuse."""
  src = """
import threading

class C:
  def __init__(self):
    self._cv = threading.Condition()
    self._stop = threading.Event()

  def ok(self):
    with self._cv:
      self._cv.wait_for(lambda: True, timeout=1.0)
    self._stop.wait(timeout=1.0)

  def notify_under_own_lock(self):
    with self._cv:
      self._cv.notify_all()
"""
  assert tlint.lint_source(src, "x.py", rules=["GL123"]) == []


def test_gl124_stale_and_unknown_suppressions():
  # a live suppression is fine; a stale one (rule never fires on that
  # line) and an unknown id are both GL124
  stale = """
def f():
  x = 1  # graftlint: disable=GL103
  return x
"""
  out = lint_source(stale, "tools/x.py", CTX, ["GL103", "GL124"])
  assert _rules(out) == ["GL124"]
  assert "suppresses nothing" in out[0].message
  live = """
def f():
  try:
    pass
  except:  # graftlint: disable=GL103
    pass
"""
  assert lint_source(live, "tools/x.py", CTX, ["GL103", "GL124"]) == []
  unknown = """
def f():
  return 1  # graftlint: disable=GL999
"""
  out = lint_source(unknown, "tools/x.py", CTX, ["GL124"])
  assert _rules(out) == ["GL124"]
  assert "unknown rule id" in out[0].message


def test_gl124_scope_rules_and_string_literals():
  # ids whose rule did NOT run this lint are not judged (a partial-rules
  # lint must not call other rules' suppressions stale) ...
  partial = """
def f():
  x = 1  # graftlint: disable=GL103
  return x
"""
  assert lint_source(partial, "tools/x.py", CTX, ["GL106", "GL124"]) == []
  # ... threadlint-owned ids are left to the threadlint pass ...
  external = """
def f():
  return 1  # graftlint: disable=GL120
"""
  assert lint_source(external, "tools/x.py", CTX, ["GL124"]) == []
  # ... and disable text inside a STRING (this suite's own fixtures) is
  # not a suppression at all
  fixture = '''
SRC = """
x = 1  # graftlint: disable=GL103
"""
'''
  assert lint_source(fixture, "tests/x.py", CTX, ["GL124"]) == []


def test_gl124_threadlint_judges_its_own_ids():
  src = """
import threading

class B:
  def __init__(self):
    self._lock = threading.Lock()
    self._n = 0  # guarded-by: _lock

  def ok(self):
    with self._lock:
      self._n += 1  # graftlint: disable=GL120
"""
  out = tlint.lint_source(src, "x.py")
  assert _rules(out) == ["GL124"]
  assert "GL120" in out[0].message


def test_gl125_registry_staleness_both_ways(tmp_path):
  (tmp_path / "pkg").mkdir()
  mod = tmp_path / "pkg" / "svc.py"
  mod.write_text("""
import threading

class Svc:
  def start(self):
    self._t = threading.Thread(target=self._loop, daemon=True)
    self._t.start()

  def _loop(self):
    pass
""")
  # discovered but unregistered: flagged at the construction site
  (tmp_path / "pyproject.toml").write_text(
      "[tool.graftlint]\nthread-roots = []\n")
  out = tlint.lint_paths([str(mod)], root=str(tmp_path))
  assert _rules(out) == ["GL125"]
  assert "not registered" in out[0].message and "Svc._loop" in out[0].message
  # registered and discovered: clean
  (tmp_path / "pyproject.toml").write_text(
      '[tool.graftlint]\nthread-roots = [\n    "pkg/svc.py::Svc._loop",\n]\n')
  assert tlint.lint_paths([str(mod)], root=str(tmp_path)) == []
  # registered but no longer discovered (thread removed): the ENTRY is
  # stale, flagged at its pyproject line
  mod.write_text("class Svc:\n  pass\n")
  out = tlint.lint_paths([str(mod)], root=str(tmp_path))
  assert _rules(out) == ["GL125"]
  assert "stale" in out[0].message
  assert out[0].path.endswith("pyproject.toml")
  # an entry for a file OUTSIDE the linted set is not judged
  (tmp_path / "pyproject.toml").write_text(
      '[tool.graftlint]\nthread-roots = [\n'
      '    "other/mod.py::Other._loop",\n]\n')
  assert tlint.lint_paths([str(mod)], root=str(tmp_path)) == []


def test_threadlint_repo_is_clean_at_head():
  """The annotated baseline: every guarded attribute in the batcher /
  engine / subscriber / router / registry / flight recorder is
  annotated, the thread-root registry matches discovery exactly, the
  lock graph is acyclic, and no suppression is stale."""
  pkg = os.path.join(REPO, "distributed_embeddings_tpu")
  findings = tlint.lint_paths([pkg], root=REPO)
  assert findings == [], "\n".join(f.render() for f in findings)


def test_threadlint_discovers_the_registered_concurrency_model():
  """The registry IS the model: parse_thread_roots and discovery agree
  entry-for-entry (the GL125 invariant, asserted directly), and the
  known long-lived service threads are all present."""
  roots = tlint.parse_thread_roots(REPO)
  assert roots is not None and len(roots) >= 10
  names = {e.split("::", 1)[1] for e, _ in roots}
  for expected in ("MicroBatcher._flush_loop", "MicroBatcher._complete_loop",
                   "DeltaSubscriber._poll_loop", "HostWorker._loop",
                   "FleetStore._hedged_call.run", "FlightRecorder._dump"):
    assert expected in names, expected


# ---------------------------------------------------------------------------
# the runtime sanitizer: lockorder agrees with the static graph
# ---------------------------------------------------------------------------


def test_lockorder_inverted_acquisition_trips():
  import threading
  mon = LockOrderMonitor()
  a = mon.wrap(threading.Lock(), "T.a")
  b = mon.wrap(threading.Lock(), "T.b")
  with a:
    with b:
      pass
  with pytest.raises(LockOrderError, match="inversion"):
    with b:
      with a:
        pass


def test_lockorder_reentrant_and_condition_share_name():
  import threading
  mon = LockOrderMonitor()
  lock = threading.RLock()
  wrapped = mon.wrap(lock, "T.lock")
  cv = mon.wrap(threading.Condition(lock), "T.lock")
  with wrapped:
    with cv:  # same name: reentrant, no self-edge
      cv.notify_all()
  assert mon.edges() == set()


def test_lockorder_consistency_with_static_graph():
  import threading
  mon = LockOrderMonitor()
  a = mon.wrap(threading.Lock(), "T.a")
  b = mon.wrap(threading.Lock(), "T.b")
  with a:
    with b:
      pass
  # consistent with an empty static graph and with a same-order edge
  mon.assert_consistent_with(set())
  mon.assert_consistent_with({("T.a", "T.b")})
  # a static edge in the OPPOSITE order closes a cycle: the runtime
  # truth contradicts the checked-in model
  with pytest.raises(LockOrderError, match="cycle"):
    mon.assert_consistent_with({("T.b", "T.a")})


def test_lockorder_static_graph_is_empty_and_acyclic_at_head():
  """The library holds at most one lock at a time lexically (cross-
  object nesting like router-over-store is runtime-only, covered by
  the instrumented tests) — pin that, so the first nested `with`
  must consciously pick an order."""
  assert tlint.static_lock_edges(REPO) == set()
