"""Op-level tests: fused lookup vs naive reference, forward + gradient.

Mirrors the reference test strategy
(`/root/reference/distributed_embeddings/python/ops/embedding_lookup_ops_test.py`):
numerical equivalence against a stock implementation for ragged
variable-hotness, dense constant-hotness, and sparse (COO) inputs.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.ops import (
    RaggedIds,
    SparseIds,
    embedding_lookup,
    row_to_split,
    sparse_dedup_grad,
)


def _naive_csr(params, values, row_splits, combiner):
  out = []
  for i in range(len(row_splits) - 1):
    rows = params[np.asarray(values[row_splits[i]:row_splits[i + 1]])]
    if rows.shape[0] == 0:
      out.append(np.zeros(params.shape[1], params.dtype))
    elif combiner == "sum":
      out.append(rows.sum(0))
    else:
      out.append(rows.mean(0))
  return np.stack(out)


def _random_ragged(rng, batch, vocab, max_hot, allow_empty=False):
  low = 0 if allow_empty else 1
  lengths = rng.integers(low, max_hot + 1, size=batch)
  nnz = int(lengths.sum())
  values = rng.integers(0, vocab, size=nnz).astype(np.int32)
  row_splits = np.concatenate([[0], np.cumsum(lengths)]).astype(np.int32)
  return values, row_splits


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_ragged_variable_hotness_forward(combiner):
  rng = np.random.default_rng(42)
  vocab, width, batch = 100, 16, 32
  params = rng.standard_normal((vocab, width)).astype(np.float32)
  values, row_splits = _random_ragged(rng, batch, vocab, max_hot=7)
  ids = RaggedIds(jnp.asarray(values), jnp.asarray(row_splits))
  got = embedding_lookup(jnp.asarray(params), ids, combiner=combiner)
  want = _naive_csr(params, values, row_splits, combiner)
  np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_ragged_grad_matches_naive(combiner):
  rng = np.random.default_rng(0)
  vocab, width, batch = 50, 8, 16
  params = jnp.asarray(rng.standard_normal((vocab, width)), jnp.float32)
  values, row_splits = _random_ragged(rng, batch, vocab, max_hot=5)
  ids = RaggedIds(jnp.asarray(values), jnp.asarray(row_splits))

  def fused(p):
    return jnp.sum(embedding_lookup(p, ids, combiner=combiner) ** 2)

  def naive(p):
    row_ids = np.repeat(np.arange(batch), np.diff(row_splits))
    rows = jnp.take(p, jnp.asarray(values), axis=0)
    out = jax.ops.segment_sum(rows, jnp.asarray(row_ids), num_segments=batch)
    if combiner == "mean":
      counts = jnp.asarray(np.diff(row_splits), jnp.float32)
      out = out / counts[:, None]
    return jnp.sum(out ** 2)

  g_fused = jax.grad(fused)(params)
  g_naive = jax.grad(naive)(params)
  np.testing.assert_allclose(g_fused, g_naive, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("combiner", [None, "sum", "mean"])
def test_dense_constant_hotness(combiner):
  rng = np.random.default_rng(7)
  vocab, width, batch, hot = 40, 4, 8, 3
  params = rng.standard_normal((vocab, width)).astype(np.float32)
  ids = rng.integers(0, vocab, size=(batch, hot)).astype(np.int32)
  got = embedding_lookup(jnp.asarray(params), jnp.asarray(ids), combiner=combiner)
  rows = params[ids]  # [B, H, D]
  if combiner is None:
    want = rows
  elif combiner == "sum":
    want = rows.sum(1)
  else:
    want = rows.mean(1)
  np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_dense_hotness_one_fast_path():
  rng = np.random.default_rng(3)
  params = rng.standard_normal((10, 4)).astype(np.float32)
  ids = rng.integers(0, 10, size=(6, 1)).astype(np.int32)
  got = embedding_lookup(jnp.asarray(params), jnp.asarray(ids), combiner="mean")
  np.testing.assert_allclose(got, params[ids[:, 0]], rtol=1e-6)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_sparse_coo_input(combiner):
  rng = np.random.default_rng(11)
  vocab, width, batch = 64, 8, 12
  params = rng.standard_normal((vocab, width)).astype(np.float32)
  values, row_splits = _random_ragged(rng, batch, vocab, max_hot=4)
  coo_rows = np.repeat(np.arange(batch), np.diff(row_splits))
  coo_cols = np.concatenate(
      [np.arange(n) for n in np.diff(row_splits)]) if len(values) else np.zeros(0)
  indices = np.stack([coo_rows, coo_cols], axis=1).astype(np.int32)
  sp = SparseIds(jnp.asarray(indices), jnp.asarray(values), (batch, 4))
  got = embedding_lookup(jnp.asarray(params), sp, combiner=combiner)
  want = _naive_csr(params, values, row_splits, combiner)
  np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_sparse_with_empty_trailing_rows():
  rng = np.random.default_rng(5)
  vocab, width = 20, 4
  params = rng.standard_normal((vocab, width)).astype(np.float32)
  # rows 0 and 1 have entries; rows 2,3 empty
  indices = np.array([[0, 0], [0, 1], [1, 0]], np.int32)
  values = np.array([3, 7, 9], np.int32)
  sp = SparseIds(jnp.asarray(indices), jnp.asarray(values), (4, 2))
  got = embedding_lookup(jnp.asarray(params), sp, combiner="sum")
  assert got.shape == (4, width)
  np.testing.assert_allclose(got[0], params[3] + params[7], rtol=1e-6)
  np.testing.assert_allclose(got[1], params[9], rtol=1e-6)
  np.testing.assert_allclose(got[2:], 0.0)


def test_row_to_split():
  rows = jnp.asarray([0, 0, 1, 3, 3, 3])
  splits = row_to_split(rows, 5)
  np.testing.assert_array_equal(np.asarray(splits), [0, 2, 3, 3, 6, 6])


def test_sparse_dedup_grad_static_shapes_and_sums():
  rng = np.random.default_rng(9)
  vocab, width, batch = 10, 4, 6
  values = np.array([2, 2, 5, 5, 5, 1, 0, 2], np.int32)
  row_splits = np.array([0, 2, 3, 5, 6, 7, 8], np.int32)
  grad = rng.standard_normal((batch, width)).astype(np.float32)
  uids, ugrads = sparse_dedup_grad(
      jnp.asarray(values), jnp.asarray(row_splits), jnp.asarray(grad), "sum", vocab)
  assert uids.shape == (8,) and ugrads.shape == (8, width)
  # scatter into dense and compare with naive accumulation
  dense = np.zeros((vocab, width), np.float32)
  row_ids = np.repeat(np.arange(batch), np.diff(row_splits))
  for v, r in zip(values, row_ids):
    dense[v] += grad[r]
  got = np.zeros((vocab + 1, width), np.float32)
  np.add.at(got, np.asarray(uids), np.asarray(ugrads))
  np.testing.assert_allclose(got[:vocab], dense, rtol=1e-5, atol=1e-5)
  # padding slots carry the out-of-range sentinel
  n_unique = len(np.unique(values))
  assert np.all(np.asarray(uids)[n_unique:] == vocab)
  np.testing.assert_allclose(np.asarray(ugrads)[n_unique:], 0.0)


def test_lookup_under_jit():
  rng = np.random.default_rng(1)
  params = jnp.asarray(rng.standard_normal((30, 8)), jnp.float32)
  values, row_splits = _random_ragged(rng, 10, 30, max_hot=4)
  ids = RaggedIds(jnp.asarray(values), jnp.asarray(row_splits))

  @jax.jit
  def f(p, ids):
    return embedding_lookup(p, ids, combiner="sum")

  got = f(params, ids)
  want = _naive_csr(np.asarray(params), values, row_splits, "sum")
  np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_int64_ids_accepted():
  rng = np.random.default_rng(2)
  params = jnp.asarray(rng.standard_normal((10, 4)), jnp.float32)
  ids = jnp.asarray(rng.integers(0, 10, (4, 2)))
  out = embedding_lookup(params, ids, combiner="sum")
  assert out.shape == (4, 4)


def test_bad_combiner_raises():
  params = jnp.zeros((4, 2))
  with pytest.raises(ValueError):
    embedding_lookup(params, jnp.zeros((2, 2), jnp.int32), combiner="max")
