"""Multi-controller TRAINING: the fused hybrid step across 2 real processes.

The comm backend's pod-scale claim (SURVEY §2.4 "comm backend") needs more
than single-controller shard_map: this spawns two JAX processes (localhost
coordinator, 4 virtual CPU devices each -> one GLOBAL 8-device mesh), runs
the fused sparse train step — dp->mp all_to_all, fused gather, backward
all_to_all, psum'd dense grads, scatter apply — as a true multi-controller
SPMD program, and checks both processes compute the SAME finite loss
sequence, which matches a single-process run of the identical problem.

The reference reaches the same scale with one NCCL/MPI rank per GPU; here
one jitted program spans processes and XLA runs the collectives.
"""

import os
import re
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
proc_id = int(sys.argv[1]); port = sys.argv[2]
n_local = 8 if port == "single" else 4
os.environ["XLA_FLAGS"] = (
    f"--xla_force_host_platform_device_count={n_local}")
os.environ.pop("JAX_PLATFORMS", None)
import jax
jax.config.update("jax_platforms", "cpu")
if port != "single":
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=2, process_id=proc_id)
    assert len(jax.devices()) == 8
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
import flax.linen as nn

from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.layers.embedding import TableConfig
from distributed_embeddings_tpu.models import bce_loss
from distributed_embeddings_tpu.ops.packed_table import adagrad_rule
from distributed_embeddings_tpu.training import (
    hybrid_partition_specs, init_sparse_state_direct, make_sparse_train_step)

WORLD = 8
tables = [TableConfig(input_dim=48 + 8 * t, output_dim=16, combiner="sum",
                      initializer="uniform") for t in range(WORLD)]
plan = DistEmbeddingStrategy(tables, WORLD, "basic",
                             input_hotness=[1] * WORLD, batch_hint=32)
rule = adagrad_rule(0.1)
opt = optax.adagrad(0.1)
mesh = Mesh(np.array(jax.devices()), ("mp",))

class Head(nn.Module):
    @nn.compact
    def __call__(self, numerical, cats, emb_acts=None):
        x = jnp.concatenate([numerical] + list(emb_acts), axis=1)
        return jnp.squeeze(nn.Dense(1, name="d")(x), -1)

rng = np.random.default_rng(7)
B = 32
numerical_np = rng.standard_normal((B, 4)).astype(np.float32)
cats_np = [rng.integers(0, t.input_dim, B).astype(np.int32) for t in tables]
labels_np = rng.integers(0, 2, B).astype(np.float32)

model = Head()
dummy = [jnp.zeros((2, 16), jnp.float32) for _ in tables]
dp = model.init(jax.random.PRNGKey(0), jnp.asarray(numerical_np[:2]), None,
                emb_acts=dummy)["params"]
state = init_sparse_state_direct(plan, rule, dp, opt, jax.random.PRNGKey(1))
sspec = hybrid_partition_specs(state, "mp")

def put(x, spec):
    # multi-controller-safe: every process holds identical host values, so
    # a global array is assembled from per-device blocks of the same data
    sh = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(
        x.shape, sh, lambda idx, x=x: np.asarray(x[idx]))

state = jax.tree_util.tree_map(
    lambda x, s: put(np.asarray(x), s), state, sspec)
batch = (jnp.asarray(numerical_np), [jnp.asarray(c) for c in cats_np],
         jnp.asarray(labels_np))
step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                              state, batch,
                              micro_batches=int(
                                  os.environ.get("TEST_MICRO_BATCHES", "1")))
batch_g = (put(numerical_np, P("mp")),
           [put(c, P("mp")) for c in cats_np],
           put(labels_np, P("mp")))
losses = []
for i in range(3):
    state, loss = step(state, *batch_g)
    # replicated loss: read the local shard (global fetch needs all procs)
    losses.append(float(np.asarray(loss.addressable_shards[0].data)))
print("LOSSES", " ".join(f"{l:.6f}" for l in losses))
assert all(np.isfinite(l) for l in losses)
"""


@pytest.mark.slow
@pytest.mark.parametrize("micro_batches", [1, 2])
def test_two_process_training_matches_single(tmp_path, micro_batches):
  """micro_batches=2 additionally runs the bounded-memory scan mode as a
  true multi-controller program (the grads' deferred single psum and the
  stashed delta streams cross the process boundary)."""
  script = tmp_path / "worker.py"
  script.write_text(_WORKER)
  with socket.socket() as s:
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
  env = {k: v for k, v in os.environ.items()
         if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PYTHONPATH")}
  env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
  env["TEST_MICRO_BATCHES"] = str(micro_batches)

  # single-process reference on the same 8-device problem
  ref = subprocess.run([sys.executable, str(script), "0", "single"],
                       env=env, capture_output=True, text=True, timeout=300)
  assert ref.returncode == 0, ref.stdout[-2000:] + ref.stderr[-2000:]
  ref_losses = re.search(r"LOSSES ([\d. -]+)", ref.stdout).group(1).split()

  procs = [subprocess.Popen(
      [sys.executable, str(script), str(i), str(port)],
      env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
      for i in range(2)]
  outs = []
  try:
    for p in procs:
      out, _ = p.communicate(timeout=300)
      outs.append(out)
  finally:
    for p in procs:
      if p.poll() is None:
        p.kill()
        p.wait()
  per_proc = []
  for i, (p, out) in enumerate(zip(procs, outs)):
    assert p.returncode == 0, f"proc {i} rc={p.returncode}\n{out[-3000:]}"
    per_proc.append(re.search(r"LOSSES ([\d. -]+)", out).group(1).split())
  # both processes of ONE program agree, and match the single-process run
  assert per_proc[0] == per_proc[1], per_proc
  for a, b in zip(per_proc[0], ref_losses):
    assert abs(float(a) - float(b)) < 1e-5, (per_proc[0], ref_losses)
