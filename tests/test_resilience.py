"""Resilience subsystem tests: durability, guards, retry, fault injection.

The two acceptance properties (ISSUE 2):

- a run killed mid-save resumes from the last durable checkpoint with a
  loss trajectory BIT-FOR-BIT identical to an uninterrupted run
  (``test_killed_mid_save_resumes_bit_exact``);
- a run fed injected NaN batches completes with the expected
  skipped-step count — and the committed state is bit-identical to a run
  that never saw the poison (``test_nan_batches_skip_*``).

Every fault here goes through the deterministic injector
(`resilience/faultinject.py`): crash-mid-save is a counted exception at
the ``ckpt_write`` site, corruption is an explicit truncate/bit-flip of
a published file, transient host-store read errors are counted raises at
the ``host_gather`` site. Nothing is timing- or luck-dependent.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu import checkpoint
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM, bce_loss
from distributed_embeddings_tpu.ops.packed_table import sparse_rule
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.parallel.lookup_engine import DistributedLookup
from distributed_embeddings_tpu.resilience import (
    FaultInjector,
    InjectedCrash,
    RetryPolicy,
    TransientIOError,
    durable,
    faultinject,
    guards,
)
from distributed_embeddings_tpu.resilience.trainer import (
    ResilientTrainer,
    TooManyBadSteps,
)
from distributed_embeddings_tpu.training import (
    init_sparse_state,
    make_sparse_train_step,
    shard_batch,
    shard_params,
)

WORLD = 4
VOCAB = [300, 200, 150, 20]


def build(world, oov="clip"):
  model = DLRM(vocab_sizes=VOCAB, embedding_dim=16, bottom_mlp=(32, 16),
               top_mlp=(32, 1), world_size=world, dense_row_threshold=32)
  plan = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=16,
            initializer={"name": "uniform", "scale": 0.05}) for v in VOCAB],
      world, "basic", dense_row_threshold=32, oov=oov)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adagrad(0.05)
  return model, plan, rule, opt


def make_batch(world, seed=0):
  rng = np.random.default_rng(seed)
  b = 4 * world
  numerical = rng.standard_normal((b, 13)).astype(np.float32)
  cats = [rng.integers(0, v, b).astype(np.int32) for v in VOCAB]
  labels = rng.integers(0, 2, b).astype(np.float32)
  return numerical, cats, labels


def init_state(model, plan, rule, opt, batch, mesh=None):
  numerical, cats, _ = batch
  params = model.init(jax.random.PRNGKey(0), jnp.asarray(numerical),
                      [jnp.asarray(c) for c in cats])["params"]
  state = init_sparse_state(plan, params, rule, opt)
  return shard_params(state, mesh) if mesh is not None else state


def assert_trees_equal(a, b):
  fa, fb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
  assert len(fa) == len(fb)
  for x, y in zip(fa, fb):
    np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# Non-finite guard: NaN batches skip bit-exactly
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("use_mesh", [False, True])
def test_nan_batches_skip_bit_exact(use_mesh):
  """A guarded run fed poison batches commits the SAME state as a run
  that never saw them — and counts exactly the injected skips."""
  world = WORLD if use_mesh else 1
  mesh = create_mesh(world) if use_mesh else None
  model, plan, rule, opt = build(world)
  batches = [make_batch(world, seed) for seed in range(5)]
  state = init_state(model, plan, rule, opt, batches[0], mesh)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, batches[0], donate=False, guard=True)

  poisoned = list(faultinject.nan_batches(batches, at_steps={1, 3}))
  assert np.isnan(poisoned[1][0]).all() and np.isnan(poisoned[3][0]).all()

  s = state
  bad_total = 0
  for batch in poisoned:
    s, loss, m = step(s, *shard_batch(batch, mesh))
    bad_total += int(m["bad_step"])
  assert bad_total == 2
  assert int(jax.device_get(s["step"])) == 3

  clean = state
  for i in (0, 2, 4):
    clean, _, _ = step(clean, *shard_batch(batches[i], mesh))
  assert_trees_equal(jax.device_get(s), jax.device_get(clean))


def test_nan_batch_skip_micro_batches():
  """The guard covers the micro-batch accumulation path too."""
  model, plan, rule, opt = build(1)
  batches = [make_batch(1, seed) for seed in range(3)]
  state = init_state(model, plan, rule, opt, batches[0])
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, None,
                                state, batches[0], donate=False, guard=True,
                                micro_batches=2)
  poisoned = list(faultinject.nan_batches(batches, at_steps={1}))
  s = state
  bad = 0
  for batch in poisoned:
    s, _, m = step(s, *shard_batch(batch, None))
    bad += int(m["bad_step"])
  assert bad == 1
  clean = state
  for i in (0, 2):
    clean, _, _ = step(clean, *shard_batch(batches[i], None))
  assert_trees_equal(jax.device_get(s), jax.device_get(clean))


def test_guard_rejects_exact():
  model, plan, rule, opt = build(1)
  batch = make_batch(1)
  state = init_state(model, plan, rule, opt, batch)
  with pytest.raises(NotImplementedError, match="guard"):
    make_sparse_train_step(model, plan, bce_loss, opt, rule, None, state,
                           batch, guard=True, exact=True)


# ---------------------------------------------------------------------------
# ResilientTrainer: auto-resume, skip accounting, abort-with-rollback
# ---------------------------------------------------------------------------


def _trainer_fixture(tmp_path, mesh, snapshot_every=2,
                     max_consecutive_bad=3, subdir="ckpts"):
  model, plan, rule, opt = build(WORLD)
  batches = [make_batch(WORLD, seed) for seed in range(8)]
  state = init_state(model, plan, rule, opt, batches[0], mesh)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, batches[0], donate=False, guard=True)

  def fresh_trainer(root):
    # state re-derived from the same seeds: a restarted process
    return ResilientTrainer(
        step, init_state(model, plan, rule, opt, batches[0], mesh),
        plan, rule, os.path.join(tmp_path, root), mesh=mesh,
        snapshot_every=snapshot_every,
        max_consecutive_bad=max_consecutive_bad)

  return batches, fresh_trainer


def test_killed_mid_save_resumes_bit_exact(tmp_path):
  """ACCEPTANCE: kill a run mid-checkpoint-save; the restarted run
  resumes from the last durable checkpoint and its loss trajectory is
  bit-for-bit the uninterrupted run's."""
  mesh = create_mesh(WORLD)
  batches, fresh_trainer = _trainer_fixture(tmp_path, mesh)

  ref = fresh_trainer("ref")
  losses_ref = ref.run(batches)
  final_ref = jax.device_get(ref.state)
  assert ref.step_count == 8

  crashed = fresh_trainer("crash")
  losses_crash = []
  # the 2nd snapshot (after committed step 4) dies mid-save: the first
  # save consumed ckpt_write events 0..7 (4 fused rank files + 4 npz),
  # so event 9 lands two files into the second save, leaving a
  # manifest-less .tmp
  inj = FaultInjector().crash_after("ckpt_write", 9)
  with pytest.raises(InjectedCrash):
    with faultinject.injected(inj):
      for batch in batches:
        losses_crash.append(crashed.step(*shard_batch(batch, mesh)))
  assert crashed.step_count == 4  # step 4 committed; its snapshot died
  root = os.path.join(tmp_path, "crash")
  assert any(d.endswith(".tmp") for d in os.listdir(root))
  assert durable.latest_valid(root)[0] == 2  # the crashed save is invalid

  resumed = fresh_trainer("crash")  # same root: auto-resume
  assert resumed.step_count == 2
  assert resumed.consumed == 2  # no skips: stream position == step
  assert resumed.resumed_from.endswith("ckpt_0000000002")
  losses_resumed = resumed.run(batches[resumed.consumed:])

  # bit-for-bit trajectory identity, both sides of the kill
  assert losses_crash == losses_ref[:len(losses_crash)]
  assert losses_resumed == losses_ref[2:]
  assert_trees_equal(jax.device_get(resumed.state), final_ref)


def test_nan_batches_skip_count_via_trainer(tmp_path):
  """ACCEPTANCE: a run fed injected NaN batches completes with the
  expected skipped-step count."""
  mesh = create_mesh(WORLD)
  batches, fresh_trainer = _trainer_fixture(tmp_path, mesh,
                                            snapshot_every=0)
  t = fresh_trainer("nan")
  losses = t.run(faultinject.nan_batches(batches[:6], at_steps={1, 4}))
  assert len(losses) == 6
  assert t.skipped_steps == 2
  assert t.step_count == 4
  assert np.isnan(losses[1]) and np.isnan(losses[4])
  assert all(np.isfinite(l) for i, l in enumerate(losses) if i not in (1, 4))


def test_resume_position_counts_skipped_batches(tmp_path):
  """A skip before the snapshot shifts the stream position off the step
  counter; resumption must use the checkpointed CONSUMED count, or a
  committed batch would be applied twice."""
  mesh = create_mesh(WORLD)
  batches, fresh_trainer = _trainer_fixture(tmp_path, mesh,
                                            snapshot_every=0)
  t = fresh_trainer("skewed")
  stream = list(faultinject.nan_batches(batches[:4], at_steps={1}))
  t.run(stream)                      # b0 commit, b1 skip, b2+b3 commit
  assert (t.step_count, t.consumed) == (3, 4)
  t.snapshot()

  resumed = fresh_trainer("skewed")
  assert resumed.step_count == 3 and resumed.consumed == 4
  # the fresh process adopts the persisted skip count, keeping
  # consumed == step_count + skipped_steps across the restart
  assert resumed.skipped_steps == 1
  resumed.run(batches[resumed.consumed:6])   # b4, b5

  clean = fresh_trainer("clean")
  clean.run([batches[i] for i in (0, 2, 3, 4, 5)])
  assert_trees_equal(jax.device_get(resumed.state),
                     jax.device_get(clean.state))


def test_abort_with_rollback_after_consecutive_bad(tmp_path):
  mesh = create_mesh(WORLD)
  batches, fresh_trainer = _trainer_fixture(tmp_path, mesh,
                                            snapshot_every=0,
                                            max_consecutive_bad=2)
  t = fresh_trainer("abort")
  t.run(batches[:2])
  t.snapshot()
  assert t.step_count == 2
  poison = faultinject.nan_batches(batches[2:6], at_steps={0, 1, 2, 3})
  with pytest.raises(TooManyBadSteps) as ei:
    t.run(poison)
  # rolled back to the snapshot before raising
  assert ei.value.resumed_step == 2
  assert t.step_count == 2
  assert t.skipped_steps == 2


# ---------------------------------------------------------------------------
# Async snapshots: background writes, joined with error propagation
# ---------------------------------------------------------------------------


def test_async_snapshots_match_sync_and_overlap(tmp_path):
  """Async snapshots publish the same checkpoints as sync ones (restore
  bit-identical), with training steps observably proceeding while the
  writer thread flushes (slow storage injected for determinism)."""
  mesh = create_mesh(WORLD)
  model, plan, rule, opt = build(WORLD)
  batches = [make_batch(WORLD, seed) for seed in range(8)]

  def fresh(root, async_snapshots):
    state = init_state(model, plan, rule, opt, batches[0], mesh)
    step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                  state, batches[0], donate=False,
                                  guard=True)
    return ResilientTrainer(step, state, plan, rule,
                            os.path.join(tmp_path, root), mesh=mesh,
                            snapshot_every=2,
                            async_snapshots=async_snapshots)

  t_sync = fresh("sync", False)
  losses_sync = t_sync.run(batches)

  t_async = fresh("async", True)
  overlap = 0
  losses_async = []
  with faultinject.injected(
      FaultInjector().delay_each("ckpt_write", 0.05)):
    for b in batches:
      losses_async.append(t_async.step(*shard_batch(b, mesh)))
      overlap += int(t_async.writer_active)
    t_async.close()
  assert losses_sync == losses_async
  assert overlap > 0  # steps ran while a snapshot was flushing
  steps_sync = [s for s, _ in
                durable.list_checkpoints(os.path.join(tmp_path, "sync"))]
  steps_async = [s for s, _ in
                 durable.list_checkpoints(os.path.join(tmp_path, "async"))]
  assert steps_sync == steps_async
  ra = fresh("async", False)  # auto-resume from the async-written root
  rs = fresh("sync", False)
  assert_trees_equal(jax.device_get(ra.state), jax.device_get(rs.state))


def test_async_snapshot_failure_propagates_at_join(tmp_path):
  """A background writer's failure must surface — at the next snapshot
  or the explicit join — not vanish with the thread."""
  mesh = create_mesh(WORLD)
  batches, fresh_trainer = _trainer_fixture(tmp_path, mesh,
                                            snapshot_every=0)
  t = fresh_trainer("async_err")
  t.retry_policy = RetryPolicy(retries=1, backoff=0.0)
  t.step(*shard_batch(batches[0], mesh))
  with faultinject.injected(FaultInjector().fail_first("ckpt_write", 10)):
    t.snapshot(async_=True)
    with pytest.raises(TransientIOError):
      t.join_writer()
  # the error is consumed by the raise; the trainer keeps working
  t.step(*shard_batch(batches[1], mesh))
  path = t.snapshot()
  assert not checkpoint.verify(path)


def test_async_snapshot_store_view_is_frozen_and_reconciled():
  """A HostTierStore's images are live mutable host state, which used to
  refuse async snapshots outright. ``snapshot(async_=True)`` now hands
  the writer ``store.snapshot_view(fused)``: owned images COPIED with
  the resident rows' device values scattered in (the same reconciliation
  ``flush`` applies to the live images) — frozen at the call, immune to
  later training/overlap mutation, and byte-identical to the flush-free
  ``overlay_reader`` at the same instant. The end-to-end async-tiered
  restore parity lives in test_pipeline.py."""
  mesh = create_mesh(WORLD)
  _, tplan, store = _tiered_fixture()
  fused = store.build_fused(mesh=mesh)
  name = next(iter(tplan.tier_specs))
  phys = tplan.by_name(name).layout_logical.phys_rows
  # drift the live image under the resident rows: between flushes the
  # device cache is authoritative there and the image copies go stale
  for r in store.owned_ranks:
    grps = store.resident_grps[name][r]
    assert grps.size > 0
    store.images[name][r][grps] += 3.0
  live_before = {r: store.images[name][r].copy() for r in store.owned_ranks}

  view = store.snapshot_view(fused)
  for r in store.owned_ranks:
    # taking the view never mutates the live image (flush would have)
    np.testing.assert_array_equal(store.images[name][r], live_before[r])
    # the view equals the flush-free overlay read of the whole image,
    # and both took the DEVICE values at the resident rows, not the
    # stale image bytes
    read = store.overlay_reader(name, r, fused)
    np.testing.assert_array_equal(view.images[name][r], read(0, phys))
    grps = store.resident_grps[name][r]
    assert not np.array_equal(view.images[name][r][grps],
                              live_before[r][grps])

  # later mutation of the live store (training, the overlap worker)
  # cannot reach the frozen view, and the view's flush is a no-op
  frozen = {r: view.images[name][r].copy() for r in store.owned_ranks}
  for r in store.owned_ranks:
    store.images[name][r] += 1.0
  view.flush(fused)
  for r in store.owned_ranks:
    np.testing.assert_array_equal(view.images[name][r], frozen[r])


# ---------------------------------------------------------------------------
# Checkpoint corruption: every failure restores previous-valid or names
# the bad file
# ---------------------------------------------------------------------------


def _two_snapshots(tmp_path):
  model, plan, rule, opt = build(1)
  batch = make_batch(1)
  state = init_state(model, plan, rule, opt, batch)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, None,
                                state, batch, donate=False)
  root = os.path.join(tmp_path, "ckpts")
  s = state
  for _ in range(2):
    s, _ = step(s, *shard_batch(batch, None))
    durable.save_rotating(root, plan, rule, s, keep=3)
  return root, plan, rule, s, step, batch


@pytest.mark.parametrize("mode", ["truncated", "bitflip", "no_manifest",
                                  "crash_mid_save"])
def test_corruption_falls_back_to_previous_valid(tmp_path, mode):
  root, plan, rule, s, step, batch = _two_snapshots(tmp_path)
  latest = durable.step_dir(root, 2)

  if mode == "truncated":
    fname = next(f for f in sorted(os.listdir(latest))
                 if f.startswith("fused_") and f.endswith("_r0.npy"))
    faultinject.truncate_file(os.path.join(latest, fname))
    expect = "truncated file"
  elif mode == "bitflip":
    fname = next(f for f in sorted(os.listdir(latest))
                 if f.startswith("fused_") and f.endswith("_r0.npy"))
    faultinject.bitflip_file(os.path.join(latest, fname))
    expect = "corrupted file"
  elif mode == "no_manifest":
    fname = "manifest.json"
    os.remove(os.path.join(latest, fname))
    expect = "missing manifest"
  else:  # crash_mid_save: the step-3 save dies; steps 1,2 stay valid
    s3, _ = step(s, *shard_batch(batch, None))
    with pytest.raises(InjectedCrash):
      with faultinject.injected(FaultInjector().crash_after("ckpt_write", 1)):
        durable.save_rotating(root, plan, rule, s3, keep=3)
    assert os.path.isdir(durable.step_dir(root, 3) + ".tmp")
    assert durable.latest_valid(root)[0] == 2
    return

  # the corrupted latest is detected and skipped...
  problems = checkpoint.verify(latest)
  assert problems and expect in problems[0] and fname in problems[0]
  assert durable.latest_valid(root)[0] == 1
  # ...restore of the bad dir names the bad file...
  with pytest.raises(ValueError, match="integrity"):
    checkpoint.restore(latest, plan, rule, s)
  try:
    checkpoint.restore(latest, plan, rule, s)
  except ValueError as e:
    assert fname in str(e)
  # ...and the auto-resume path lands on the previous valid checkpoint
  got = durable.restore_latest(root, plan, rule, s)
  assert got is not None and got[1] == 1
  assert int(jax.device_get(got[0]["step"])) == 1


def test_rotation_prunes_and_ignores_foreign_entries(tmp_path):
  root, plan, rule, s, step, batch = _two_snapshots(tmp_path)
  os.makedirs(os.path.join(root, "not_a_ckpt"))
  open(os.path.join(root, "ckpt_notanumber"), "w").close()
  for _ in range(3):
    s, _ = step(s, *shard_batch(batch, None))
    durable.save_rotating(root, plan, rule, s, keep=2)
  steps = [st for st, _ in durable.list_checkpoints(root)]
  assert steps == [4, 5]
  assert durable.latest_valid(root)[0] == 5


def test_checkpoint_io_retries_transient_errors(tmp_path):
  """A transient OSError inside save is retried by save_rotating (the
  partial tmp of the failed attempt is replaced by the retry)."""
  root, plan, rule, s, step, batch = _two_snapshots(tmp_path)
  s, _ = step(s, *shard_batch(batch, None))
  inj = FaultInjector().fail_first("ckpt_write", 1)
  with faultinject.injected(inj):
    path = durable.save_rotating(root, plan, rule, s, keep=3,
                                 policy=RetryPolicy(retries=2, backoff=0.0))
  assert not checkpoint.verify(path)
  assert durable.latest_valid(root)[0] == 3


# ---------------------------------------------------------------------------
# OOV policy
# ---------------------------------------------------------------------------


def test_oov_counted_and_clip_numerics_unchanged():
  mesh = create_mesh(WORLD)
  model, plan, rule, opt = build(WORLD)
  batch = make_batch(WORLD)
  state = init_state(model, plan, rule, opt, batch, mesh)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, batch, donate=False, guard=True)
  numerical, cats, labels = batch
  oov_cats = [c.copy() for c in cats]
  oov_cats[0][:3] = VOCAB[0] + 7   # 3 OOV occurrences on input 0
  oov_cats[1][0] = 10 ** 8         # 1 on input 1
  s1, _, m = step(state, *shard_batch((numerical, oov_cats, labels), mesh))
  assert sum(int(v) for v in m["oov"].values()) == 4
  assert int(m["bad_step"]) == 0
  # clip semantics: identical to pre-clamped ids
  clamped = [np.clip(c, 0, v - 1) for c, v in zip(oov_cats, VOCAB)]
  s2, _, m2 = step(state, *shard_batch((numerical, clamped, labels), mesh))
  assert sum(int(v) for v in m2["oov"].values()) == 0
  assert_trees_equal(jax.device_get(s1), jax.device_get(s2))


def test_oov_error_policy_raises_eagerly():
  _, plan, _, _ = build(1, oov="error")
  engine = DistributedLookup(plan)
  cats = [np.zeros((4,), np.int32) for _ in VOCAB]
  cats[2][1] = VOCAB[2] + 5
  with pytest.raises(ValueError, match="OOV policy 'error'"):
    engine.route_ids([jnp.asarray(c) for c in cats])


def test_oov_error_policy_raises_from_metrics(tmp_path):
  mesh = create_mesh(WORLD)
  model, plan, rule, opt = build(WORLD, oov="error")
  batch = make_batch(WORLD)
  state = init_state(model, plan, rule, opt, batch, mesh)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, batch, donate=False, guard=True)
  t = ResilientTrainer(step, state, plan, rule,
                       os.path.join(tmp_path, "oov"), mesh=mesh,
                       snapshot_every=0)
  numerical, cats, labels = batch
  t.step(*shard_batch(batch, mesh))  # clean batch passes
  before = jax.device_get(t.state)
  bad_cats = [c.copy() for c in cats]
  bad_cats[0][0] = VOCAB[0] + 1
  with pytest.raises(ValueError, match="OOV policy 'error'"):
    t.step(*shard_batch((numerical, bad_cats, labels), mesh))
  # the offending batch is commit-gated: the raise fires with the state
  # bit-identical to before the batch (nothing trained the clipped row)
  assert_trees_equal(before, jax.device_get(t.state))
  # ...but the batch IS fully accounted before the raise, so a
  # supervisor that catches it can snapshot a consistent position
  assert t.consumed == t.step_count + t.skipped_steps == 2
  assert sum(t.oov_totals.values()) == 1


def test_oov_error_policy_requires_guard():
  model, plan, rule, opt = build(1, oov="error")
  batch = make_batch(1)
  state = init_state(model, plan, rule, opt, batch)
  with pytest.raises(ValueError, match="requires make_sparse_train_step"):
    make_sparse_train_step(model, plan, bce_loss, opt, rule, None,
                           state, batch, donate=False, guard=False)


# ---------------------------------------------------------------------------
# guards unit behavior
# ---------------------------------------------------------------------------


def test_all_finite_and_bad_step_counter():
  assert bool(guards.all_finite({"a": jnp.ones(3),
                                 "i": jnp.arange(3)}))
  assert not bool(guards.all_finite((jnp.ones(2),
                                     jnp.array([1.0, np.nan]))))
  assert not bool(guards.all_finite(jnp.array([np.inf])))
  c = guards.BadStepCounter(2)
  assert c.update(0) and c.update(1)
  assert not c.update(1)          # second consecutive: abort
  assert c.skipped == 2
  c2 = guards.BadStepCounter(None)
  assert all(c2.update(1) for _ in range(10))


# ---------------------------------------------------------------------------
# Retry + host-tier store bounds (tiering surgery)
# ---------------------------------------------------------------------------


def _tiered_fixture():
  from distributed_embeddings_tpu.layers.embedding import TableConfig
  from distributed_embeddings_tpu.models.dlrm import _dlrm_initializer
  from distributed_embeddings_tpu.tiering import (
      HostTierStore,
      TieringConfig,
      TieringPlan,
  )
  vocab = [4096, 64]
  plan = DistEmbeddingStrategy(
      [TableConfig(input_dim=v, output_dim=16,
                   initializer=_dlrm_initializer(v)) for v in vocab],
      WORLD, "memory_balanced", dense_row_threshold=0,
      host_row_threshold=1000)
  rule = sparse_rule("adagrad", 0.05)
  tplan = TieringPlan(plan, rule, TieringConfig(cache_fraction=0.25,
                                                staging_grps=64))
  store = HostTierStore(tplan)
  store.init_uniform(0)
  return plan, tplan, store


def test_store_bounds_check_names_class_and_index():
  _, tplan, store = _tiered_fixture()
  name = next(iter(tplan.tier_specs))
  phys = tplan.by_name(name).layout_logical.phys_rows
  with pytest.raises(IndexError) as ei:
    store.gather(name, 0, np.array([0, phys + 3], np.int64))
  msg = str(ei.value)
  assert name in msg and str(phys + 3) in msg and str(phys) in msg
  with pytest.raises(IndexError, match="-1"):
    store.scatter(name, 1, np.array([-1], np.int64),
                  np.zeros((1, tplan.by_name(name).layout_logical.phys_width),
                           np.float32))
  # in-range passes
  rows = store.gather(name, 0, np.array([0, 1], np.int32))
  assert rows.shape[0] == 2


def test_host_gather_transient_errors_are_retried():
  from distributed_embeddings_tpu.tiering import TieredPrefetcher
  plan, tplan, store = _tiered_fixture()
  pf = TieredPrefetcher(tplan, store, mesh=None,
                        retry_policy=RetryPolicy(retries=3, backoff=0.0))
  rng = np.random.default_rng(0)
  cats = [rng.integers(0, v, 8 * WORLD).astype(np.int32)
          for v in (4096, 64)]
  with faultinject.injected(FaultInjector().fail_first("host_gather", 2)):
    staged = pf.stage(pf.classify(cats))
  assert pf.host_gather_retries == 2
  assert staged.device["rows"]  # staging upload produced


def test_unknown_fault_site_rejected_at_construction():
  """A typo'd site name used to install a rule that could never fire —
  the test went on 'passing' while injecting nothing. Rules now validate
  against the registered site set and name the valid ones."""
  with pytest.raises(ValueError, match="ckpt_write"):
    FaultInjector().crash_after("ckpt_wrte", 0)  # graftlint: disable=GL108
  with pytest.raises(ValueError, match="host_gather"):
    FaultInjector().fail_first("host_gathr", 2)  # graftlint: disable=GL108
  # registered extensions are accepted (and feed graftlint's GL108 set)
  site = faultinject.register_site("test_extension_site")
  try:
    FaultInjector().crash_after(site, 0)
  finally:
    faultinject._extra_sites.discard(site)


def test_host_gather_retries_exhausted_raises():
  from distributed_embeddings_tpu.tiering import TieredPrefetcher
  plan, tplan, store = _tiered_fixture()
  pf = TieredPrefetcher(tplan, store, mesh=None,
                        retry_policy=RetryPolicy(retries=1, backoff=0.0))
  rng = np.random.default_rng(0)
  cats = [rng.integers(0, v, 8 * WORLD).astype(np.int32)
          for v in (4096, 64)]
  with faultinject.injected(FaultInjector().fail_first("host_gather", 10)):
    with pytest.raises(TransientIOError, match="retries exhausted"):
      pf.stage(pf.classify(cats))


# ---------------------------------------------------------------------------
# ResilientTrainer drives TIERED steps (ROADMAP carried follow-on)
# ---------------------------------------------------------------------------


def test_resilient_trainer_drives_tiered_steps(tmp_path):
  """The trainer accepts the tiered step's return shape and nested
  metrics dict: bad_step/oov are accounted exactly like the sparse
  step's (skip counting, consumed-stream position), snapshots flush and
  checkpoint the host-tier store, and a fresh process auto-resumes —
  with the prefetcher's resident maps refreshed — to a bit-exact tail
  trajectory."""
  from distributed_embeddings_tpu.layers.embedding import TableConfig
  from distributed_embeddings_tpu.models.dlrm import _dlrm_initializer
  from distributed_embeddings_tpu.models.synthetic import power_law_ids
  from distributed_embeddings_tpu.tiering import (
      HostTierStore,
      TieredTrainer,
      TieringConfig,
      TieringPlan,
      init_tiered_state,
  )

  vocab = [5000, 300, 40]
  mesh = create_mesh(WORLD)
  plan = DistEmbeddingStrategy(
      [TableConfig(input_dim=v, output_dim=16,
                   initializer=_dlrm_initializer(v)) for v in vocab],
      WORLD, "memory_balanced", dense_row_threshold=0,
      host_row_threshold=1000)
  model = DLRM(vocab_sizes=vocab, embedding_dim=16, bottom_mlp=(32, 16),
               top_mlp=(32, 1), world_size=WORLD,
               strategy="memory_balanced", dense_row_threshold=0)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adam(1e-3)
  cfg = TieringConfig(cache_fraction=0.3, staging_grps=64,
                      rerank_interval=3)

  def make_batch(seed):
    r = np.random.default_rng(seed)
    numerical = r.standard_normal((32, 13)).astype(np.float32)
    cats = [power_law_ids(r, 32, 1, v, 1.05)[:, 0].astype(np.int32)
            for v in vocab]
    labels = r.integers(0, 2, 32).astype(np.float32)
    return numerical, cats, labels

  batch0 = make_batch(0)

  def fresh(seed):
    tplan = TieringPlan(plan, rule, cfg)
    store = HostTierStore(tplan)
    params = model.init(jax.random.PRNGKey(0), batch0[0],
                        batch0[1])["params"]
    dense = {k: v for k, v in params.items() if k != "embeddings"}
    state = shard_params(
        init_tiered_state(tplan, store, rule, dense, opt,
                          jax.random.PRNGKey(seed), mesh=mesh), mesh)
    tt = TieredTrainer(model, tplan, store, bce_loss, opt, rule, mesh,
                       state, batch0, donate=False, guard=True)
    return ResilientTrainer(None, None, plan, rule,
                            os.path.join(tmp_path, "ck"), mesh=mesh,
                            snapshot_every=2, tiered=tt)

  # an unguarded tiered trainer is refused up front
  tplan_u = TieringPlan(plan, rule, cfg)
  store_u = HostTierStore(tplan_u)
  params = model.init(jax.random.PRNGKey(0), batch0[0], batch0[1])["params"]
  dense_u = {k: v for k, v in params.items() if k != "embeddings"}
  state_u = shard_params(
      init_tiered_state(tplan_u, store_u, rule, dense_u, opt,
                        jax.random.PRNGKey(1), mesh=mesh), mesh)
  tt_u = TieredTrainer(model, tplan_u, store_u, bce_loss, opt, rule, mesh,
                       state_u, batch0, donate=False, guard=False)
  with pytest.raises(ValueError, match="guard=True"):
    ResilientTrainer(None, None, plan, rule, os.path.join(tmp_path, "x"),
                     mesh=mesh, tiered=tt_u)

  batches = [make_batch(100 + i) for i in range(6)]
  bad = list(batches[3])
  bad[2] = np.full_like(bad[2], np.nan)  # poison labels -> NaN loss
  batches[3] = tuple(bad)

  tr = fresh(7)
  losses = tr.run(batches)
  # the poison batch skipped: counted, nothing committed, stream moved on
  assert not np.isfinite(losses[3])
  assert tr.step_count == 5
  assert tr.skipped_steps == 1
  assert tr.consumed == 6  # == step_count + skipped_steps
  assert tr.tiered.hit_rate() > 0.5  # tier bookkeeping still accumulates

  # a fresh process (different init seed — must be overwritten by the
  # restore) resumes at the last snapshot and replays the tail
  # bit-exactly, skip accounting included
  tr2 = fresh(99)
  assert tr2.resumed_from is not None
  assert tr2.consumed == tr2.step_count + tr2.skipped_steps
  start = tr2.consumed
  tail = tr2.run(batches[start:])
  np.testing.assert_allclose(tail, losses[start:], rtol=0, atol=0)


# ---------------------------------------------------------------------------
# Full-jitter backoff (ISSUE 15 satellite): a resized pod's workers
# retrying host-tier gathers / checkpoint I/O on identical exponential
# schedules are thundering-herd shaped — jitter='full' decorrelates
# them, and the seed parameter keeps tests exact
# ---------------------------------------------------------------------------


def test_retry_jitter_none_is_the_historical_schedule():
  p = RetryPolicy(backoff=0.05, max_backoff=2.0)
  assert p.jitter == "none" and p.make_rng() is None
  assert [p.sleep_for(a) for a in range(6)] == \
      [0.05, 0.1, 0.2, 0.4, 0.8, 1.6]
  assert p.sleep_for(10) == 2.0  # capped


def test_retry_full_jitter_is_bounded_and_seed_deterministic():
  p = RetryPolicy(backoff=0.05, max_backoff=2.0, jitter="full", seed=42)
  seq1 = [p.sleep_for(a, rng) for rng in [p.make_rng()] for a in range(8)]
  seq2 = [p.sleep_for(a, rng) for rng in [p.make_rng()] for a in range(8)]
  assert seq1 == seq2  # same seed -> same sleep sequence, exactly
  caps = [min(0.05 * 2 ** a, 2.0) for a in range(8)]
  assert all(0.0 <= s <= c for s, c in zip(seq1, caps))
  assert any(s != c for s, c in zip(seq1, caps))  # actually jittered
  # different seeds decorrelate (the whole point)
  other = RetryPolicy(backoff=0.05, max_backoff=2.0, jitter="full", seed=7)
  rng_o = other.make_rng()
  assert [other.sleep_for(a, rng_o) for a in range(8)] != seq1


def test_retry_call_uses_jittered_sleeps():
  from distributed_embeddings_tpu.resilience import retry as retry_mod

  p = RetryPolicy(retries=3, backoff=0.05, max_backoff=2.0,
                  jitter="full", seed=123)
  calls = {"n": 0}

  def flaky():
    calls["n"] += 1
    if calls["n"] <= 3:
      raise OSError("transient")
    return "ok"

  slept = []
  assert retry_mod.retry_call(flaky, policy=p, sleep=slept.append) == "ok"
  rng = p.make_rng()
  assert slept == [p.sleep_for(a, rng) for a in range(3)]
  # and a second identical call sequence sleeps identically (seeded)
  calls["n"] = 0
  slept2 = []
  retry_mod.retry_call(flaky, policy=p, sleep=slept2.append)
  assert slept2 == slept


def test_retry_policy_rejects_unknown_jitter():
  with pytest.raises(ValueError, match="jitter"):
    RetryPolicy(jitter="half")


# ---------------------------------------------------------------------------
# Chaos harness (tools/chaos_train.py): long variant is slow-marked so
# tier-1 stays fast; `make chaos` runs the short standalone form
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_long():
  import sys
  sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
  import chaos_train
  res = chaos_train.run_chaos(steps=48, nan_every=5, snapshot_every=4,
                              crash_at_write_event=50, verbose=False)
  assert res["ok"], res
