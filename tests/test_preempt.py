"""Live elastic resize + preemption supervision (ISSUE 15).

The acceptance contract: a RUNNING trainer changes world size in place
— quiesce, re-shard rank blocks through the same regroup path the
elastic checkpoint restore uses (factored into ``resilience.elastic``),
resume — with every logical row f32 bit-exact at the resize boundary
and ``consumed == steps + skipped`` conserved across any shrink/grow
sequence, WITHOUT a checkpoint restore round-trip. Plus the SIGTERM
graceful-drain path (finish the in-flight step, snapshot, exit clean)
and the pod-membership supervisor the chaos harness
(``tools/chaos_preempt.py``, ``make chaos-preempt``) drives with real
SIGKILLs.
"""

import os
import signal
import sys

import jax
import numpy as np
import optax
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_elastic import (  # noqa: E402
    RULE,
    T_CFG,
    T_VOCAB,
    assert_tables_equal,
    build,
    host_logical_tables,
    init,
    logical_tables,
    make_batch,
    tiered_batch,
    tiered_build,
    tiered_fresh,
)

from distributed_embeddings_tpu import telemetry  # noqa: E402
from distributed_embeddings_tpu.models import bce_loss  # noqa: E402
from distributed_embeddings_tpu.parallel import create_mesh  # noqa: E402
from distributed_embeddings_tpu.resilience import elastic  # noqa: E402
from distributed_embeddings_tpu.resilience import faultinject  # noqa: E402
from distributed_embeddings_tpu.resilience.trainer import (  # noqa: E402
    ResilientTrainer,
)
from distributed_embeddings_tpu.tiering import (  # noqa: E402
    HostTierStore,
    TieredTrainer,
    TieringPlan,
)
from distributed_embeddings_tpu.tiering.train import (  # noqa: E402
    init_tiered_state,
)
from distributed_embeddings_tpu.training import (  # noqa: E402
    init_sparse_state,
    make_sparse_train_step,
    shard_batch,
    shard_params,
)


def sparse_world(world, guard=False):
  """mesh, plan, step_fn and a fresh state for one world size."""
  mesh = create_mesh(world)
  model, plan, opt = build(world)
  b = make_batch()
  params = model.init(jax.random.PRNGKey(0), b[0], b[1])["params"]
  state = shard_params(init_sparse_state(plan, params, RULE, opt), mesh)
  step = make_sparse_train_step(model, plan, bce_loss, opt, RULE, mesh,
                                state, b, donate=False, guard=guard)
  return mesh, plan, step, state


# ---------------------------------------------------------------------------
# elastic_resize: the in-memory re-shard itself
# ---------------------------------------------------------------------------


def test_elastic_resize_roundtrip_bit_exact():
  """4 -> 2 -> 4 in memory: every logical row (weights + optimizer
  lanes) bit-exact at each boundary, step counter preserved, and the
  state is LIVE at each world (a step runs)."""
  mesh4, plan4, step4, state = sparse_world(4)
  sb = shard_batch(make_batch(), mesh4)
  for _ in range(3):
    state, _ = step4(state, *sb)
  want = logical_tables(plan4, RULE, jax.device_get(state))

  reg = telemetry.MetricsRegistry()
  mesh2 = create_mesh(2)
  plan2, s2 = elastic.elastic_resize(state, plan4, 2, RULE, new_mesh=mesh2,
                                     telemetry=reg)
  assert plan2.world_size == 2
  assert int(jax.device_get(s2["step"])) == 3
  assert_tables_equal(want, logical_tables(plan2, RULE, jax.device_get(s2)))

  plan4b, s4b = elastic.elastic_resize(s2, plan2, 4, RULE, new_mesh=mesh4,
                                       telemetry=reg)
  assert_tables_equal(want,
                      logical_tables(plan4b, RULE, jax.device_get(s4b)))
  assert reg.counter("elastic/resizes").value == 2
  assert reg.histogram("elastic/quiesce_s").count == 2
  # the resized state trains (same step builder recipe, new world)
  _, plan2c, step2, _ = sparse_world(2)
  s2c, loss = step2(s2, *shard_batch(make_batch(), mesh2))
  assert np.isfinite(float(loss))
  assert int(jax.device_get(s2c["step"])) == 4


def test_elastic_resize_accepts_plan_or_world_int():
  mesh4, plan4, _, state = sparse_world(4)
  _, plan2_explicit, _, _ = sparse_world(2)
  p_a, s_a = elastic.elastic_resize(state, plan4, 2, RULE)
  p_b, s_b = elastic.elastic_resize(state, plan4, plan2_explicit, RULE)
  assert p_a.world_size == p_b.world_size == 2
  assert_tables_equal(logical_tables(p_a, RULE, jax.device_get(s_a)),
                      logical_tables(p_b, RULE, jax.device_get(s_b)))


def test_resize_refusals_name_the_reason():
  from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
  _, plan4, _, state = sparse_world(4)
  other = DistEmbeddingStrategy(
      [dict(input_dim=v + 1, output_dim=16,
            initializer={"name": "uniform", "scale": 0.05})
       for v in [300, 200, 150, 20]],
      2, "basic", dense_row_threshold=32)
  with pytest.raises(ValueError, match="tables differ"):
    elastic.elastic_resize(state, plan4, other, RULE)
  flip = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=16,
            initializer={"name": "uniform", "scale": 0.05})
       for v in [300, 200, 150, 20]],
      2, "basic", dense_row_threshold=0)  # vocab-20 table flips kind
  with pytest.raises(ValueError, match="kind"):
    elastic.elastic_resize(state, plan4, flip, RULE)
  tiered = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=16,
            initializer={"name": "uniform", "scale": 0.05})
       for v in [300, 200, 150, 20]],
      2, "basic", dense_row_threshold=32, host_row_threshold=250)
  with pytest.raises(ValueError, match="tier"):
    elastic.elastic_resize(state, plan4, tiered, RULE)


# ---------------------------------------------------------------------------
# ResilientTrainer.resize: counter conservation across shrink/grow/shrink
# ---------------------------------------------------------------------------


def test_trainer_resize_conserves_counters_4_2_4_guarded():
  """World 4 -> 2 -> 4 mid-run with NaN batches around the resizes:
  consumed == steps + skipped across the WHOLE sequence, every poison
  batch skipped exactly once, no restore round-trip, and the
  trajectory matches an unresized same-data run — bit-exact before the
  first resize, fp-associativity bound after."""
  steps = 12
  batches = [make_batch(100 + i) for i in range(steps)]
  nan_at = {3, 7}
  stream = list(faultinject.nan_batches(batches, at_steps=nan_at))

  def run(tmp, resize_at=None):
    reg = telemetry.MetricsRegistry()
    mesh, plan, step, state = sparse_world(4, guard=True)
    t = ResilientTrainer(step, state, plan, RULE,
                         os.path.join(tmp, "ckpts"), mesh=mesh,
                         snapshot_every=0, resume=False, telemetry=reg)
    losses = []
    for i, b in enumerate(stream):
      if resize_at and i in resize_at:
        world = resize_at[i]
        new_mesh, new_plan, new_step, _ = sparse_world(world, guard=True)
        t.resize(new_plan, step_fn=new_step, new_mesh=new_mesh)
      losses.append(t.step(*shard_batch(b, t.mesh)))
    return t, losses, reg

  import tempfile
  ref_t, ref_losses, _ = run(tempfile.mkdtemp())
  t, losses, reg = run(tempfile.mkdtemp(), resize_at={5: 2, 9: 4})

  assert t.plan.world_size == 4
  assert t.consumed == steps
  assert t.skipped_steps == len(nan_at)
  assert t.consumed == t.step_count + t.skipped_steps
  assert reg.counter("elastic/resizes").value == 2
  assert reg.histogram("elastic/quiesce_s").count == 2
  # no restore round-trip: nothing was ever checkpointed or resumed
  assert t.resumed_from is None
  assert not os.path.isdir(os.path.join(t.ckpt_root))
  for i, (a, b) in enumerate(zip(losses, ref_losses)):
    if i in nan_at:
      assert np.isnan(a) and np.isnan(b)
    elif i < 5:
      assert a == b, f"step {i} diverged before the first resize"
    else:
      assert np.isclose(a, b, rtol=5e-4, atol=1e-5), f"step {i}"


def test_trainer_resize_sparse_requires_step_fn():
  mesh, plan, step, state = sparse_world(4, guard=True)
  import tempfile
  t = ResilientTrainer(step, state, plan, RULE, tempfile.mkdtemp(),
                       mesh=mesh, resume=False,
                       telemetry=telemetry.MetricsRegistry())
  with pytest.raises(ValueError, match="step_fn"):
    t.resize(2)


# ---------------------------------------------------------------------------
# tiered: host images re-shard in place, prefetcher refreshes
# ---------------------------------------------------------------------------


def tiered_factory_for(world, mesh, telemetry_reg):
  """A tiered_factory closure + the new world's store, as
  ResilientTrainer.resize wants them."""
  plan, model = tiered_build(world)
  tplan = TieringPlan(plan, RULE, T_CFG)
  store = HostTierStore(tplan)
  b0 = tiered_batch(100)

  def factory(new_state):
    return TieredTrainer(model, tplan, store, bce_loss, optax.adam(1e-3),
                         RULE, mesh, new_state, b0, donate=False,
                         guard=True, telemetry=telemetry_reg)

  return plan, store, factory


def test_trainer_resize_tiered_4_2_4():
  """A guarded TIERED run resizes 4 -> 2 -> 4 in place: host-tier
  logical rows bit-exact at each boundary, the re-bound prefetcher
  serves continued training with zero misses, and the hit/skip/OOV
  accounting carries across (consumed == steps + skipped end to end)."""
  mesh4, mesh2 = create_mesh(4), create_mesh(2)
  reg = telemetry.MetricsRegistry()
  plan4, model4, tplan4, store4, b0, state4 = tiered_fresh(4, mesh4)
  tr4 = TieredTrainer(model4, tplan4, store4, bce_loss, optax.adam(1e-3),
                      RULE, mesh4, shard_params(state4, mesh4), b0,
                      donate=False, guard=True, telemetry=reg)
  import tempfile
  t = ResilientTrainer(None, None, plan4, RULE, tempfile.mkdtemp(),
                       mesh=mesh4, resume=False, tiered=tr4, telemetry=reg)
  batches = [tiered_batch(100 + i) for i in range(8)]
  poison = list(faultinject.nan_batches(batches, at_steps={2}))

  for b in poison[:3]:
    t.step(*b)
  t.tiered.flush()
  want = host_logical_tables(plan4, tplan4, store4)

  plan2, store2, factory2 = tiered_factory_for(2, mesh2, reg)
  t.resize(plan2, new_mesh=mesh2, new_store=store2, tiered_factory=factory2)
  tplan2 = store2.tplan
  # every host-tier logical row (weights + optimizer lanes) bit-exact
  assert_tables_equal(want, host_logical_tables(plan2, tplan2, store2))

  for b in poison[3:6]:
    t.step(*b)
  t.tiered.flush()
  want2 = host_logical_tables(plan2, tplan2, store2)

  plan4b, store4b, factory4 = tiered_factory_for(4, mesh4, reg)
  t.resize(plan4b, new_mesh=mesh4, new_store=store4b,
           tiered_factory=factory4)
  assert_tables_equal(want2,
                      host_logical_tables(plan4b, store4b.tplan, store4b))

  for b in poison[6:]:
    t.step(*b)
  assert t.consumed == 8
  assert t.skipped_steps == 1
  assert t.consumed == t.step_count + t.skipped_steps
  assert reg.counter("elastic/resizes").value == 2
  # the prefetch contract held through both resizes on the NEW worlds
  assert all(v["missed"] == 0
             for v in t.tiered.metrics_summary()["per_class"].values())
  assert t.resumed_from is None


def test_tiered_resize_remaps_counts_and_warm_starts():
  """The live resize routes observed counts window-wise into the new
  store (remap_group_counts — shared with the restore path): each
  table's peak count survives exactly and the hottest group is already
  resident."""
  mesh4, mesh2 = create_mesh(4), create_mesh(2)
  plan4, model4, tplan4, store4, b0, state4 = tiered_fresh(4, mesh4)
  tr4 = TieredTrainer(model4, tplan4, store4, bce_loss, optax.adam(1e-3),
                      RULE, mesh4, shard_params(state4, mesh4), b0,
                      donate=False)
  tr4.run([tiered_batch(100 + i) for i in range(4)])
  tr4.flush()

  plan2, _ = tiered_build(2)
  tplan2 = TieringPlan(plan2, RULE, T_CFG)
  store2 = HostTierStore(tplan2)
  _, _ = elastic.elastic_resize(tr4.state, plan4, plan2, RULE,
                                new_mesh=mesh2, old_store=store4,
                                new_store=store2,
                                telemetry=telemetry.MetricsRegistry())
  for key, c in tplan2.classes.items():
    for rank in range(2):
      cnt = store2.counts[c.name][rank]
      if cnt.max() == 0:
        continue
      assert int(np.argmax(cnt)) in store2.resident_grps[c.name][rank]
  total4 = sum(int(v.sum()) for name in store4.counts
               for v in store4.counts[name])
  assert total4 > 0
  total2 = sum(int(v.sum()) for name in store2.counts
               for v in store2.counts[name])
  assert total2 > 0


def test_resize_partially_owned_store_via_spill(tmp_path):
  """A rank-owner-sharded store (one multi-controller process's view)
  resizes through the shared spill directory: each process publishes
  the rank blocks only IT can read, unowned source ranks are
  window-read back from the spill.  A single process plays both sides
  here — it owns ranks (0, 1), the peer's images are pre-planted where
  the spill protocol puts them — and the result must be bit-exact with
  a fully-owned in-memory resize."""
  mesh4, mesh2 = create_mesh(4), create_mesh(2)
  plan4, model4, tplan4, store4, b0, state4 = tiered_fresh(4, mesh4)
  plan2, _ = tiered_build(2)
  tplan2 = TieringPlan(plan2, RULE, T_CFG)

  # argument contract (configuration errors, not process-count refusals)
  partial = HostTierStore(tplan4, owned_ranks=(0, 1))
  with pytest.raises(ValueError, match="needs spill_dir"):
    elastic.elastic_resize(state4, plan4, plan2, RULE, old_store=partial,
                           new_store=HostTierStore(tplan2))
  with pytest.raises(ValueError, match="needs new_mesh"):
    elastic.elastic_resize(state4, plan4, plan2, RULE, old_store=partial,
                           new_store=HostTierStore(tplan2),
                           spill_dir=str(tmp_path))

  # reference: fully-owned resize (flushes store4's images on the way)
  ref_store = HostTierStore(tplan2)
  _, ref_state = elastic.elastic_resize(state4, plan4, plan2, RULE,
                                        new_mesh=mesh2, old_store=store4,
                                        new_store=ref_store)

  # the partial view mirrors store4's owned images + the replicated
  # bookkeeping every process carries (resident sets, counts)
  for name in store4.images:
    for rank in range(4):
      if rank in (0, 1):
        partial.set_image(name, rank, store4.images[name][rank])
      partial.resident_map[name][rank][:] = store4.resident_map[name][rank]
      partial.resident_grps[name][rank] = \
          store4.resident_grps[name][rank].copy()
      partial.counts[name][rank][:] = store4.counts[name][rank]

  # plant the peer's spill exactly where its process would have put it
  step_now = int(np.asarray(jax.device_get(state4["step"])))
  sub = os.path.join(str(tmp_path), f"resize_{step_now:010d}_w4to2")
  os.makedirs(sub, exist_ok=True)
  for name in store4.images:
    for rank in (2, 3):
      np.save(os.path.join(sub, f"src_{name}_r{rank}.npy"),
              store4.images[name][rank])

  got_store = HostTierStore(tplan2)
  _, got_state = elastic.elastic_resize(state4, plan4, plan2, RULE,
                                        new_mesh=mesh2, old_store=partial,
                                        new_store=got_store,
                                        spill_dir=str(tmp_path))
  for name in ref_store.images:
    for rank in range(2):
      np.testing.assert_array_equal(got_store.images[name][rank],
                                    ref_store.images[name][rank])
      np.testing.assert_array_equal(got_store.counts[name][rank],
                                    ref_store.counts[name][rank])
  for k in ref_state["fused"]:
    np.testing.assert_array_equal(jax.device_get(got_state["fused"][k]),
                                  jax.device_get(ref_state["fused"][k]))
  # the spill sub-directory is cleaned up after the completion fence
  assert not os.path.exists(sub)


def test_membership_barrier(tmp_path):
  """Survivors agree on one (step, world); a laggard times out with the
  arrivals named; a disagreeing member fails LOUDLY before any rank
  block regroups."""
  import threading

  pod = str(tmp_path)
  res = {}

  def post(mid):
    res[mid] = elastic.membership_barrier(pod, 1, mid, 2, step=7, world=4)

  t = threading.Thread(target=post, args=("m1",))
  t.start()
  got = elastic.membership_barrier(pod, 1, "m0", 2, step=7, world=4)
  t.join()
  assert got == (7, 4) and res["m1"] == (7, 4)

  # epoch isolation: epoch 1's records cannot satisfy epoch 2's barrier
  with pytest.raises(RuntimeError, match="only \\['m0'\\] of 2"):
    elastic.membership_barrier(pod, 2, "m0", 2, step=8, world=4,
                               timeout_s=0.3)

  # a survivor that raced one step past the boundary is named
  d = os.path.join(pod, "barriers", "000003")
  os.makedirs(d)
  with open(os.path.join(d, "m1.json"), "w") as f:
    f.write('{"id": "m1", "step": 9, "world": 4}')
  with pytest.raises(RuntimeError, match="DISAGREES.*m1"):
    elastic.membership_barrier(pod, 3, "m0", 2, step=8, world=4)


def test_resize_membership_barrier_wiring(tmp_path):
  """ResilientTrainer.resize(pod_dir=...) posts to the membership
  barrier before regrouping, defaults spill_dir under the pod, and a
  half-specified barrier is a loud configuration error."""
  reg = telemetry.MetricsRegistry()
  mesh4, plan4, step4, state = sparse_world(4, guard=True)
  tr = ResilientTrainer(step4, state, plan4, RULE,
                        os.path.join(str(tmp_path), "ckpts"), mesh=mesh4,
                        resume=False, telemetry=reg)
  mesh2, plan2b, step2, _ = sparse_world(2, guard=True)
  with pytest.raises(ValueError, match="membership-change barrier"):
    tr.resize(plan2b, step2, new_mesh=mesh2, pod_dir=str(tmp_path))
  # a single survivor (n_participants=1) barriers with itself and
  # proceeds through the normal single-controller resize
  got = tr.resize(plan2b, step2, new_mesh=mesh2, pod_dir=str(tmp_path),
                  barrier_epoch=1, member_id="m0", n_participants=1)
  assert got.world_size == 2
  rec = os.path.join(str(tmp_path), "barriers", "000001", "m0.json")
  assert os.path.exists(rec)
  assert reg.counter("elastic/membership_barriers").value == 1


def test_prefetcher_rebind():
  """TieredPrefetcher.rebind re-points a live prefetcher at a resized
  world's plan + store; cumulative counters survive."""
  mesh4, mesh2 = create_mesh(4), create_mesh(2)
  plan4, model4, tplan4, store4, b0, state4 = tiered_fresh(4, mesh4)
  tr4 = TieredTrainer(model4, tplan4, store4, bce_loss, optax.adam(1e-3),
                      RULE, mesh4, shard_params(state4, mesh4), b0,
                      donate=False)
  tr4.run([tiered_batch(100)])
  pf = tr4.prefetcher
  bytes_before = pf.total_host_gather_bytes
  assert bytes_before > 0

  plan2, _ = tiered_build(2)
  tplan2 = TieringPlan(plan2, RULE, T_CFG)
  store2 = HostTierStore(tplan2)
  store2.init_uniform(3)
  pf.rebind(tplan2, store2, mesh=mesh2)
  assert pf.plan is plan2
  assert pf.total_host_gather_bytes == bytes_before
  cold = pf.classify(tiered_batch(200)[1])  # routes against the NEW plan
  assert set(cold) == set(tplan2.tier_specs)
  assert all(len(per_rank) == 2 for per_rank in cold.values())


# ---------------------------------------------------------------------------
# SIGTERM graceful drain (the preemption NOTICE path)
# ---------------------------------------------------------------------------


def test_sigterm_drain_mid_run_snapshots_and_resumes_bit_exact(tmp_path):
  """SIGTERM delivered mid-run: the in-flight step finishes, a durable
  snapshot lands, run() stops consuming; a fresh trainer auto-resumes
  and the completed stream matches an uninterrupted reference
  bit-for-bit."""
  steps = 8
  batches = [make_batch(100 + i) for i in range(steps)]
  root = os.path.join(str(tmp_path), "ckpts")

  # reference: uninterrupted
  mesh, plan, step, state = sparse_world(4, guard=True)
  t_ref = ResilientTrainer(step, state, plan, RULE,
                           os.path.join(str(tmp_path), "ref"), mesh=mesh,
                           snapshot_every=0, resume=False,
                           telemetry=telemetry.MetricsRegistry())
  ref_losses = t_ref.run(batches)

  mesh, plan, step, state = sparse_world(4, guard=True)
  reg = telemetry.MetricsRegistry()
  t = ResilientTrainer(step, state, plan, RULE, root, mesh=mesh,
                       snapshot_every=0, resume=False, telemetry=reg)
  old_handler = signal.getsignal(signal.SIGTERM)
  try:
    t.install_sigterm_drain(deadline_s=120.0)

    def noticed_stream():
      for i, b in enumerate(batches):
        if i == 3:
          # the preemption notice arrives while batch 3 is being fed:
          # the handler only flags, so this step still runs to
          # completion before the drain snapshot is taken
          os.kill(os.getpid(), signal.SIGTERM)
        yield b

    losses = t.run(noticed_stream())
    assert t.drain_requested and t.drained
    assert len(losses) == 4  # batches 0..3 consumed, then the drain
    assert t.consumed == 4
    assert reg.counter("train/sigterm_drains").value == 1
    assert os.path.isdir(root) and any(
        d.startswith("ckpt_") and not d.endswith(".tmp")
        for d in os.listdir(root))

    # relaunch: auto-resume from the drain snapshot, finish the stream
    mesh2, plan2, step2, state2 = sparse_world(4, guard=True)
    t2 = ResilientTrainer(step2, state2, plan2, RULE, root, mesh=mesh2,
                          snapshot_every=0, resume=True,
                          telemetry=telemetry.MetricsRegistry())
    assert t2.resumed_from is not None and t2.consumed == 4
    losses2 = t2.run(batches[t2.consumed:])
    stitched = losses + losses2
    assert len(stitched) == steps
    for i, (a, b) in enumerate(zip(stitched, ref_losses)):
      assert a == b, f"step {i} diverged across the drain"
  finally:
    signal.signal(signal.SIGTERM, old_handler)


def test_maybe_drain_is_noop_without_notice(tmp_path):
  mesh, plan, step, state = sparse_world(4, guard=True)
  t = ResilientTrainer(step, state, plan, RULE, str(tmp_path), mesh=mesh,
                       resume=False, telemetry=telemetry.MetricsRegistry())
  assert not t.maybe_drain()
  assert not t.drain_requested and not t.drained


def test_failed_drain_snapshot_is_not_drained(tmp_path):
  """A drain snapshot that RAISES must not read as a completed drain
  (exit 0 on it would record a clean drain with no snapshot behind it):
  the error propagates, ``drained`` stays False, the watchdog is still
  disarmed, and the next ``maybe_drain`` retries the snapshot."""
  mesh, plan, step, state = sparse_world(4, guard=True)
  t = ResilientTrainer(step, state, plan, RULE, str(tmp_path), mesh=mesh,
                       snapshot_every=0, resume=False,
                       telemetry=telemetry.MetricsRegistry())
  t._drain_requested.set()  # the notice, without a real signal
  orig, calls = t.snapshot, {"n": 0}

  def flaky(*a, **k):
    calls["n"] += 1
    if calls["n"] == 1:
      raise OSError("disk full")
    return orig(*a, **k)

  t.snapshot = flaky
  with pytest.raises(OSError, match="disk full"):
    t.maybe_drain()
  assert not t.drained            # failure is not durability
  assert t._drained.is_set()      # but the hang watchdog is disarmed
  assert t.maybe_drain()          # the retry takes the real snapshot
  assert t.drained


# ---------------------------------------------------------------------------
# stream re-root across a resize
# ---------------------------------------------------------------------------


def test_resize_re_roots_delta_chain(tmp_path):
  from distributed_embeddings_tpu import checkpoint
  from distributed_embeddings_tpu.streaming import (
      DeltaPublisher,
      RowGenerationTracker,
  )

  mesh4, plan4, step4, state = sparse_world(4, guard=True)
  reg = telemetry.MetricsRegistry()
  pubdir = os.path.join(str(tmp_path), "pub")
  tracker = RowGenerationTracker(plan4)
  pub = DeltaPublisher(pubdir, plan4, RULE, tracker, telemetry=reg)
  t = ResilientTrainer(step4, state, plan4, RULE,
                       os.path.join(str(tmp_path), "ckpts"), mesh=mesh4,
                       snapshot_every=0, resume=False, stream=pub,
                       telemetry=reg)
  pub.publish_base(t.state)
  root_before = pub.chain_root
  b = make_batch(7)
  pub.observe_batch(b[1])
  t.step(*shard_batch(b, t.mesh))
  assert pub.publish_delta(t.state) is not None
  seq_before = pub.seq

  mesh2, plan2, step2, _ = sparse_world(2, guard=True)
  t.resize(plan2, step_fn=step2, new_mesh=mesh2)

  # the chain was explicitly re-rooted: counted, re-bound to the new
  # plan, fingerprint-logged in the new base's manifest
  assert reg.counter("stream/re_roots").value == 1
  assert pub.plan is plan2 and pub.seq == 0
  assert pub.chain_root != root_before
  man = checkpoint.read_manifest(os.path.join(pubdir, "base"))
  note = man["extra"]["stream"]["re_rooted"]
  assert "elastic resize" in note["reason"]
  assert note["prev_chain_root"] == root_before
  assert note["prev_seq"] == seq_before
  # the re-rooted chain publishes deltas at the new world
  b2 = make_batch(8)
  pub.observe_batch(b2[1])
  t.step(*shard_batch(b2, t.mesh))
  assert pub.publish_delta(t.state) is not None


def test_re_root_requires_reason(tmp_path):
  from distributed_embeddings_tpu.streaming import (
      DeltaPublisher,
      RowGenerationTracker,
  )
  _, plan4, _, state = sparse_world(4)
  pub = DeltaPublisher(os.path.join(str(tmp_path), "pub"), plan4, RULE,
                       RowGenerationTracker(plan4),
                       telemetry=telemetry.MetricsRegistry())
  with pytest.raises(ValueError, match="reason"):
    pub.re_root(state, "")
  with pytest.raises(ValueError, match="together"):
    pub.re_root(state, "operator decision", plan=plan4)
  with pytest.raises(ValueError, match="store was passed"):
    pub.re_root(state, "operator decision", store=object())


# ---------------------------------------------------------------------------
# pod membership + preemption supervisor
# ---------------------------------------------------------------------------


def test_membership_and_target_world(tmp_path):
  pod = str(tmp_path)
  sup = elastic.PreemptionSupervisor(pod, allowed_worlds=(1, 2, 4))
  assert sup.target_world() == 1  # empty pod clamps to the floor
  elastic.register_member(pod, "leader")  # this process: alive
  assert elastic.alive_members(pod) == {"leader": os.getpid()}
  assert sup.target_world() == 1
  # three more live members (all lease this test's pid) -> world 4
  for k in range(3):
    elastic.register_member(pod, f"w{k}")
  assert sup.target_world() == 4
  # a DEAD pid's lease is stale: spawn-and-reap a child for a real
  # dead pid, then lease it
  import subprocess
  child = subprocess.Popen([sys.executable, "-c", ""])
  child.wait()
  elastic.register_member(pod, "w0", pid=child.pid)
  assert "w0" not in elastic.alive_members(pod)
  assert sup.target_world() == 2  # 3 alive -> largest legal world <= 3
  elastic.withdraw_member(pod, "w1")
  elastic.withdraw_member(pod, "w2")
  assert sup.target_world() == 1
  # foreign/torn files never crash the scan
  with open(os.path.join(pod, "members", "junk.json"), "w") as f:
    f.write("{not json")
  assert elastic.alive_members(pod) == {"leader": os.getpid()}


def test_recycled_pid_lease_is_stale(tmp_path):
  """A lease whose pid is alive but belongs to a DIFFERENT process
  incarnation (the OS recycled the pid after the member died) must not
  count as alive — the probe matches /proc start times, not just pid
  existence."""
  import json
  pod = str(tmp_path)
  elastic.register_member(pod, "w0")
  assert "w0" in elastic.alive_members(pod)
  path = elastic.member_path(pod, "w0")
  with open(path) as f:
    rec = json.load(f)
  if rec["start"] is None:
    pytest.skip("/proc start times unavailable on this platform")
  rec["start"] = int(rec["start"]) + 1  # same pid, other incarnation
  with open(path, "w") as f:
    json.dump(rec, f)
  assert "w0" not in elastic.alive_members(pod)
  # a lease without a start field (foreign writer) falls back to the
  # pid-existence probe
  del rec["start"]
  with open(path, "w") as f:
    json.dump(rec, f)
  assert "w0" in elastic.alive_members(pod)


def test_supervisor_validates_worlds(tmp_path):
  with pytest.raises(ValueError, match="allowed_worlds"):
    elastic.PreemptionSupervisor(str(tmp_path), allowed_worlds=())
  with pytest.raises(ValueError, match="allowed_worlds"):
    elastic.PreemptionSupervisor(str(tmp_path), allowed_worlds=(0, 2))


# ---------------------------------------------------------------------------
# the long chaos variant (the smoke tier rides make verify)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_preempt_long():
  sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
  import chaos_preempt
  res = chaos_preempt.run_chaos_preempt(steps=26, verbose=False,
                                        extra_cycles=True)
  assert res["ok"], res
