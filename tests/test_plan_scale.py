"""Plan-scale: the medium zoo config (311 tables) must plan fast and
trace one fused step in bounded time on the 8-device CPU mesh.

The engine's bucket/slot caches (`lookup_engine._bucket_cache`,
`_slot_map_cache`) exist exactly so thousand-table models don't trace
quadratically; this pins the property in CI at the 311-table scale
(large/jumbo at 612/1022 tables run in tools/plan_scale_dryrun.py:
plan 0.05/0.11 s, one CPU step 83/119 s — recorded in
docs/BENCHMARKS.md). Shared recipe: `utils/zoo_bench.run_zoo_plan_step`.
"""

import numpy as np
import pytest

from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.utils.zoo_bench import run_zoo_plan_step

WORLD = 8


@pytest.mark.slow
def test_medium_zoo_plan_traces_bounded():
  mesh = create_mesh(WORLD)
  r = run_zoo_plan_step("medium", mesh, WORLD, vocab_cap=1000)
  assert np.isfinite(r["loss"])
  assert r["tables"] == 311
  assert r["plan_s"] < 5.0, f"plan took {r['plan_s']:.1f}s for 311 tables"
  assert r["classes"] < 20
  # generous CI bound; the point is "minutes, not hours" (quadratic trace
  # at 311 tables would blow far past this)
  assert r["step_s"] < 300, f"trace+compile+step took {r['step_s']:.0f}s"


def test_colossal_full_scale_plan_with_row_slicing():
  """Plan the colossal config at FULL published vocab (22 TiB, 2002 tables,
  2B-row max table) over a 64-rank world with row slicing — the plan is
  pure Python, so full scale costs nothing and pins that the planner
  handles the reference's largest published config (config_v3.py:128-142)
  without dense materialization, with every table placed exactly once."""
  import time
  from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
  from distributed_embeddings_tpu.models import SYNTHETIC_MODELS, expand_tables

  cfg = SYNTHETIC_MODELS["colossal"]
  tables, tmap, hotness = expand_tables(cfg)
  assert max(t.input_dim for t in tables) == 2_000_000_000
  # at world 64 the 2B-row width-256 giant CANNOT legally shard even at
  # a tight row_slice threshold: slices are capped at min(2^k, world),
  # leaving 31.25M-row x 256-lane shards over XLA's 2^31-element buffer
  # limit — the planner must say so up front instead of failing
  # cryptically inside XLA at runtime
  with pytest.raises(ValueError, match="exceeds one TPU buffer"):
    DistEmbeddingStrategy(
        tables, 64, "memory_balanced", input_table_map=tmap,
        dense_row_threshold=4096, input_hotness=hotness, batch_hint=65536,
        row_slice_threshold=2_000_000 * 256)
  # at pod scale (1024 workers) it plans legally
  world = 1024
  t0 = time.perf_counter()
  plan = DistEmbeddingStrategy(
      tables, world, "memory_balanced", input_table_map=tmap,
      dense_row_threshold=4096, input_hotness=hotness,
      batch_hint=65536 * 16,
      row_slice_threshold=2_000_000 * 256)  # rows x width elements
  plan_s = time.perf_counter() - t0
  assert plan_s < 60, f"colossal plan took {plan_s:.1f}s"

  # every table's vocab is covered exactly once across all shards
  rows_of = {}
  for shards in plan.rank_shards:
    for sh in shards:
      if sh.col_start == 0:  # one column slice set per table is enough
        rows_of[sh.table_id] = rows_of.get(sh.table_id, 0) + sh.input_dim
  for t, c in enumerate(tables):
    assert rows_of.get(t, 0) in (c.input_dim,), (t, rows_of.get(t))
  # the 2B-row giants must be row-sliced (they exceed the threshold)
  giant = next(t for t, c in enumerate(tables)
               if c.input_dim == 2_000_000_000)
  assert len(plan.table_row_ranges[giant]) > 1
  # every rank got work, and no single-rank fused buffer exceeds the
  # 2^31-element XLA limit under a one-aux packed layout
  assert all(plan.rank_shards)
  for key in plan.class_keys:
    cp = plan.classes[key]
    stride = 2 * cp.width
    rpp = max(1, 128 // stride)
    phys_width = max(128, -(-stride // 128) * 128)
    for rows in cp.rows_per_rank:
      phys = (-(-rows // rpp)) * phys_width
      assert phys <= 2 ** 31, (key, rows)
