"""Plan-scale: the medium zoo config (311 tables) must plan fast and
trace one fused step in bounded time on the 8-device CPU mesh.

The engine's bucket/slot caches (`lookup_engine._bucket_cache`,
`_slot_map_cache`) exist exactly so thousand-table models don't trace
quadratically; this pins the property in CI at the 311-table scale
(large/jumbo at 612/1022 tables run in tools/plan_scale_dryrun.py:
plan 0.05/0.11 s, one CPU step 83/119 s — recorded in
docs/BENCHMARKS.md). Shared recipe: `utils/zoo_bench.run_zoo_plan_step`.
"""

import numpy as np
import pytest

from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.utils.zoo_bench import run_zoo_plan_step

WORLD = 8


@pytest.mark.slow
def test_medium_zoo_plan_traces_bounded():
  mesh = create_mesh(WORLD)
  r = run_zoo_plan_step("medium", mesh, WORLD, vocab_cap=1000)
  assert np.isfinite(r["loss"])
  assert r["tables"] == 311
  assert r["plan_s"] < 5.0, f"plan took {r['plan_s']:.1f}s for 311 tables"
  assert r["classes"] < 20
  # generous CI bound; the point is "minutes, not hours" (quadratic trace
  # at 311 tables would blow far past this)
  assert r["step_s"] < 300, f"trace+compile+step took {r['step_s']:.0f}s"
