"""Parity tests for the fused Pallas interaction kernels (round 5).

The kernels only run on real TPU hardware (`use_pallas_interact` gates on
backend); here they execute in Pallas interpret mode — valid for these
kernels because they have no input/output aliasing or RMW (unlike
`pallas_apply`, whose simulator exists for that reason) — and are checked
against the XLA matmul-form `_tril_products`, which in turn is covered by
`test_models.py` against the reference semantics
(`/root/reference/examples/dlrm/utils.py:92-113`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_embeddings_tpu.models.dlrm import _tril_select_np
from distributed_embeddings_tpu.ops.pallas_interact import (
    BWD_BLOCK,
    FWD_BLOCK,
    interact_parts_bwd,
    interact_parts_fwd,
    use_pallas_interact,
    xla_reference,
)

F, D = 9, 128
B = 2 * FWD_BLOCK


def _xla_reference(flat, f, k):
  m_np, _ = _tril_select_np(f, k)
  return xla_reference(flat, m_np, f)


def _mk_parts(seed, f=F, b=B):
  rng = np.random.default_rng(seed)
  return [jnp.asarray(rng.standard_normal((b, D)) * 0.3, jnp.bfloat16)
          for _ in range(f)]


@pytest.mark.parametrize("k", [-1, 0])
def test_parts_fwd_matches_xla_form(k):
  parts = _mk_parts(0)
  m_np, _ = _tril_select_np(F, k)
  got = interact_parts_fwd(parts, jnp.asarray(m_np, jnp.bfloat16),
                           interpret=True)
  flat = jnp.concatenate(parts, axis=1)
  want = _xla_reference(flat, F, k)
  np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                             rtol=2e-2, atol=2e-2)


def test_parts_bwd_matches_xla_vjp():
  k = -1
  parts = _mk_parts(1)
  m_np, _ = _tril_select_np(F, k)
  m3t = jnp.asarray(np.swapaxes(m_np, 1, 2), jnp.bfloat16)

  flat = jnp.concatenate(parts, axis=1)
  acts, vjp = jax.vjp(lambda x: _xla_reference(x, F, k), flat)
  rng = np.random.default_rng(2)
  d_acts = jnp.asarray(rng.standard_normal(acts.shape), jnp.float32)
  (want_flat,) = vjp(d_acts)

  got = interact_parts_bwd(d_acts, parts, m3t, interpret=True)
  assert len(got) == F
  for p in range(F):
    w = np.asarray(want_flat[:, p * D:(p + 1) * D], np.float32)
    g = np.asarray(got[p], np.float32)
    scale = max(np.abs(w).max(), 1e-3)
    np.testing.assert_allclose(g, w, rtol=0, atol=4e-2 * scale,
                               err_msg=f"part {p}")


def test_gate_logic():
  bf, f32 = jnp.bfloat16, jnp.float32
  if jax.default_backend() != "tpu":
    # non-TPU backends: always off, even for kernel-legal shapes
    assert not use_pallas_interact(FWD_BLOCK * 4, 27, 128, bf)
  # dtype/shape guards are backend-independent
  assert not use_pallas_interact(FWD_BLOCK * 4, 27, 128, f32)
  assert not use_pallas_interact(FWD_BLOCK * 4, 64, 128, bf)  # f too wide
  assert not use_pallas_interact(FWD_BLOCK * 4, 27, 64, bf)  # d not lane-mult
  assert not use_pallas_interact(FWD_BLOCK + 1, 27, 128, bf)  # ragged batch
  assert B % FWD_BLOCK == 0 and B % BWD_BLOCK == 0
