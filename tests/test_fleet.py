"""Fleet serving subsystem tests (`distributed_embeddings_tpu/fleet/`).

The contracts under test:

- **fleet answers are bit-exact vs the single-process ServeEngine** on
  identical requests — f32 bitwise, int8/fp8 the same bytes — across
  all-device and tiered artifacts: the owners move the memory, never
  the arithmetic (the router runs the same traced combine + forward).
- **owner-sharded load**: ``export.load(owned_ranks=...)`` materializes
  only the named ranks, refuses un-owned rank access naming the rank,
  round-trips through the crc32-manifest-last protocol, and partial
  gathers bitwise-match the full artifact's blocks row-for-row.
- **counted failover, never a wrong answer**: killing a replicated
  owner mid-load yields identical answers with ``fleet/failovers``
  counted; a rank whose every replica is dead FAILS the request
  explicitly.
- **the fleet plan is sound**: replication levels by weight, refusals
  name the misconfiguration, JSON round-trips.
- **serve-side re-shard**: ``fleet.reshard`` re-cuts a published
  artifact to a new world without a trainer checkpoint — logical rows
  byte-identical (quantized rows move wholesale with their scales).
- **fleet freshness**: every member follows the delta chain
  independently (validated folds, heartbeats); at a quiesced watermark
  the fleet serves exactly what a full subscriber serves.
"""

import os

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu.fleet import (
    FleetConfig,
    FleetDeltaFollower,
    FleetOwner,
    FleetPlan,
    FleetRouter,
    InProcTransport,
    OwnerUnavailableError,
    RemoteRefusal,
    SocketOwnerServer,
    SocketTransport,
    rank_weights_from_artifact,
    reshard,
)
from distributed_embeddings_tpu.layers.dist_model_parallel import set_weights
from distributed_embeddings_tpu.layers.embedding import TableConfig
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.ops.packed_table import sparse_rule
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.parallel.lookup_engine import PAD_ID
from distributed_embeddings_tpu.resilience import faultinject
from distributed_embeddings_tpu.serving import ServeEngine, ServeTierConfig
from distributed_embeddings_tpu.serving.export import export as serve_export
from distributed_embeddings_tpu.serving.export import load as serve_load
from distributed_embeddings_tpu.streaming import (
    DeltaPublisher,
    DeltaSubscriber,
    RowGenerationTracker,
)
from distributed_embeddings_tpu.tiering import (
    HostTierStore,
    TieringConfig,
    TieringPlan,
    init_tiered_state_from_params,
)
from distributed_embeddings_tpu.training import (
    init_sparse_state,
    make_sparse_train_step,
    shard_batch,
    shard_params,
)


class ActsModel:
  """Embedding-activations stub: every table's rows visible in preds."""

  def apply(self, variables, numerical, cats, emb_acts=None):
    del variables, numerical, cats
    return jnp.concatenate(list(emb_acts), axis=-1)


# big enough that the w16 class stays SHARDED (remote staging path) at
# the test threshold; the w8 class is small -> auto-replicated locally,
# so the mixed shard/replicate mode is always exercised
SIZES = [1536, 768, 53]
WIDTHS = [16, 16, 8]
HOTNESS = [3, 1, 2]

FLEET_CFG = FleetConfig(cache_fraction=0.1, staging_grps=64,
                        shard_min_phys_rows=16)


def _fixture(world, seed=0, **plan_kw):
  rng = np.random.default_rng(seed)
  tables = [TableConfig(s, w, combiner="sum")
            for s, w in zip(SIZES, WIDTHS)]
  plan = DistEmbeddingStrategy(tables, world, "memory_balanced",
                               dense_row_threshold=0,
                               input_hotness=HOTNESS, **plan_kw)
  weights = [rng.standard_normal((s, w)).astype(np.float32)
             for s, w in zip(SIZES, WIDTHS)]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  mesh = create_mesh(world) if world > 1 else None
  state = shard_params(init_sparse_state(plan, params, rule,
                                         optax.sgd(0.01)), mesh)
  return plan, rule, mesh, state, rng


def _mkbatch(rng, b, sizes=SIZES, hotness=HOTNESS):
  ids = []
  for s, h in zip(sizes, hotness):
    x = rng.integers(0, s, (b, h)).astype(np.int32)
    x[rng.random(x.shape) < 0.25] = PAD_ID
    ids.append(x)
  return rng.standard_normal((b, 4)).astype(np.float32), ids


def _export(tmp_path, plan, rule, state, quantize, store=None,
            name="art"):
  path = os.path.join(str(tmp_path), name)
  serve_export(path, plan, rule, state, quantize=quantize, store=store)
  return path


def _fleet(path, plan, fplan, mesh, config=FLEET_CFG, **kw):
  owners = {o: FleetOwner(path, plan, fplan.owned_ranks(o), owner_id=o)
            for o in range(fplan.n_owners)}
  transport = InProcTransport(owners)
  router = FleetRouter(ActsModel(), plan, path, fplan, transport,
                       mesh=mesh, config=config, **kw)
  return owners, transport, router


# ---------------------------------------------------------------------------
# FleetPlan
# ---------------------------------------------------------------------------


def test_fleet_plan_balanced_and_replicated():
  fp = FleetPlan.balanced(4, 2)
  assert fp.owners == ((0,), (1,), (0,), (1,))
  assert fp.owned_ranks(0) == (0, 2) and fp.owned_ranks(1) == (1, 3)
  assert fp.replicated_ranks() == ()
  # hot ranks (by weight) get R owners, replicas level by load
  fp = FleetPlan.replicated(4, 3, rank_weights=[100, 1, 1, 1],
                            replicas=2, hot_fraction=0.25)
  assert len(fp.owners_of(0)) == 2
  assert all(len(fp.owners_of(r)) == 1 for r in (1, 2, 3))
  # round-trips and equals itself
  assert FleetPlan.from_json(fp.to_json()) == fp


def test_fleet_plan_refusals():
  with pytest.raises(ValueError, match="no owner"):
    FleetPlan(2, 2, ((0,), ()))
  with pytest.raises(ValueError, match="outside"):
    FleetPlan(2, 2, ((0,), (5,)))
  with pytest.raises(ValueError, match="twice"):
    FleetPlan(2, 2, ((0, 0), (1,)))
  with pytest.raises(ValueError, match="own no rank"):
    FleetPlan(2, 3, ((0,), (1,)))
  with pytest.raises(ValueError, match="names 1 ranks"):
    FleetPlan(2, 1, ((0,),))


# ---------------------------------------------------------------------------
# owner-sharded artifact load (the export.load(owned_ranks=...) contract)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("quantize", ["f32", "int8"])
def test_owned_ranks_load_partial_and_refusal(tmp_path, quantize):
  world = 4
  plan, rule, mesh, state, rng = _fixture(world)
  path = _export(tmp_path, plan, rule, state, quantize)
  full = serve_load(path, plan)  # host-fetchable full artifact
  part = serve_load(path, plan, owned_ranks=(1, 2))
  assert part.owned_ranks == (1, 2)
  assert part.state["serve"] == {}  # no device placement, host blocks only
  for name, m in part.meta.items():
    for rank in (1, 2):
      np.testing.assert_array_equal(
          part.rank_block(name, rank), full.rank_block(name, rank))
    for rank in (0, 3):
      with pytest.raises(ValueError, match=f"rank {rank}"):
        part.rank_block(name, rank)
  # partial gathers bitwise-match the full artifact row-for-row
  owner = FleetOwner(path, plan, (1, 2), owner_id=7)
  name = next(iter(part.meta))
  m = part.meta[name]
  grps = np.arange(min(8, m.packed.phys_rows), dtype=np.int64)
  got = m.from_disk(np.asarray(owner.rpc_gather(name, 1, grps)["rows"]))
  np.testing.assert_array_equal(got, full.rank_block(name, 1)[grps])
  with pytest.raises(ValueError, match="rank 0"):
    owner.rpc_gather(name, 0, grps)


def test_owned_ranks_load_detects_corruption(tmp_path):
  world = 2
  plan, rule, mesh, state, rng = _fixture(world)
  path = _export(tmp_path, plan, rule, state, "f32")
  victim = None
  for fn in sorted(os.listdir(path)):
    if fn.startswith("serve_") and fn.endswith("_r1.npy"):
      victim = os.path.join(path, fn)
      break
  faultinject.bitflip_file(victim)
  with pytest.raises(ValueError, match="integrity"):
    serve_load(path, plan, owned_ranks=(1,))


# ---------------------------------------------------------------------------
# fleet == single process, bit for bit
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("world,quantize", [(2, "f32"), (4, "f32"),
                                            (2, "int8"), (2, "fp8")])
def test_fleet_bitexact_vs_single_process(tmp_path, world, quantize):
  plan, rule, mesh, state, rng = _fixture(world)
  path = _export(tmp_path, plan, rule, state, quantize)
  art = serve_load(path, plan, mesh=mesh)
  single = ServeEngine(ActsModel(), plan, art, mesh=mesh)
  fplan = FleetPlan.balanced(world, 2)
  owners, transport, router = _fleet(path, plan, fplan, mesh)
  # the big class really is sharded (remote path exercised); at world 2
  # the 53-row w8 table stays below the shard threshold, so the mixed
  # shard/replicate mode is exercised too (at world 4 memory_balanced
  # column-slices the big table into the w8 class — all sharded)
  assert router.tplan is not None
  if world == 2:
    assert router.replicated_names
  rpc0 = router.store._counters["rpcs"].value
  for _ in range(3):
    numerical, ids = _mkbatch(rng, 4 * world)
    np.testing.assert_array_equal(single.predict(numerical, ids),
                                  router.predict(numerical, ids))
  assert router.store._counters["rpcs"].value > rpc0


def test_fleet_tiered_artifact_bitexact(tmp_path):
  world = 2
  rng = np.random.default_rng(3)
  tables = [TableConfig(s, w, combiner="sum")
            for s, w in zip(SIZES, WIDTHS)]
  plan = DistEmbeddingStrategy(tables, world, "memory_balanced",
                               dense_row_threshold=0,
                               input_hotness=HOTNESS,
                               host_row_threshold=512)
  assert plan.host_tier_class_keys()  # the big class is host-tier
  weights = [rng.standard_normal((s, w)).astype(np.float32)
             for s, w in zip(SIZES, WIDTHS)]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  mesh = create_mesh(world)
  tplan = TieringPlan(plan, rule, TieringConfig(cache_fraction=0.25,
                                                staging_grps=64))
  store = HostTierStore(tplan)
  state = shard_params(
      init_tiered_state_from_params(tplan, store, rule, params,
                                    optax.sgd(0.01), mesh=mesh), mesh)
  path = _export(tmp_path, plan, rule, state, "f32", store=store)
  art = serve_load(path, plan, mesh=mesh)
  single = ServeEngine(ActsModel(), plan, art, mesh=mesh,
                       tier_config=ServeTierConfig(cache_fraction=0.25,
                                                   staging_grps=32))
  fplan = FleetPlan.balanced(world, 2)
  owners, transport, router = _fleet(path, plan, fplan, mesh)
  for _ in range(2):
    numerical, ids = _mkbatch(rng, 4 * world)
    np.testing.assert_array_equal(single.predict(numerical, ids),
                                  router.predict(numerical, ids))


# ---------------------------------------------------------------------------
# failover: counted, never a wrong answer
# ---------------------------------------------------------------------------


def test_fleet_failover_and_dead_rank(tmp_path):
  world = 2
  plan, rule, mesh, state, rng = _fixture(world)
  path = _export(tmp_path, plan, rule, state, "f32")
  art = serve_load(path, plan, mesh=mesh)
  single = ServeEngine(ActsModel(), plan, art, mesh=mesh)
  # full 2-way replication: every rank survives one dead owner
  fplan = FleetPlan.replicated(world, 2, replicas=2, hot_fraction=1.0)
  assert fplan.replicated_ranks() == tuple(range(world))
  cfg = FleetConfig(cache_fraction=0.1, staging_grps=64,
                    shard_min_phys_rows=16, revive_after_s=3600.0)
  from distributed_embeddings_tpu.telemetry import MetricsRegistry
  owners, transport, router = _fleet(path, plan, fplan, mesh, config=cfg,
                                     telemetry=MetricsRegistry())
  numerical, ids = _mkbatch(rng, 4 * world)
  want = single.predict(numerical, ids)
  np.testing.assert_array_equal(want, router.predict(numerical, ids))
  transport.kill(0)
  got = router.predict(numerical, ids)
  np.testing.assert_array_equal(want, got)  # zero wrong answers
  assert router.store._counters["failovers"].value >= 1
  assert router.telemetry.gauge("fleet/owners_dead").value == 1
  # second dispatch: owner 0 is marked dead, replicas answer directly
  np.testing.assert_array_equal(want, router.predict(numerical, ids))
  # every replica dead -> the request FAILS, never a substituted row
  transport.kill(1)
  with pytest.raises(OwnerUnavailableError, match="every replica"):
    router.predict(numerical, ids)
  assert router.store._counters["dead_rank_errors"].value >= 1
  # ORGANIC revival: once the revive window elapses, the next dispatch
  # pings the recovered owners back into the rotation — no manual heal
  transport.revive(0)
  transport.revive(1)
  object.__setattr__(cfg, "revive_after_s", 0.0)  # window elapsed "now"
  np.testing.assert_array_equal(want, router.predict(numerical, ids))
  assert not router.store._dead  # both owners back in the rotation


def test_fleet_transient_rpc_faults_absorbed(tmp_path):
  """A flaky fleet network (injected at the ``fleet_rpc`` site) is
  absorbed by the bounded retry — counted, no failover, no error."""
  from distributed_embeddings_tpu.resilience import retry as _retry
  world = 2
  plan, rule, mesh, state, rng = _fixture(world)
  path = _export(tmp_path, plan, rule, state, "f32")
  fplan = FleetPlan.balanced(world, 2)
  owners = {o: FleetOwner(path, plan, fplan.owned_ranks(o), owner_id=o)
            for o in range(2)}
  transport = InProcTransport(owners)
  from distributed_embeddings_tpu.telemetry import MetricsRegistry
  router = FleetRouter(ActsModel(), plan, path, fplan, transport,
                       mesh=mesh, config=FLEET_CFG,
                       telemetry=MetricsRegistry(),  # isolated accounting
                       retry_policy=_retry.RetryPolicy(retries=3,
                                                       backoff=0.0))
  numerical, ids = _mkbatch(rng, 4 * world)
  want = router.predict(numerical, ids)
  inj = faultinject.FaultInjector().fail_first("fleet_rpc", 2)
  with faultinject.injected(inj):
    got = router.predict(numerical, ids)
  np.testing.assert_array_equal(want, got)
  assert router.store._counters["rpc_retries"].value >= 2
  assert router.store._counters["failovers"].value == 0


def test_fleet_handshake_refusals(tmp_path):
  world = 2
  plan, rule, mesh, state, rng = _fixture(world)
  path_f32 = _export(tmp_path, plan, rule, state, "f32", name="a_f32")
  path_int8 = _export(tmp_path, plan, rule, state, "int8", name="a_int8")
  fplan = FleetPlan.balanced(world, 2)
  # owner 1 serves a different quantize mode than the router's artifact
  owners = {0: FleetOwner(path_f32, plan, fplan.owned_ranks(0)),
            1: FleetOwner(path_int8, plan, fplan.owned_ranks(1),
                          owner_id=1)}
  with pytest.raises(ValueError, match="quantize"):
    FleetRouter(ActsModel(), plan, path_f32, fplan,
                InProcTransport(owners), mesh=mesh, config=FLEET_CFG)
  # fleet plan names an owner that does not hold the rank
  owners = {0: FleetOwner(path_f32, plan, (0,)),
            1: FleetOwner(path_f32, plan, (1,), owner_id=1)}
  # rank 1 assigned to owner 0, whose store holds only rank 0
  bad = FleetPlan(world, 2, ((0,), (0, 1)))
  with pytest.raises(ValueError, match="owner stores disagree|disagree"):
    FleetRouter(ActsModel(), plan, path_f32, bad,
                InProcTransport(owners), mesh=mesh, config=FLEET_CFG)


# ---------------------------------------------------------------------------
# socket transport
# ---------------------------------------------------------------------------


def test_fleet_over_sockets_bitexact(tmp_path):
  world = 2
  plan, rule, mesh, state, rng = _fixture(world)
  path = _export(tmp_path, plan, rule, state, "f32")
  art = serve_load(path, plan, mesh=mesh)
  single = ServeEngine(ActsModel(), plan, art, mesh=mesh)
  fplan = FleetPlan.balanced(world, 2)
  owners = {o: FleetOwner(path, plan, fplan.owned_ranks(o), owner_id=o)
            for o in range(2)}
  servers = {o: SocketOwnerServer(owners[o]) for o in owners}
  transport = SocketTransport({o: s.address for o, s in servers.items()})
  try:
    router = FleetRouter(ActsModel(), plan, path, fplan, transport,
                         mesh=mesh, config=FLEET_CFG)
    numerical, ids = _mkbatch(rng, 4 * world)
    np.testing.assert_array_equal(single.predict(numerical, ids),
                                  router.predict(numerical, ids))
    # a remote refusal (wrong rank) maps to RemoteRefusal, not OSError:
    # it must NOT be retried or failed over
    with pytest.raises(RemoteRefusal, match="not owned"):
      transport.call(0, "gather", name=next(iter(art.meta)),
                     rank=fplan.owned_ranks(1)[0],
                     grps=np.zeros((1,), np.int64))
  finally:
    transport.close()
    for s in servers.values():
      s.close()


# ---------------------------------------------------------------------------
# serve-side re-shard (fleet resize without the trainer)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("quantize", ["f32", "int8"])
def test_reshard_artifact_to_new_world(tmp_path, quantize):
  # hotness 1 everywhere: a serve answer is then exactly the dequantized
  # row per id, so cross-world equality checks ROW fidelity bit-for-bit
  # (cross-world combine-order equality is not claimed anywhere); four
  # tables so every world has at least one per rank without col slicing
  sizes = [1536, 768, 512, 384]
  widths = [16, 16, 16, 16]
  hotness = [1, 1, 1, 1]
  rng = np.random.default_rng(11)
  tables = [TableConfig(s, w, combiner="sum")
            for s, w in zip(sizes, widths)]

  def build(world):
    # a huge column_slice_threshold keeps tables un-col-sliced at every
    # world, so the class composition is world-invariant (auto slicing
    # cuts differently per world — reshard refuses that, by design)
    return DistEmbeddingStrategy(tables, world, "basic",
                                 dense_row_threshold=0,
                                 column_slice_threshold=10**9,
                                 input_hotness=hotness)

  plan4, plan2 = build(4), build(2)
  weights = [rng.standard_normal((s, w)).astype(np.float32)
             for s, w in zip(sizes, widths)]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan4, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  mesh4 = create_mesh(4)
  state = shard_params(init_sparse_state(plan4, params, rule,
                                         optax.sgd(0.01)), mesh4)
  src = os.path.join(str(tmp_path), "src")
  serve_export(src, plan4, rule, state, quantize=quantize)
  dst = os.path.join(str(tmp_path), "dst")
  man = reshard(src, plan4, dst, plan2)
  assert man["extra"]["resharded"]["dst_world"] == 2
  # the re-cut artifact loads and verifies under the NEW plan
  mesh2 = create_mesh(2)
  art4 = serve_load(src, plan4, mesh=mesh4)
  art2 = serve_load(dst, plan2, mesh=mesh2)
  eng4 = ServeEngine(ActsModel(), plan4, art4, mesh=mesh4)
  eng2 = ServeEngine(ActsModel(), plan2, art2, mesh=mesh2)
  b = 8
  ids = [rng.integers(0, s, (b, 1)).astype(np.int32) for s in sizes]
  numerical = rng.standard_normal((b, 4)).astype(np.float32)
  np.testing.assert_array_equal(eng4.predict(numerical, ids),
                                eng2.predict(numerical, ids))


def test_reshard_refuses_wrong_source_plan(tmp_path):
  plan2, rule, mesh, state, rng = _fixture(2)
  path = _export(tmp_path, plan2, rule, state, "f32")
  other = DistEmbeddingStrategy(
      [TableConfig(s, w, combiner="sum")
       for s, w in zip(SIZES, WIDTHS)], 4, "memory_balanced",
      dense_row_threshold=0, input_hotness=HOTNESS)
  with pytest.raises(ValueError, match="EXPORTED under"):
    reshard(path, other, os.path.join(str(tmp_path), "out"), plan2)


# ---------------------------------------------------------------------------
# fleet freshness: every member follows the delta chain
# ---------------------------------------------------------------------------


def test_fleet_delta_followers_converge_bitexact(tmp_path):
  world = 2
  plan, rule, mesh, state, rng = _fixture(world, seed=5)
  batch0 = _mkbatch(rng, 4 * world)
  step = make_sparse_train_step(
      ActsModel(), plan,
      lambda preds, labels: jnp.mean((jnp.sum(preds, -1) - labels) ** 2),
      optax.sgd(0.01), rule, mesh, state,
      (jnp.asarray(batch0[0]), tuple(jnp.asarray(x) for x in batch0[1]),
       jnp.zeros((4 * world,), jnp.float32)), donate=False)
  pub = os.path.join(str(tmp_path), "pub")
  tracker = RowGenerationTracker(plan)
  publisher = DeltaPublisher(pub, plan, rule, tracker, quantize="f32")

  def train(state, n):
    for _ in range(n):
      numerical, ids = _mkbatch(rng, 4 * world)
      labels = rng.integers(0, 2, 4 * world).astype(np.float32)
      publisher.observe_batch(ids)
      state, _ = step(state, *shard_batch(
          (numerical, tuple(jnp.asarray(x) for x in ids), labels), mesh))
    return state

  state = train(state, 2)
  base = publisher.publish_base(state)
  # the reference: a full single-process subscriber on the same chain
  sub = DeltaSubscriber.from_artifact(ActsModel(), plan, pub, mesh=mesh)
  # the fleet: owners + router on the base, one follower each
  fplan = FleetPlan.balanced(world, 2)
  owners = {o: FleetOwner(base, plan, fplan.owned_ranks(o), owner_id=o)
            for o in range(2)}
  transport = InProcTransport(owners)
  router = FleetRouter(ActsModel(), plan, base, fplan, transport,
                       mesh=mesh, config=FLEET_CFG)
  followers = [FleetDeltaFollower(owners[o], pub, plan,
                                  subscriber_id=f"owner-{o}")
               for o in range(2)]
  followers.append(FleetDeltaFollower(router, pub, plan,
                                      subscriber_id="router"))

  state = train(state, 2)
  assert publisher.publish_delta(state) is not None
  assert sub.poll_once() == 1
  for f in followers:
    assert f.poll_once() == 1
    assert f.last_refusal is None
  assert router.step == sub.engine.step
  # each promote records the /healthz readiness detail: the served
  # watermark and the last-promote wall time (staleness probe source)
  for f in followers:
    assert f.telemetry.peek("stream/served_step").value == router.step
    assert f.telemetry.peek("stream/last_promote_unixtime").value > 0
  assert sub.telemetry.peek("stream/served_step").value \
      == sub.engine.step
  numerical, ids = _mkbatch(rng, 4 * world)
  np.testing.assert_array_equal(sub.predict(numerical, ids),
                                router.predict(numerical, ids))
  # heartbeats: the publisher's quorum sees every fleet member
  from distributed_embeddings_tpu.streaming import read_heartbeats
  live, _ = read_heartbeats(pub, ttl_s=60.0)
  assert {"owner-0", "owner-1", "router"} <= set(live)
  assert all(hb["applied_seq"] == 1 for hb in live.values())


def test_fleet_follower_refuses_torn_delta(tmp_path):
  world = 2
  plan, rule, mesh, state, rng = _fixture(world, seed=6)
  pub = os.path.join(str(tmp_path), "pub")
  tracker = RowGenerationTracker(plan)
  publisher = DeltaPublisher(pub, plan, rule, tracker, quantize="f32")
  numerical, ids = _mkbatch(rng, 4 * world)
  publisher.observe_batch(ids)
  base = publisher.publish_base(state)
  owner = FleetOwner(base, plan, (0,), owner_id=0)
  follower = FleetDeltaFollower(owner, pub, plan, subscriber_id="o0")
  # advance a row and publish, then corrupt the delta payload
  publisher.observe_batch(ids)
  dpath = publisher.publish_delta(state, force=True)
  victim = None
  for fn in sorted(os.listdir(dpath)):
    if fn.startswith("rows_"):
      victim = os.path.join(dpath, fn)
      break
  faultinject.bitflip_file(victim)
  assert follower.poll_once() == 0
  assert follower.last_refusal is not None
  assert follower.last_refusal["field"] == "checksums"
  assert follower.applied_seq == 0  # held at the last valid artifact


# ---------------------------------------------------------------------------
# fleet plan weights from the artifact's observed counts
# ---------------------------------------------------------------------------


def test_rank_weights_from_artifact(tmp_path):
  # no counts (all-device artifact): uniform fallback
  plan, rule, mesh, state, rng = _fixture(2)
  path = _export(tmp_path, plan, rule, state, "f32")
  w = rank_weights_from_artifact(path, 2)
  np.testing.assert_array_equal(w, np.ones(2))


# ---------------------------------------------------------------------------
# distributed tracing across the fleet wire (round 18)
# ---------------------------------------------------------------------------


def test_fleet_trace_context_crosses_socket_transport(tmp_path):
  """A request context minted at the edge must ride the TCP framing:
  the owner-side gather span adopts the request's trace id and parents
  to the router's rpc span — even though the gather runs on the owner
  server's handler thread, where no thread-local could have leaked."""
  from distributed_embeddings_tpu import telemetry

  world = 2
  plan, rule, mesh, state, rng = _fixture(world)
  path = _export(tmp_path, plan, rule, state, "f32")
  fplan = FleetPlan.balanced(world, 2)
  owners = {o: FleetOwner(path, plan, fplan.owned_ranks(o), owner_id=o)
            for o in range(2)}
  servers = {o: SocketOwnerServer(owners[o]) for o in owners}
  transport = SocketTransport({o: s.address for o, s in servers.items()})
  try:
    router = FleetRouter(ActsModel(), plan, path, fplan, transport,
                         mesh=mesh, config=FLEET_CFG)
    numerical, ids = _mkbatch(rng, 4 * world)
    with telemetry.tracing() as tr:
      with telemetry.use_context(telemetry.mint_context(["req-7"])):
        router.predict(numerical, ids)
    evs = [e for e in tr.to_chrome()["traceEvents"]
           if e.get("ph") == "X"]
    gathers = [e for e in evs if e["name"] == "fleet/owner/gather"
               and (e.get("args") or {}).get("trace_id")]
    rpcs = {e["args"]["span_id"]: e for e in evs
            if e["name"] == "fleet/rpc" and "span_id" in
            (e.get("args") or {})}
    assert gathers, "no context-carrying gather spans recorded"
    for g in gathers:
      # the id minted at the edge reached the owner over the wire...
      assert g["args"]["trace_id"] == "req-7"
      # ...as the child of the specific rpc attempt that carried it,
      # nested inside it on the (shared same-process) clock
      rpc = rpcs[g["args"]["parent_span_id"]]
      assert rpc["args"]["trace_id"] == "req-7"
      assert rpc["ts"] <= g["ts"]
      assert g["ts"] + g["dur"] <= rpc["ts"] + rpc["dur"]
    # fan-out/route spans share the same trace
    assert any(e["name"] == "fleet/fanout"
               and (e.get("args") or {}).get("trace_id") == "req-7"
               for e in evs)
  finally:
    transport.close()
    for s in servers.values():
      s.close()


def test_fleet_clock_handshake_and_trace_collection(tmp_path):
  """Every owner answers the ``clock`` RPC (bounded-uncertainty offset
  per owner) and the ``trace`` RPC (its span buffer, or None when
  tracing is off in that process)."""
  from distributed_embeddings_tpu import telemetry

  world = 2
  plan, rule, mesh, state, rng = _fixture(world)
  path = _export(tmp_path, plan, rule, state, "f32")
  fplan = FleetPlan.balanced(world, 2)
  owners, transport, router = _fleet(path, plan, fplan, mesh)
  offsets = router.store.clock_offsets(rounds=4)
  assert sorted(offsets) == [0, 1]
  for off in offsets.values():
    # same process, same CLOCK_MONOTONIC: the offset is bounded by the
    # handshake's own stated uncertainty
    assert abs(off.offset_ns) <= off.uncertainty_ns
    assert off.uncertainty_ns >= 1 and off.rtt_ns >= 0
  # tracing disabled in the "owner process": trace collection says so
  assert router.store.collect_traces() == {0: None, 1: None}
  with telemetry.tracing():
    numerical, ids = _mkbatch(rng, 4 * world)
    router.predict(numerical, ids)
    traces = router.store.collect_traces()
  for o in (0, 1):
    assert traces[o] is not None and "traceEvents" in traces[o]
  router.close()


def test_injected_rpc_fault_is_an_attempt_span(tmp_path):
  """A chaos-injected rpc failure records its own ``fleet/rpc`` span —
  the one-span-per-ATTEMPT contract holds for faults fired at the
  ``fleet_rpc`` site, not just transport errors, so retry storms under
  chaos are visible on the merged timeline."""
  from distributed_embeddings_tpu import telemetry
  from distributed_embeddings_tpu.resilience import retry as _retry

  world = 2
  plan, rule, mesh, state, rng = _fixture(world)
  path = _export(tmp_path, plan, rule, state, "f32")
  fplan = FleetPlan.balanced(world, 2)
  owners = {o: FleetOwner(path, plan, fplan.owned_ranks(o), owner_id=o)
            for o in range(2)}
  transport = InProcTransport(owners)
  router = FleetRouter(ActsModel(), plan, path, fplan, transport,
                       mesh=mesh, config=FLEET_CFG,
                       retry_policy=_retry.RetryPolicy(retries=3,
                                                       backoff=0.0))
  numerical, ids = _mkbatch(rng, 4 * world)
  router.predict(numerical, ids)  # compile off the traced run
  with telemetry.tracing() as tr:
    router.predict(numerical, ids)
  baseline = sum(e.get("name") == "fleet/rpc"
                 for e in tr.to_chrome()["traceEvents"])
  inj = faultinject.FaultInjector().fail_first("fleet_rpc", 2)
  with telemetry.tracing() as tr:
    with faultinject.injected(inj):
      router.predict(numerical, ids)
  spans = [e for e in tr.to_chrome()["traceEvents"]
           if e.get("ph") == "X" and e["name"] == "fleet/rpc"]
  # the two injected failures each burned an attempt span on top of
  # the fault-free run's count
  assert len(spans) == baseline + 2, (len(spans), baseline)


def test_follower_stop_leaves_healthz_quorum(tmp_path):
  """A deliberately stopped follower removes its promote gauges —
  keyed AND the unkeyed last-writer pair — so the /healthz most-stale
  scan never reports a decommissioned member as stalled forever."""
  world = 2
  plan, rule, mesh, state, rng = _fixture(world, seed=6)
  batch0 = _mkbatch(rng, 4 * world)
  step = make_sparse_train_step(
      ActsModel(), plan,
      lambda preds, labels: jnp.mean((jnp.sum(preds, -1) - labels) ** 2),
      optax.sgd(0.01), rule, mesh, state,
      (jnp.asarray(batch0[0]), tuple(jnp.asarray(x) for x in batch0[1]),
       jnp.zeros((4 * world,), jnp.float32)), donate=False)
  pub = os.path.join(str(tmp_path), "pub")
  tracker = RowGenerationTracker(plan)
  publisher = DeltaPublisher(pub, plan, rule, tracker, quantize="f32")
  numerical, ids = _mkbatch(rng, 4 * world)
  labels = rng.integers(0, 2, 4 * world).astype(np.float32)
  publisher.observe_batch(ids)
  state, _ = step(state, *shard_batch(
      (numerical, tuple(jnp.asarray(x) for x in ids), labels), mesh))
  base = publisher.publish_base(state)
  fplan = FleetPlan.balanced(world, 2)
  owners = {o: FleetOwner(base, plan, fplan.owned_ranks(o), owner_id=o)
            for o in range(2)}
  follower = FleetDeltaFollower(owners[0], pub, plan,
                                subscriber_id="f0")
  reg = follower.telemetry
  publisher.observe_batch(ids)
  state, _ = step(state, *shard_batch(
      (numerical, tuple(jnp.asarray(x) for x in ids), labels), mesh))
  assert publisher.publish_delta(state) is not None
  assert follower.poll_once() == 1
  assert reg.peek("stream/served_step/f0") is not None
  assert reg.peek("stream/last_promote_unixtime/f0") is not None
  follower.stop()
  assert reg.peek("stream/served_step/f0") is None
  assert reg.peek("stream/last_promote_unixtime/f0") is None
  # the unkeyed last-writer pair goes too: in the single-member
  # topology nothing else would ever refresh it, so leaving it would
  # read as a stalled subscriber forever
  assert reg.peek("stream/served_step") is None
  assert reg.peek("stream/last_promote_unixtime") is None


# ---------------------------------------------------------------------------
# hedged gathers: first answer wins, bit-exact, exactly-once counted
# ---------------------------------------------------------------------------


def _hedged_fleet(tmp_path, plan, rule, mesh, state, rng, world,
                  **cfg_over):
  """Fully replicated 2-owner fleet with hedging armed low enough that
  an injected slow replica always trips it."""
  from distributed_embeddings_tpu.telemetry import MetricsRegistry
  path = _export(tmp_path, plan, rule, state, "f32")
  fplan = FleetPlan.replicated(world, 2, replicas=2, hot_fraction=1.0)
  cfg_kw = dict(cache_fraction=0.1, staging_grps=64,
                shard_min_phys_rows=16, revive_after_s=3600.0,
                hedge_quantile=0.5, hedge_min_s=0.005,
                hedge_min_samples=5)
  cfg_kw.update(cfg_over)
  cfg = FleetConfig(**cfg_kw)
  owners, transport, router = _fleet(path, plan, fplan, mesh, config=cfg,
                                     telemetry=MetricsRegistry())
  return path, owners, transport, router


def _settle(counter, want, deadline_s=5.0):
  """Wait for a counter racing against a late loser thread to settle."""
  import time
  t0 = time.time()
  while counter.value < want and time.time() - t0 < deadline_s:
    time.sleep(0.005)
  return counter.value


def test_hedged_gather_first_answer_wins_bit_exact(tmp_path):
  world = 2
  plan, rule, mesh, state, rng = _fixture(world)
  path, owners, transport, router = _hedged_fleet(
      tmp_path, plan, rule, mesh, state, rng, world)
  art = serve_load(path, plan, mesh=mesh)
  single = ServeEngine(ActsModel(), plan, art, mesh=mesh)
  numerical, ids = _mkbatch(rng, 4 * world)
  want = single.predict(numerical, ids)
  for _ in range(8):  # warm the per-owner recent-latency windows
    np.testing.assert_array_equal(want, router.predict(numerical, ids))
  c = router.store._counters
  assert c["hedges"].value == 0  # a healthy fleet never hedges
  # one replica turns slow: ONLY requests whose primary is owner 0
  # stall past the recent quantile and duplicate to the other replica
  inj = faultinject.FaultInjector()
  inj.delay_when("fleet_rpc", 0.25, owner=0)
  with faultinject.injected(inj):
    got = router.predict(numerical, ids)
  np.testing.assert_array_equal(want, got)  # same f32 bytes, hedged
  assert c["hedges"].value >= 1
  assert c["hedges_won"].value >= 1
  assert c["failovers"].value == 0  # slow is not dead: nobody abandoned
  # the slow loser finishes eventually: counted wasted EXACTLY once per
  # hedge that raced to completion, never more
  wasted = _settle(c["hedges_wasted"], c["hedges"].value)
  assert wasted <= c["hedges"].value
  router.close()


def test_hedged_gather_exactly_once_accounting(tmp_path):
  """Pin the counters on ONE hedged gather: a retried attempt inside
  the race does not double-count the hedge, and the loser's eventual
  completion is one wasted increment."""
  from distributed_embeddings_tpu.resilience import retry as _retry
  world = 2
  plan, rule, mesh, state, rng = _fixture(world)
  path, owners, transport, router = _hedged_fleet(
      tmp_path, plan, rule, mesh, state, rng, world)
  store = router.store
  store.retry_policy = _retry.RetryPolicy(retries=3, backoff=0.0)
  name = next(n for n in sorted(store.meta)
              if n not in router.replicated_names)
  rank = 0
  order = store._replica_order(store.fplan.owners_of(rank))
  grps = np.arange(4, dtype=np.int64)
  c = store._counters
  inj = faultinject.FaultInjector()
  # primary: one transient fault (absorbed by retry), THEN slow — the
  # hedge must fire once for the logical gather, not once per attempt
  inj.fail_first("fleet_rpc", 1)
  inj.delay_when("fleet_rpc", 0.25, owner=order[0])
  with faultinject.injected(inj):
    out = store._gather_call(rank, name=name, rank=rank, grps=grps)
  direct = owners[order[1]].rpc_gather(name, rank, grps)
  np.testing.assert_array_equal(np.asarray(out["rows"]),
                                np.asarray(direct["rows"]))
  assert c["hedges"].value == 1
  assert c["hedges_won"].value == 1
  # the slow primary completes after losing: exactly one wasted, even
  # given time to double-count
  assert _settle(c["hedges_wasted"], 1) == 1
  import time
  time.sleep(0.1)
  assert c["hedges_wasted"].value == 1
  # the transient WAS retried inside the losing attempt — and the
  # retried attempt did not re-count the hedge
  assert c["rpc_retries"].value >= 1
  assert c["hedges"].value == 1
  assert c["failovers"].value == 0
  router.close()


def test_hedged_gather_every_replica_dead_fails(tmp_path):
  world = 2
  plan, rule, mesh, state, rng = _fixture(world)
  path, owners, transport, router = _hedged_fleet(
      tmp_path, plan, rule, mesh, state, rng, world)
  numerical, ids = _mkbatch(rng, 4 * world)
  want = router.predict(numerical, ids)
  transport.kill(0)
  # one dead replica: the race degrades to counted failover, answers
  # stay bit-exact
  np.testing.assert_array_equal(want, router.predict(numerical, ids))
  transport.kill(1)
  with pytest.raises(OwnerUnavailableError, match="every replica"):
    router.predict(numerical, ids)
  assert router.store._counters["dead_rank_errors"].value >= 1
  router.close()


def test_hedging_disabled_is_a_true_noop(tmp_path):
  """hedge_quantile=None (the default): no hedge counters move, no
  latency windows exist — the pre-control router, byte for byte."""
  world = 2
  plan, rule, mesh, state, rng = _fixture(world)
  path, owners, transport, router = _hedged_fleet(
      tmp_path, plan, rule, mesh, state, rng, world, hedge_quantile=None)
  numerical, ids = _mkbatch(rng, 4 * world)
  inj = faultinject.FaultInjector()
  inj.delay_when("fleet_rpc", 0.05, owner=0)
  with faultinject.injected(inj):
    router.predict(numerical, ids)  # slow replica, nobody hedges
  c = router.store._counters
  assert c["hedges"].value == 0
  assert c["hedges_won"].value == 0
  assert c["hedges_wasted"].value == 0
  assert router.store._gather_window == {}  # not even allocated
  router.close()


def test_scale_down_drains_inflight_gathers(tmp_path):
  """A scale-DOWN drains the departing owner's in-flight gathers
  (bounded) before the rotation forgets it — counted
  ``fleet/drained_gathers``; a wedged gather only holds actuation to
  the deadline (drain_owner returns False, the call fails over like
  any owner death)."""
  import threading
  import time

  world = 2
  plan, rule, mesh, state, rng = _fixture(world)
  path = _export(tmp_path, plan, rule, state, "f32")
  fplan2 = FleetPlan.balanced(world, 2)
  owners, transport, router = _fleet(path, plan, fplan2, mesh)
  store = router.store

  # a gather in flight on owner 1 (the one being dropped)
  with store._lock:
    store._inflight[1] += 1

  def finish():
    time.sleep(0.15)
    with store._lock:
      store._inflight[1] -= 1

  t = threading.Thread(target=finish)
  t.start()
  owners1 = {0: FleetOwner(path, plan, (0, 1), owner_id=0)}
  t0 = time.monotonic()
  router.apply_fleet(FleetPlan.balanced(world, 1), InProcTransport(owners1))
  waited = time.monotonic() - t0
  t.join()
  assert waited >= 0.1  # actuation waited for the in-flight gather
  assert router.fleet_plan.n_owners == 1
  assert store._counters["drained_gathers"].value == 1

  # wedged: the drain is bounded, not an unbounded wait
  with store._lock:
    store._inflight[0] += 1
  t0 = time.monotonic()
  assert store.drain_owner(0, deadline_s=0.05) is False
  assert time.monotonic() - t0 < 2.0
  with store._lock:
    store._inflight[0] -= 1
  # nothing NEW completed during the wedged wait
  assert store._counters["drained_gathers"].value == 1
  router.close()
