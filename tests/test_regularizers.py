"""Embedding regularizer / constraint parity tests.

Reference `embedding.py:62-70,96-100` accepts
embeddings_regularizer / activity_regularizer / embeddings_constraint;
round 1 silently dropped them (VERDICT item 6). These pin:
- layer-level semantics (sown penalties, constraint projection);
- plan-level training integration (make_train_step with plan=...);
- the planner's explicit rejections (activity reg in distributed path,
  constraint on a column-sliced table, fused-path NotImplementedError).
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_embeddings_tpu.layers import (
    DistEmbeddingStrategy,
    Embedding,
    TableConfig,
    collect_regularization_losses,
)
from distributed_embeddings_tpu.layers.embedding import (
    resolve_constraint,
    resolve_regularizer,
)
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.training import (
    make_train_step,
    plan_constraint_fn,
    plan_regularizer_fn,
    shard_batch,
    shard_params,
)

WORLD = 8


def test_layer_sows_regularizer_losses():
  layer = Embedding(input_dim=10, output_dim=4,
                    embeddings_regularizer="l2",
                    activity_regularizer=lambda y: 0.5 * jnp.sum(y * y))
  x = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
  params = {"params": layer.init(jax.random.PRNGKey(0), x)["params"]}
  out, mutated = layer.apply(params, x, mutable=["losses"])
  table = params["params"]["embeddings"]
  want = 0.01 * np.sum(np.square(table)) + 0.5 * np.sum(np.square(out))
  got = float(collect_regularization_losses(mutated))
  np.testing.assert_allclose(got, want, rtol=1e-5)


def test_shared_layer_counts_weight_penalty_once():
  """Keras semantics: a shared layer's WEIGHT penalty counts once per
  variable regardless of call count; the ACTIVITY penalty counts per call."""
  import flax.linen as nn

  class TwoCalls(nn.Module):
    @nn.compact
    def __call__(self, a, b):
      layer = Embedding(input_dim=10, output_dim=4,
                        embeddings_regularizer="l2",
                        activity_regularizer=lambda y: jnp.sum(y * y))
      return layer(a) + layer(b)

  m = TwoCalls()
  a = jnp.asarray([1, 2])
  b = jnp.asarray([3, 4])
  params = {"params": m.init(jax.random.PRNGKey(0), a, b)["params"]}
  _, mut = m.apply(params, a, b, mutable=["losses"])
  table = np.asarray(params["params"]["Embedding_0"]["embeddings"])
  want = 0.01 * np.sum(np.square(table)) \
      + np.sum(np.square(table[np.asarray(a)])) \
      + np.sum(np.square(table[np.asarray(b)]))
  np.testing.assert_allclose(
      float(collect_regularization_losses(mut)), want, rtol=1e-5)


def test_layer_constraint_projection():
  layer = Embedding(input_dim=6, output_dim=4, embeddings_constraint="non_neg")
  w = jnp.asarray([[-1.0, 2.0, -3.0, 4.0]] * 6)
  got = layer.apply_constraint(w)
  assert float(jnp.min(got)) == 0.0 and float(got[0, 1]) == 2.0
  unit = resolve_constraint("unit_norm")(w)
  np.testing.assert_allclose(
      np.linalg.norm(np.asarray(unit), axis=-1), 1.0, rtol=1e-4)
  mx = resolve_constraint("max_norm")(w)
  assert np.all(np.linalg.norm(np.asarray(mx), axis=-1) <= 2.0 + 1e-5)


def test_resolvers_reject_unknown():
  with pytest.raises(ValueError):
    resolve_regularizer("l3")
  with pytest.raises(ValueError):
    resolve_constraint("sorted_rows")


def test_plan_regularizer_matches_manual():
  plan = DistEmbeddingStrategy(
      [TableConfig(20, 8, regularizer="l2"),
       TableConfig(30, 8),
       TableConfig(10, 8, regularizer="l1")], 1, "basic")
  fn = plan_regularizer_fn(plan)
  rng = np.random.default_rng(0)
  from distributed_embeddings_tpu.parallel.lookup_engine import (
      class_param_name, padded_rows)
  name = class_param_name(*plan.class_keys[0])
  rows = padded_rows(plan, plan.class_keys[0])
  buf = jnp.asarray(rng.standard_normal((rows, 8)), jnp.float32)
  got = float(fn({name: buf}, 0))
  # manual: find each table's window and apply its penalty
  cp = plan.classes[plan.class_keys[0]]
  want = 0.0
  for sh, off in zip(cp.shards_per_rank[0], cp.row_offsets_per_rank[0]):
    w = np.asarray(buf[off:off + sh.input_dim])
    if sh.table_id == 0:
      want += 0.01 * np.sum(np.square(w))
    elif sh.table_id == 2:
      want += 0.01 * np.sum(np.abs(w))
  np.testing.assert_allclose(got, want, rtol=1e-5)


def _engine_params(plan, seed=0):
  from distributed_embeddings_tpu.parallel.lookup_engine import (
      DistributedLookup)
  engine = DistributedLookup(plan)
  rng = np.random.default_rng(seed)
  return engine, {
      "embeddings": {
          name: jnp.asarray(rng.standard_normal(shape) * 0.1, jnp.float32)
          for name, shape in engine.param_shapes().items()}}


def test_train_step_honors_reg_and_constraint_distributed():
  """8-device hybrid step: the regularizer shrinks its table's weights vs
  an unregularized run, and non_neg keeps its table non-negative."""
  mesh = create_mesh(WORLD)
  # >= WORLD tables so the auto column-slicer stays off (it would slice
  # the constrained table, which the planner rightly rejects)
  tables = [TableConfig(24, 16, regularizer="l2"),
            TableConfig(40, 16, constraint="non_neg")] + \
           [TableConfig(16 + i, 16) for i in range(8)]
  plan = DistEmbeddingStrategy(tables, WORLD, "basic")
  engine, train_params = _engine_params(plan)
  rng = np.random.default_rng(1)
  b = 16
  cats = [jnp.asarray(rng.integers(0, c.input_dim, b), jnp.int32)
          for c in tables]

  def loss_fn(p, *cats):
    outs = engine.forward(p["embeddings"], list(cats))
    return sum(jnp.mean(jnp.tanh(o)) for o in outs)

  opt = optax.sgd(0.5)
  opt_state = opt.init(train_params)
  batch = shard_batch(tuple(cats), mesh)

  def run(plan_arg):
    p = shard_params(train_params, mesh)
    o = shard_params(opt_state, mesh)
    step = make_train_step(loss_fn, opt, mesh, p, o, batch, plan=plan_arg,
                           donate=False)
    for _ in range(3):
      p, o, loss = step(p, o, *batch)
    assert np.isfinite(float(loss))
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        get_weights)
    return get_weights(plan, p["embeddings"])

  w_with = run(plan)
  w_plain = run(None)
  assert float(np.min(w_with[1])) >= 0.0, "non_neg constraint violated"
  assert np.linalg.norm(w_with[0]) < np.linalg.norm(w_plain[0]), \
      "l2 regularizer did not shrink its table"


def test_planner_rejects_unsupported():
  with pytest.raises(ValueError, match="activity_regularizer"):
    DistEmbeddingStrategy(
        [dict(input_dim=10, output_dim=4, activity_regularizer="l2")],
        1, "basic")
  with pytest.raises(ValueError, match="column-sliced"):
    DistEmbeddingStrategy(
        [TableConfig(1 << 14, 64, constraint="max_norm")], 4, "basic",
        column_slice_threshold=1 << 16)
  from distributed_embeddings_tpu.ops.packed_table import sgd_rule
  from distributed_embeddings_tpu.training import make_sparse_train_step

  # uniform l2 is SUPPORTED on the fused path (folded into the deltas as
  # touched-rows decay); the remaining rejections are constraints,
  # non-l2 penalties, and per-table λ mismatches
  plan = DistEmbeddingStrategy([TableConfig(5000, 16, constraint="non_neg")],
                               1, "basic")
  with pytest.raises(NotImplementedError, match="constraint"):
    make_sparse_train_step(None, plan, None, optax.sgd(0.1), sgd_rule(0.1),
                           None, {}, ())
  plan = DistEmbeddingStrategy([TableConfig(5000, 16, regularizer="l1")],
                               1, "basic")
  with pytest.raises(NotImplementedError, match="pure l2"):
    make_sparse_train_step(None, plan, None, optax.sgd(0.1), sgd_rule(0.1),
                           None, {}, ())
  plan = DistEmbeddingStrategy(
      [TableConfig(5000, 16, regularizer="l2"),
       TableConfig(4000, 16,
                   regularizer={"name": "l2", "factor": 0.5})], 1, "basic")
  with pytest.raises(NotImplementedError, match="different l2"):
    make_sparse_train_step(None, plan, None, optax.sgd(0.1), sgd_rule(0.1),
                           None, {}, ())


@pytest.mark.parametrize("opt_name,exact", [
    ("sgd", True), ("sgd", False), ("adagrad", True), ("adagrad", False),
])
def test_fused_l2_decay_matches_dense_path_all_rows_touched(opt_name, exact):
  """Fused-path uniform l2 == the dense path's full-table penalty when the
  batch touches every sparse row exactly once (touched-rows decay equals
  the full sweep there). A dense-kind table rides along: its penalty takes
  the exact full-table route inside the fused step (reg_fn on emb_dense).

  sgd with exact=False exercises the keep_rows residual plumbing (an
  aux-free rule needs the forward-time rows saved; exact=True re-gathers
  at apply time instead); adagrad exercises decay-into-accumulator
  (g + 2λw enters the g² accumulation on both paths). The sparse ids are
  a permutation (no duplicates), where per-occurrence and dedup'd decay
  agree — so every variant must match the dense reference exactly."""
  from distributed_embeddings_tpu.models import DLRM, bce_loss
  from distributed_embeddings_tpu.ops.packed_table import sparse_rule
  from distributed_embeddings_tpu.training import (
      init_sparse_state,
      make_sparse_train_step,
      make_train_step,
      unpack_sparse_state,
  )

  vocab = [32, 8]
  thresh = 16  # table 0 (32 rows) sparse, table 1 (8 rows) MXU dense-kind
  lam = 0.03
  reg = {"name": "l2", "factor": lam}
  plan = DistEmbeddingStrategy(
      [TableConfig(v, 16, regularizer=reg) for v in vocab],
      1, "basic", dense_row_threshold=thresh)
  model = DLRM(vocab_sizes=vocab, embedding_dim=16, bottom_mlp=(32, 16),
               top_mlp=(32, 1), dense_row_threshold=thresh)
  rng = np.random.default_rng(3)
  b = 32
  numerical = jnp.asarray(rng.standard_normal((b, 13)), jnp.float32)
  cats = [jnp.asarray(rng.permutation(32), jnp.int32),  # every row once
          jnp.asarray(rng.integers(0, 8, b), jnp.int32)]
  labels = jnp.asarray(rng.integers(0, 2, b), jnp.float32)
  batch = (numerical, cats, labels)
  params = model.init(jax.random.PRNGKey(0), numerical, cats)["params"]

  opt = optax.sgd(0.1) if opt_name == "sgd" else optax.adagrad(0.1)

  def loss_fn(p, numerical, cats, labels):
    return bce_loss(model.apply({"params": p}, numerical, cats), labels)

  dstate = opt.init(params)
  dense_step = make_train_step(loss_fn, opt, None, params, dstate, batch,
                               plan=plan, donate=False)
  p_dense, _, loss_dense = dense_step(params, dstate, *batch)

  rule = sparse_rule(opt_name, 0.1)
  state = init_sparse_state(plan, params, rule, opt)
  sparse_step = make_sparse_train_step(model, plan, bce_loss, opt, rule,
                                       None, state, batch, exact=exact,
                                       donate=False)
  state2, loss_sparse = sparse_step(state, *batch)

  # loss values: the dense path reports data + full penalty; the fused
  # path reports data + dense-kind penalty only (sparse decay is folded
  # into the deltas, documented) — compare the parameters, not the loss
  p_sparse, _ = unpack_sparse_state(plan, rule, state2)
  flat_d = jax.tree_util.tree_leaves_with_path(p_dense)
  flat_s = {jax.tree_util.keystr(k): v
            for k, v in jax.tree_util.tree_leaves_with_path(p_sparse)}
  for k, v in flat_d:
    ks = jax.tree_util.keystr(k)
    np.testing.assert_allclose(np.asarray(v), np.asarray(flat_s[ks]),
                               rtol=1e-4, atol=1e-5, err_msg=ks)
