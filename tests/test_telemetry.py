"""Telemetry subsystem tests: registry, histograms, spans, persistence.

The four contracts the observability layer rests on (ISSUE 10):

- **histogram correctness**: the log-bucket percentile estimates stay
  within the constructed relative-error bound against EXACT nearest-rank
  percentiles on adversarial distributions (power-law, bimodal spikes,
  ten-decade dynamic range, constants, zeros);
- **span nesting / thread-track attribution**: spans record on the
  track of the thread that ran them — pinned under the micro-batcher's
  REAL flusher/completer worker threads and the async checkpoint
  writer, plus virtual tracks for the device window;
- **counter persistence**: cumulative counters ride the checkpoint
  manifest's ``telemetry`` section through a kill/resume cycle without
  double-counting (the dynvocab-totals discipline, generalized);
- **disabled-mode cost**: ``span()`` with no tracer installed returns a
  process-wide singleton and allocates NOTHING (tracemalloc-pinned) —
  disabled telemetry is a true no-op, and the jaxpr fingerprints
  (tests/test_analysis.py) stay byte-identical because spans never
  enter traced code at all.
"""

import json
import math
import os
import threading
import tracemalloc

import jax
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu import telemetry
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM, bce_loss
from distributed_embeddings_tpu.ops.packed_table import sparse_rule
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.resilience import faultinject
from distributed_embeddings_tpu.resilience.trainer import ResilientTrainer
from distributed_embeddings_tpu.serving import MicroBatcher
from distributed_embeddings_tpu.telemetry import (
    Histogram,
    JsonlWriter,
    MetricsRegistry,
    WindowedHistogram,
    emit_verdict,
    prometheus_text,
    span,
    tracing,
)
from distributed_embeddings_tpu.training import (
    init_sparse_state,
    make_sparse_train_step,
    shard_batch,
    shard_params,
)

WORLD = 4
VOCAB = [300, 200, 150, 20]


# ---------------------------------------------------------------------------
# histogram: log-bucket error bound vs exact percentiles
# ---------------------------------------------------------------------------


def _exact_nearest_rank(xs, q):
  s = np.sort(np.asarray(xs))
  return float(s[max(1, math.ceil(q / 100.0 * len(s))) - 1])


@pytest.mark.parametrize("rel_err", [0.01, 0.05])
def test_histogram_bound_on_adversarial_distributions(rel_err):
  """Estimates stay within the constructed relative-error bound against
  the exact nearest-rank percentile, for distributions chosen to break
  bucketing schemes: heavy tails, ten-decade range, point masses sitting
  exactly on bucket boundaries' bad side, and bimodal spikes."""
  rng = np.random.default_rng(0)
  dists = {
      "powerlaw": rng.pareto(1.05, 4000) + 1e-7,
      "ten_decades": 10.0 ** rng.uniform(-8, 2, 4000),
      "bimodal_spikes": np.r_[np.full(999, 1e-6), np.full(1000, 123.456),
                              rng.normal(1.0, 1e-4, 5)],
      "constant": np.full(100, 0.0421),
      "lognormal": rng.lognormal(0.0, 5.0, 3000),
  }
  for name, xs in dists.items():
    h = Histogram("t", rel_err=rel_err)
    h.observe_many(xs)
    assert h.count == len(xs)
    for q in (0.1, 25, 50, 90, 99, 99.9, 100):
      exact = _exact_nearest_rank(xs, q)
      est = h.percentile(q)
      assert abs(est - exact) <= rel_err * exact * (1 + 1e-9), \
          (name, q, est, exact)


def test_histogram_zeros_count_and_merge():
  h = Histogram("t", rel_err=0.01)
  h.observe_many([0.0, 0.0, 0.0, 1.0])
  assert h.percentile(50) == 0.0 and h.percentile(75) == 0.0
  assert abs(h.percentile(100) - 1.0) <= 0.01
  other = Histogram("t", rel_err=0.01)
  other.observe_many([2.0] * 4)
  h.merge(other)
  assert h.count == 8 and abs(h.percentile(100) - 2.0) <= 0.02
  with pytest.raises(ValueError, match="rel_err"):
    h.merge(Histogram("t", rel_err=0.02))
  assert math.isnan(Histogram("e").percentile(50))
  with pytest.raises(ValueError, match="nan"):
    h.observe(float("nan"))


def test_histogram_state_roundtrip_through_json():
  rng = np.random.default_rng(3)
  h = Histogram("t", rel_err=0.01)
  h.observe_many(rng.lognormal(0, 3, 500))
  st = json.loads(json.dumps(h.state()))  # the manifest path is JSON
  h2 = Histogram("t", rel_err=0.01)
  h2.load(st)
  assert h2.count == h.count and h2.sum == h.sum
  for q in (50, 99):
    assert h2.percentile(q) == h.percentile(q)
  with pytest.raises(ValueError, match="rel_err"):
    Histogram("t", rel_err=0.05).load(st)


def test_windowed_histogram_rotation_expires_old_samples():
  w = WindowedHistogram("t", slots=3, rel_err=0.01)
  for _ in range(100):
    w.observe(10.0)  # an old latency regime
  w.rotate()
  assert w.rotations == 1
  for _ in range(100):
    w.observe(0.001)  # the new regime
  # both regimes visible while the old slot is in the ring
  assert w.count == 200
  assert w.percentile(99) > 1.0
  # rotate the old regime past the ring depth: the view forgets it —
  # the new regime's slot is still inside the window
  for _ in range(3):
    w.rotate()
  assert w.count == 100
  assert w.percentile(99) < 1.0  # the 10.0 regime is GONE from p99
  assert abs(w.percentile(99) - 0.001) <= 0.001 * 0.03
  # and once the new regime ages past the ring too, the window is empty
  w.rotate()
  assert w.count == 0


def test_windowed_histogram_view_merge_is_exact():
  """The window's view is EXACTLY the merge of its live sub-histograms:
  same counts, same percentile estimates as one histogram fed the same
  stream (merge exactness is the DDSketch bucket-union property)."""
  rng = np.random.default_rng(7)
  w = WindowedHistogram("t", slots=4, rel_err=0.01)
  ref = Histogram("t", rel_err=0.01)
  for chunk in range(4):
    xs = rng.lognormal(0, 2, 300)
    for x in xs:
      w.observe(x)
      ref.observe(x)
    if chunk < 3:
      w.rotate()
  # nothing aged out (3 rotations < 4 slots): the union must be exact
  view = w.view()
  assert view.count == ref.count
  for q in (50, 90, 99, 99.9):
    assert view.percentile(q) == ref.percentile(q)
  # the view is caller-owned: observing more does not mutate it
  w.observe(1e9)
  assert view.count == ref.count


def test_windowed_histogram_clocked_rotation_and_refusals():
  w = WindowedHistogram("t", slots=2, rotate_every_s=1.0)
  assert not w.maybe_rotate(100.0)  # first call stamps, never rotates
  w.observe(5.0)
  assert not w.maybe_rotate(100.5)  # not due yet
  assert w.maybe_rotate(101.1)  # due: the open slot seals
  assert w.rotations == 1
  with pytest.raises(ValueError, match="slots"):
    WindowedHistogram("t", slots=0)


# ---------------------------------------------------------------------------
# registry: schema, thread-safety, prometheus rendering
# ---------------------------------------------------------------------------


def test_registry_kinds_and_conflicts():
  r = MetricsRegistry()
  r.counter("a").inc(2)
  assert r.counter("a").value == 2  # same object on re-request
  with pytest.raises(ValueError, match="already registered"):
    r.gauge("a")
  with pytest.raises(ValueError, match="monotone"):
    r.counter("a").inc(-1)
  r.gauge("g").set(1.5)
  assert r.snapshot()["g"] == 1.5
  # a histogram re-request with a different error bound is a loud
  # mismatch, not a silently-wrong geometry (same policy as load/merge)
  r.histogram("h", rel_err=0.01)
  with pytest.raises(ValueError, match="rel_err"):
    r.histogram("h", rel_err=0.001)


def test_registry_counters_under_thread_contention():
  r = MetricsRegistry()

  def work():
    c = r.counter("hits")
    for _ in range(10000):
      c.inc()

  threads = [threading.Thread(target=work) for _ in range(8)]
  for t in threads:
    t.start()
  for t in threads:
    t.join()
  assert r.counter("hits").value == 80000


def test_registry_state_dict_roundtrip_and_adoption():
  r = MetricsRegistry()
  r.counter("train/bad_step").inc(3)
  r.gauge("vocab/occupancy/c").set(17)
  r.histogram("serve/latency_s").observe_many([0.001, 0.002, 0.4])
  section = json.loads(json.dumps(r.state_dict()))

  r2 = MetricsRegistry()
  r2.counter("train/bad_step").inc(99)   # pre-resume local noise
  r2.counter("other/thing").inc(5)       # not in the section: untouched
  r2.load_state_dict(section)
  assert r2.counter("train/bad_step").value == 3  # REPLACED, not added
  assert r2.counter("other/thing").value == 5
  assert r2.gauge("vocab/occupancy/c").value == 17
  assert r2.histogram("serve/latency_s").count == 3


def test_prometheus_text_format():
  r = MetricsRegistry()
  r.counter("train/oov/class_a").inc(4)
  r.gauge("queue/depth").set(2)
  r.histogram("serve/latency_s").observe_many([0.01] * 100)
  text = prometheus_text(r)
  assert "# TYPE train_oov_class_a counter" in text
  assert "train_oov_class_a 4" in text
  assert "# TYPE serve_latency_s summary" in text
  assert 'serve_latency_s{quantile="0.99"}' in text
  assert "serve_latency_s_count 100" in text


# ---------------------------------------------------------------------------
# spans: disabled-mode no-op, nesting, thread tracks
# ---------------------------------------------------------------------------


def test_disabled_span_is_singleton_and_zero_allocation():
  """The disabled path must cost nothing: one shared no-op object, zero
  allocations attributed to the telemetry modules (tracemalloc pins the
  'spans compile to nothing' claim — a closure or kwargs dict per call
  would show up here)."""
  assert telemetry.current_tracer() is None
  assert span("a") is span("b") is span("c", track="device")

  here = os.path.dirname(telemetry.__file__)
  for _ in range(100):  # warm any lazy interning
    with span("warm"):
      pass
  tracemalloc.start()
  try:
    s0 = tracemalloc.take_snapshot()
    for _ in range(5000):
      with span("hot/stage"):
        pass
    s1 = tracemalloc.take_snapshot()
  finally:
    tracemalloc.stop()
  telem = [st for st in s1.compare_to(s0, "filename")
           if here in st.traceback[0].filename and st.count_diff > 0]
  # a couple of constant warm-up blocks (code-object bookkeeping under
  # tracemalloc) are tolerated; anything PER-CALL over 5000 iterations
  # would show up as thousands
  blocks = sum(st.count_diff for st in telem)
  assert blocks < 50, f"disabled spans allocate per call: {telem}"


def test_span_nesting_and_virtual_tracks():
  with tracing() as tr:
    with span("outer"):
      with span("inner", args={"k": 3}):
        pass
    dev = span("device/step", track="device").start()
    with span("overlapped-host-work"):
      pass
    dev.finish()
  chrome = tr.to_chrome()
  evs = {e["name"]: e for e in chrome["traceEvents"] if e["ph"] == "X"}
  # nesting: inner starts no earlier and ends no later than outer
  out_, in_ = evs["outer"], evs["inner"]
  assert out_["ts"] <= in_["ts"]
  assert in_["ts"] + in_["dur"] <= out_["ts"] + out_["dur"] + 1e-6
  assert in_["args"] == {"k": 3}
  # the virtual device track is a distinct tid, and the host span is
  # inside the device window — overlap is visible, not asserted
  assert evs["device/step"]["tid"] != evs["overlapped-host-work"]["tid"]
  d, h = evs["device/step"], evs["overlapped-host-work"]
  assert d["ts"] <= h["ts"] and h["ts"] + h["dur"] <= d["ts"] + d["dur"]
  names = {e["args"]["name"] for e in chrome["traceEvents"]
           if e.get("name") == "thread_name"}
  assert "device" in names
  assert telemetry.current_tracer() is None  # uninstalled on exit


def test_thread_tracks_unique_across_sequential_threads():
  """CPython reuses thread idents after a thread exits; track keys must
  not — two short-lived workers (the async ckpt-writer pattern) each
  get their OWN correctly-named track."""
  def worker():
    with span("w"):
      pass

  with tracing() as tr:
    for i in range(2):
      th = threading.Thread(target=worker, name=f"writer-{i}")
      th.start()
      th.join()
  chrome = tr.to_chrome()
  names = {e["args"]["name"] for e in chrome["traceEvents"]
           if e.get("name") == "thread_name"}
  assert {"writer-0", "writer-1"} <= names
  w_tids = {e["tid"] for e in chrome["traceEvents"]
            if e.get("ph") == "X" and e["name"] == "w"}
  assert len(w_tids) == 2  # one track per thread, never merged


def test_span_thread_tracks_under_real_batcher_threads(tmp_path):
  """Track attribution under the batcher's REAL flusher/completer
  threads: pack/dispatch spans land on the flusher's track, completion
  spans on the completer's, both distinct from the submitting thread."""
  def dispatch(numerical, cats):
    return np.zeros((8, 1), np.float32)

  path = str(tmp_path / "trace.json")
  with tracing(path):
    mb = MicroBatcher(dispatch, max_batch=8, max_delay_s=0.001)
    futs = [mb.submit(np.zeros((2, 3), np.float32),
                      [np.zeros((2,), np.int32)]) for _ in range(6)]
    for f in futs:
      f.result(timeout=30)
    mb.close()
  chrome = json.load(open(path))
  tracks = {e["tid"]: e["args"]["name"] for e in chrome["traceEvents"]
            if e.get("name") == "thread_name"}
  by_name = {}
  for e in chrome["traceEvents"]:
    if e["ph"] == "X":
      by_name.setdefault(e["name"], set()).add(tracks[e["tid"]])
  assert by_name["serve/pack"] == {"serve-batcher-flush"}
  assert by_name["serve/dispatch"] == {"serve-batcher-flush"}
  assert by_name["serve/complete"] == {"serve-batcher-complete"}
  # and the registry-backed accounting saw every request
  assert mb.stats["completed"] == 6 and mb.stats["rejected"] == 0
  assert mb.telemetry.histogram("serve/latency_s").count == 6


def test_ckpt_save_span_on_async_writer_thread(tmp_path):
  """The async snapshot's ckpt/save span lands on the writer thread's
  own track (named ckpt-writer-<step>), while training-side spans stay
  on the main thread — the overlap the async path exists for is a
  visible two-track fact in the trace."""
  from tests.test_resilience import build, init_state, make_batch

  model, plan, rule, opt = build(1)
  batch = make_batch(1)
  state = init_state(model, plan, rule, opt, batch)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, None,
                                state, batch, donate=False, guard=True)
  path = str(tmp_path / "trace.json")
  with tracing(path):
    t = ResilientTrainer(step, state, plan, rule,
                         str(tmp_path / "ckpts"), resume=False,
                         async_snapshots=True,
                         telemetry=MetricsRegistry())
    t.step(*shard_batch(batch, None))
    t.snapshot(async_=True)
    t.join_writer()
  chrome = json.load(open(path))
  tracks = {e["tid"]: e["args"]["name"] for e in chrome["traceEvents"]
            if e.get("name") == "thread_name"}
  saves = [e for e in chrome["traceEvents"]
           if e.get("ph") == "X" and e["name"] == "ckpt/save"]
  assert saves and all(
      tracks[e["tid"]].startswith("ckpt-writer-") for e in saves)
  assert t.telemetry.counter("ckpt/snapshots").value == 1


# ---------------------------------------------------------------------------
# counter persistence across a kill/resume cycle
# ---------------------------------------------------------------------------


def _trainer_fixture(root, registry, mesh, built, state0, step):
  model, plan, rule, opt = built
  return ResilientTrainer(step, state0, plan, rule, str(root), mesh=mesh,
                          snapshot_every=2, telemetry=registry)


def test_counters_persist_across_kill_resume(tmp_path):
  """The generalized dynvocab pattern: cumulative telemetry rides the
  manifest's ``telemetry`` section; a fresh process (fresh registry)
  adopts the persisted counts on first resume and continues them —
  totals over the logical run match an uninterrupted run exactly, with
  nothing double-counted on the replayed tail."""
  from tests.test_resilience import build, init_state, make_batch

  mesh = create_mesh(WORLD)
  built = build(WORLD)
  model, plan, rule, opt = built
  batches = [make_batch(WORLD, seed) for seed in range(8)]
  stream = list(faultinject.nan_batches(batches, at_steps={2, 5}))
  state0 = init_state(model, plan, rule, opt, batches[0], mesh)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state0, batches[0], donate=False,
                                guard=True)

  # uninterrupted reference
  ref_reg = MetricsRegistry()
  ref = _trainer_fixture(tmp_path / "ref", ref_reg, mesh, built,
                         init_state(model, plan, rule, opt, batches[0],
                                    mesh), step)
  ref.run(stream)
  assert ref_reg.counter("train/bad_step").value == 2
  assert ref_reg.counter("train/consumed").value == 8

  # killed run: crash mid-save partway through the stream
  reg1 = MetricsRegistry()
  t1 = _trainer_fixture(tmp_path / "run", reg1, mesh, built,
                        init_state(model, plan, rule, opt, batches[0],
                                   mesh), step)
  inj = faultinject.FaultInjector().crash_after("ckpt_write", 20)
  with pytest.raises(faultinject.InjectedCrash):
    with faultinject.injected(inj):
      for b in stream:
        t1.step(*shard_batch(b, mesh))

  # fresh process stand-in: NEW registry, adopts the persisted section
  reg2 = MetricsRegistry()
  reg2.counter("train/bad_step").inc(7)  # pre-resume noise: replaced
  t2 = _trainer_fixture(tmp_path / "run", reg2, mesh, built,
                        init_state(model, plan, rule, opt, batches[0],
                                   mesh), step)
  assert t2.resumed_from is not None
  persisted_bad = reg2.counter("train/bad_step").value
  persisted_consumed = reg2.counter("train/consumed").value
  assert persisted_consumed == t2.consumed  # adopted, in sync
  t2.run(stream[t2.consumed:])
  assert reg2.counter("train/consumed").value == 8
  assert reg2.counter("train/bad_step").value == 2  # never double-counted
  assert reg2.counter("train/bad_step").value >= persisted_bad
  assert reg2.counter("ckpt/restores").value >= 0  # global-registry metric
  # and the manifest section is plain JSON in the checkpoint
  from distributed_embeddings_tpu import checkpoint
  from distributed_embeddings_tpu.resilience import durable
  _, path = durable.latest_valid(str(tmp_path / "run"))
  sec = checkpoint.read_manifest(path)["telemetry"]
  assert sec["counters"]["train/consumed"] >= persisted_consumed


# ---------------------------------------------------------------------------
# export: jsonl rotation durability, verdict schema
# ---------------------------------------------------------------------------


def test_jsonl_writer_rotation_keeps_tail(tmp_path):
  path = str(tmp_path / "events.jsonl")
  w = JsonlWriter(path, max_bytes=120, keep=2)
  for i in range(120):
    w.write({"i": i})
  w.close()
  assert os.path.exists(path + ".1") and os.path.exists(path + ".2")
  assert not os.path.exists(path + ".3")  # keep bound enforced
  ids = []
  for f in (path + ".2", path + ".1", path):
    with open(f) as fh:
      ids += [json.loads(line)["i"] for line in fh]
  # the surviving window is contiguous and ends at the last write
  assert ids == list(range(ids[0], 120))


def test_emit_verdict_schema_and_exit_codes(tmp_path, capsys):
  log = str(tmp_path / "verdicts.jsonl")
  assert emit_verdict("chaos", {"ok": True, "skips": 3}, path=log) == 0
  assert emit_verdict("chaos-kill", {"ok": False}, verbose=False,
                      path=log) == 1
  assert emit_verdict("obs-bench", {}, verbose=False, path=log) == 1
  out = capsys.readouterr().out
  assert "CHAOS: PASS" in out and "CHAOS-KILL: FAIL" in out
  with open(log) as f:
    records = [json.loads(line) for line in f]
  assert [r["tool"] for r in records] == ["chaos", "chaos-kill",
                                          "obs-bench"]
  assert [r["ok"] for r in records] == [True, False, False]
  assert records[0]["verdict"]["skips"] == 3  # full result rides along


def test_write_prometheus_atomic(tmp_path):
  r = MetricsRegistry()
  r.counter("x").inc()
  path = str(tmp_path / "metrics.prom")
  telemetry.write_prometheus(r, path)
  assert open(path).read().startswith("# TYPE x counter")
  assert not os.path.exists(path + ".tmp")


# ---------------------------------------------------------------------------
# checkpoint integration: telemetry section through save/restore
# ---------------------------------------------------------------------------


def test_checkpoint_telemetry_section_roundtrip(tmp_path):
  from distributed_embeddings_tpu import checkpoint
  from tests.test_resilience import build, init_state, make_batch

  model, plan, rule, opt = build(1)
  batch = make_batch(1)
  state = init_state(model, plan, rule, opt, batch)
  reg = MetricsRegistry()
  reg.counter("train/consumed").inc(11)
  reg.histogram("serve/latency_s").observe_many([0.01, 0.02])
  path = str(tmp_path / "ckpt")
  checkpoint.save(path, plan, rule, state, telemetry=reg)
  assert not checkpoint.verify(path)
  reg2 = MetricsRegistry()
  checkpoint.restore(path, plan, rule, state, telemetry=reg2)
  assert reg2.counter("train/consumed").value == 11
  assert reg2.histogram("serve/latency_s").count == 2
  # a registry-less restore ignores the section (observability, not
  # state), and a section-less checkpoint is fine with a registry
  checkpoint.restore(path, plan, rule, state)
  path2 = str(tmp_path / "ckpt2")
  checkpoint.save(path2, plan, rule, state)
  checkpoint.restore(path2, plan, rule, state,
                     telemetry=MetricsRegistry())


# ---------------------------------------------------------------------------
# histogram bucket-collapse (bounded cardinality for unbounded streams)
# ---------------------------------------------------------------------------


def test_histogram_bucket_collapse_bounds_cardinality():
  # At rel_err=0.05 a bucket covers ~4.3% of a decade, so 64 buckets
  # span ~2.8 decades; eight decades of uniform-log input would occupy
  # ~185 buckets unbounded. The collapse folds the LOWEST buckets, so
  # the top of the distribution keeps its bound while small values
  # degrade (upward — never under-reported).
  h = telemetry.Histogram("stream/freshness_s", rel_err=0.05,
                          max_buckets=64)
  rng = np.random.default_rng(0)
  xs = 10.0 ** rng.uniform(-4, 4, 5000)
  h.observe_many(xs.tolist())
  assert len(h._buckets) <= 64
  # exact aggregates survive the collapse
  assert h.count == 5000
  assert abs(h.sum - xs.sum()) < 1e-6 * xs.sum()
  assert h.min == xs.min() and h.max == xs.max()
  ordered = np.sort(xs)
  # p99 lives in the intact top buckets: the rel_err bound holds
  exact99 = ordered[max(1, math.ceil(0.99 * 5000)) - 1]
  assert abs(h.percentile(99.0) - exact99) <= 0.0501 * exact99
  # below the collapse boundary estimates degrade, but only UPWARD (a
  # lag histogram that can only over-report staleness stays safe to
  # alert on)
  exact50 = ordered[max(1, math.ceil(0.5 * 5000)) - 1]
  assert h.percentile(50.0) >= exact50 * (1.0 - 0.05)
  # state round-trips the collapse accounting
  h2 = telemetry.Histogram("x", rel_err=0.05, max_buckets=64)
  h2.load(h.state())
  assert h2.percentile(99.0) == h.percentile(99.0)
  assert h2._collapsed == h._collapsed > 0


def test_histogram_collapse_needs_two_buckets():
  with pytest.raises(ValueError, match="max_buckets"):
    telemetry.Histogram("h", max_buckets=1)


def test_registry_histogram_max_buckets_policy():
  reg = telemetry.MetricsRegistry()
  h = reg.histogram("stream/freshness_s", max_buckets=8)
  # readers with the default None get the same (bounded) histogram
  assert reg.histogram("stream/freshness_s") is h
  # an unbounded histogram adopts the FIRST explicit bound...
  u = reg.histogram("serve/latency_s")
  for v in (1e-6, 1e-3, 1.0, 1e3, 1e6):
    u.observe(v)
  assert reg.histogram("serve/latency_s", max_buckets=4) is u
  assert u.max_buckets == 4 and len(u._buckets) <= 4
  # ...but two different explicit bounds are a loud conflict
  with pytest.raises(ValueError, match="max_buckets"):
    reg.histogram("stream/freshness_s", max_buckets=32)


# ---------------------------------------------------------------------------
# live /metrics scrape endpoint
# ---------------------------------------------------------------------------


def test_metrics_http_endpoint_serves_and_shuts_down_clean():
  import urllib.error
  import urllib.request

  reg = telemetry.MetricsRegistry()
  reg.counter("stream/deltas_applied").inc(5)
  reg.gauge("vocab/occupancy/t0").set(17.0)
  reg.histogram("serve/latency_s").observe_many([0.001, 0.004, 0.2])
  with telemetry.MetricsServer(reg) as server:
    assert server.port > 0
    body = urllib.request.urlopen(server.url, timeout=5).read().decode()
    assert "# TYPE stream_deltas_applied counter" in body
    assert "stream_deltas_applied 5" in body
    assert "vocab_occupancy_t0 17.0" in body
    assert 'serve_latency_s{quantile="0.99"}' in body
    # same content as the textfile renderer: one schema, two transports
    assert body == telemetry.prometheus_text(reg)
    health = json.loads(urllib.request.urlopen(
        f"http://{server.host}:{server.port}/healthz", timeout=5).read())
    # readiness detail (round 18): JSON body; a process that never
    # promoted reports nulls, never fabricated freshness
    assert health["ok"] is True
    assert health["served_step"] is None
    assert health["staleness_s"] is None
    with pytest.raises(urllib.error.HTTPError):
      urllib.request.urlopen(
          f"http://{server.host}:{server.port}/nope", timeout=5)
    port = server.port
  # shutdown-clean: thread joined, socket closed, port refused
  assert server.closed
  with pytest.raises(OSError):
    urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=2)
  server.close()  # idempotent


def test_metrics_fleet_rollup_merges_pushed_snapshots():
  """``/metrics?scope=fleet``: counters sum across pushed per-process
  snapshots, gauges take the last writer, and the default scope stays
  the local registry only."""
  import json
  import urllib.request

  local = telemetry.MetricsRegistry()
  local.counter("serve/completed").inc(10)
  local.gauge("fleet/owners_dead").set(0.0)
  member_a = telemetry.MetricsRegistry()
  member_a.counter("serve/completed").inc(7)
  member_a.gauge("fleet/owners_dead").set(1.0)
  member_b = telemetry.MetricsRegistry()
  member_b.counter("serve/completed").inc(5)
  member_b.gauge("fleet/owners_dead").set(2.0)
  member_b.histogram("serve/latency_s").observe_many([0.01, 0.02])
  with telemetry.MetricsServer(local) as server:
    server.push("owner-0", member_a)
    # the second member pushes over HTTP, the deployment shape
    payload = json.dumps({"source": "owner-1",
                          "telemetry": member_b.state_dict()})
    req = urllib.request.Request(
        f"http://{server.host}:{server.port}/push",
        data=payload.encode("utf-8"), method="POST")
    assert urllib.request.urlopen(req, timeout=5).status == 200
    fleet = urllib.request.urlopen(server.fleet_url,
                                   timeout=5).read().decode()
    assert "serve_completed 22" in fleet          # 10 + 7 + 5: counters SUM
    assert "fleet_owners_dead 2.0" in fleet       # last writer (owner-1)
    assert 'serve_latency_s{quantile="0.5"}' in fleet
    # default scope: the local registry only, untouched by pushes
    solo = urllib.request.urlopen(server.url, timeout=5).read().decode()
    assert "serve_completed 10" in solo
    # replace-by-source: a re-push never double-counts
    member_a.counter("serve/completed").inc(1)
    server.push("owner-0", member_a)
    fleet = urllib.request.urlopen(server.fleet_url,
                                   timeout=5).read().decode()
    assert "serve_completed 23" in fleet


# ---------------------------------------------------------------------------
# distributed tracing (round 18): contexts, clock offsets, merged timeline
# ---------------------------------------------------------------------------


def test_trace_context_parenting_and_ids():
  """Spans under a context mint their own span ids, chain parent ->
  child through the thread-local, and record the batch's full trace-id
  list — the per-process half of the cross-process timeline."""
  with telemetry.tracing() as tr:
    ctx = telemetry.mint_context(["r1", "r2"])
    with telemetry.use_context(ctx):
      with telemetry.span("parent"):
        with telemetry.span("child"):
          pass
    with telemetry.span("no_ctx"):
      pass
  evs = {e["name"]: e for e in tr.to_chrome()["traceEvents"]
         if e.get("ph") == "X"}
  p, c = evs["parent"], evs["child"]
  assert p["args"]["trace_id"] == c["args"]["trace_id"] == "r1"
  assert c["args"]["parent_span_id"] == p["args"]["span_id"]
  assert p["args"]["parent_span_id"] == ctx.span_id
  assert p["args"]["trace_ids"] == ["r1", "r2"]
  # a context-free span carries no ids (trainer spans stay lean)
  assert "args" not in evs["no_ctx"]


def test_trace_context_wire_roundtrip():
  ctx = telemetry.mint_context(["a", "b"])
  assert telemetry.TraceContext.from_wire(ctx.to_wire()) == ctx
  solo = telemetry.mint_context()
  back = telemetry.TraceContext.from_wire(solo.to_wire())
  assert back.trace_id == solo.trace_id
  assert back.trace_ids == (solo.trace_id,)


def test_clock_offset_recovered_within_stated_uncertainty():
  """The handshake's bound is structural, not statistical: the remote
  read happens inside the min round trip, so the TRUE offset is within
  ±rtt/2 of the estimate — pinned against injected skews (including a
  deliberately slow remote leg the min-RTT selection must absorb)."""
  import time as _time

  from distributed_embeddings_tpu.telemetry import trace as trz

  for skew in (0, 25_000_000, -3_600_000_000_000):
    def remote(skew=skew):
      _time.sleep(0.0005)  # queueing delay inside the round trip
      return trz.clock_ns() + skew
    off = telemetry.estimate_clock_offset(remote, rounds=6)
    assert abs(off.offset_ns - skew) <= off.uncertainty_ns
    assert off.uncertainty_ns == max(1, off.rtt_ns // 2)
    # the mapping direction: a remote stamp maps back near local now
    local = off.to_local(trz.clock_ns() + skew)
    assert abs(local - trz.clock_ns()) <= off.uncertainty_ns + 10_000_000


def test_merged_trace_rpc_contains_gather_after_correction():
  """Two 'processes' with a large clock skew: the router's rpc span
  must STRICTLY contain the owner's gather span — but only after the
  handshaked offset corrects the owner's clock (uncorrected, the skew
  throws the gather far outside the rpc window, which proves the
  correction is load-bearing, not decorative)."""
  from distributed_embeddings_tpu.telemetry import trace as trz

  SKEW = 3_700_000_000  # 3.7 s — dwarfs the handshake uncertainty
  a = telemetry.Tracer(label="router")
  b = telemetry.Tracer(label="owner-0")
  t0 = trz.clock_ns()
  ms = 1_000_000
  a.record_window("fleet/rpc", t0 + 1 * ms, t0 + 9 * ms,
                  args={"span_id": "S", "trace_id": "T"})
  # the owner's clock reads SKEW ahead; the true window sits inside
  b.record_window("fleet/owner/gather",
                  t0 + 3 * ms + SKEW, t0 + 6 * ms + SKEW,
                  args={"parent_span_id": "S", "trace_id": "T"})
  off = telemetry.estimate_clock_offset(lambda: trz.clock_ns() + SKEW,
                                        rounds=6)

  def spans(merged):
    out = {}
    for e in merged["traceEvents"]:
      if e.get("ph") == "X":
        out[e["name"]] = e
    return out

  corrected = spans(telemetry.merge_traces(
      [{"trace": a.to_chrome()},
       {"trace": b.to_chrome(), "offset_ns": off.offset_ns}]))
  rpc, g = corrected["fleet/rpc"], corrected["fleet/owner/gather"]
  assert rpc["ts"] < g["ts"]
  assert g["ts"] + g["dur"] < rpc["ts"] + rpc["dur"]
  assert g["args"]["parent_span_id"] == rpc["args"]["span_id"]
  # uncorrected: the skew expels the gather from the rpc window
  raw = spans(telemetry.merge_traces(
      [{"trace": a.to_chrome()}, {"trace": b.to_chrome()}]))
  rpc, g = raw["fleet/rpc"], raw["fleet/owner/gather"]
  assert not (rpc["ts"] < g["ts"]
              and g["ts"] + g["dur"] < rpc["ts"] + rpc["dur"])


def test_merge_traces_one_pid_per_process():
  a = telemetry.Tracer(label="router")
  b = telemetry.Tracer(label="owner-1")
  with telemetry.tracing() as _:
    pass  # tracing() must not interfere with manual tracers
  a.record_window("x", a.t0_ns + 10, a.t0_ns + 20)
  b.record_window("y", b.t0_ns + 10, b.t0_ns + 20)
  merged = telemetry.merge_traces([{"trace": a.to_chrome()},
                                   {"trace": b.to_chrome()}])
  names = {e["pid"]: e["args"]["name"] for e in merged["traceEvents"]
           if e.get("ph") == "M" and e.get("name") == "process_name"}
  assert sorted(names.values()) == ["owner-1", "router"]
  pid_of = {v: k for k, v in names.items()}
  xs = {e["name"]: e for e in merged["traceEvents"]
        if e.get("ph") == "X"}
  assert xs["x"]["pid"] == pid_of["router"]
  assert xs["y"]["pid"] == pid_of["owner-1"]


def test_attach_device_track_anchors_and_preserves_spacing():
  a = telemetry.Tracer(label="router")
  a.record_window("serve/dispatch", a.t0_ns + 5_000_000,
                  a.t0_ns + 9_000_000)
  merged = telemetry.merge_traces([{"trace": a.to_chrome()}])
  device = {"traceEvents": [
      {"ph": "M", "pid": 7, "name": "process_name",
       "args": {"name": "/device:TPU:0"}},
      {"ph": "X", "pid": 7, "tid": 1, "name": "fusion", "ts": 100.0,
       "dur": 2.0},
      {"ph": "X", "pid": 7, "tid": 1, "name": "fusion.1", "ts": 103.5,
       "dur": 1.0},
  ]}
  anchor_ns = a.t0_ns + 5_000_000  # the dispatch span's start
  out = telemetry.attach_device_track(merged, device, anchor_ns)
  names = {e["pid"]: e["args"]["name"] for e in out["traceEvents"]
           if e.get("ph") == "M" and e.get("name") == "process_name"}
  assert "device" in names.values()
  dev = [e for e in out["traceEvents"] if e.get("ph") == "X"
         and e["name"].startswith("fusion")]
  dev.sort(key=lambda e: e["ts"])
  # earliest device event lands AT the anchor; relative spacing exact
  base = merged["base_ns"]
  assert abs(dev[0]["ts"] - (anchor_ns - base) / 1e3) < 1e-6
  assert abs((dev[1]["ts"] - dev[0]["ts"]) - 3.5) < 1e-6


# ---------------------------------------------------------------------------
# flight recorder: ring, stages, trips, bundles
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_trip_and_bundle(tmp_path):
  from distributed_embeddings_tpu.telemetry import flight

  reg = telemetry.MetricsRegistry()
  rec = telemetry.FlightRecorder(dir=str(tmp_path), capacity=8,
                                 registry=reg, min_interval_s=0.0)
  telemetry.install_flight_recorder(rec)
  try:
    import time as _time
    for i, slow in enumerate([0.0, 0.02, 0.0]):
      r = rec.begin(f"t{i}")
      rec.bind(r)
      _time.sleep(slow)  # slowest = largest real begin->end wall
      flight.observe_stage("rpc", 0.001 + slow)
      flight.observe_stage("combine", 0.0005)
      if i == 1:
        rec.note("failover", owner=0)
      rec.bind(None)
      rec.end(r)
    path = rec.trip("failover", owner=0)
    assert path is not None  # no live records -> dumped inline
    with open(path) as f:
      bundle = json.load(f)
    assert bundle["reason"] == "failover"
    assert len(bundle["requests"]) == 3
    # the slowest request's critical path names its dominant stage
    assert bundle["slowest"]["trace_id"] == "t1"
    assert bundle["slowest"]["critical_stage"] == "rpc"
    assert any(n["kind"] == "failover"
               for n in bundle["slowest"]["notes"])
    # stage taxonomy histograms fed alongside the records
    assert bundle["stage_s"]["rpc"]["count"] == 3
    assert reg.histogram("serve/stage_s/combine").count == 3
    assert reg.counter("flight/trips").value == 1
    assert reg.counter("flight/bundles").value == 1
  finally:
    telemetry.uninstall_flight_recorder()


def test_flight_trip_defers_until_inflight_record_ends(tmp_path):
  """A trip fired mid-dispatch must wait for the in-flight record —
  the failed-then-retried request belongs IN its own bundle."""
  reg = telemetry.MetricsRegistry()
  rec = telemetry.FlightRecorder(dir=str(tmp_path), registry=reg,
                                 min_interval_s=0.0)
  r = rec.begin("inflight")
  assert rec.trip("failover") is None
  assert rec.bundles == []
  # a later trip must not overwrite the pending one: the FIRST moment
  # is the one worth capturing (both are still counted)
  assert rec.trip("shed/queue_full") is None
  rec.observe_stage("rpc", 0.25, rec=r)
  rec.end(r)
  assert len(rec.bundles) == 1
  with open(rec.bundles[0]) as f:
    bundle = json.load(f)
  assert bundle["reason"] == "failover"
  assert [q["trace_id"] for q in bundle["requests"]] == ["inflight"]
  assert bundle["requests"][0]["done"] is True
  assert reg.counter("flight/trips").value == 2


def test_flight_trip_rate_limit_per_reason(tmp_path):
  reg = telemetry.MetricsRegistry()
  rec = telemetry.FlightRecorder(dir=str(tmp_path), registry=reg,
                                 min_interval_s=3600.0)
  assert rec.trip("shed/queue_full") is not None
  assert rec.trip("shed/queue_full") is None   # rate-limited
  assert rec.trip("refusal") is not None       # other reasons pass
  assert len(rec.bundles) == 2
  # every trip is counted even when its dump is suppressed
  assert reg.counter("flight/trips").value == 3
  assert reg.counter("flight/trips/shed").value == 2


def test_batcher_shed_trips_flight_recorder(tmp_path):
  from distributed_embeddings_tpu.serving import MicroBatcher, Rejected

  reg = telemetry.MetricsRegistry()
  rec = telemetry.install_flight_recorder(
      telemetry.FlightRecorder(dir=str(tmp_path), registry=reg,
                               min_interval_s=0.0))
  try:
    mb = MicroBatcher(lambda n, c: n, max_batch=4, queue_rows=4,
                      start=False)
    mb.submit(np.zeros((4, 1), np.float32),
              [np.zeros((4, 1), np.int32)])
    with pytest.raises(Rejected):
      mb.submit(np.zeros((4, 1), np.float32),
                [np.zeros((4, 1), np.int32)])
    # the shed trips with defer=True (it fires under the batcher's
    # lock): the dump lands on a short-lived background thread
    import time as _time
    deadline = _time.monotonic() + 5.0
    while not rec.bundles and _time.monotonic() < deadline:
      _time.sleep(0.01)
    assert len(rec.bundles) == 1
    with open(rec.bundles[0]) as f:
      assert json.load(f)["reason"] == "shed/queue_full"
  finally:
    telemetry.uninstall_flight_recorder()


def test_batcher_mints_request_ids_onto_dispatch_span():
  """Admission mints each request's trace id; the dispatch context
  carries ALL coalesced ids, and pack/dispatch/complete share one
  trace — the per-process half of the fleet acceptance."""
  from distributed_embeddings_tpu.serving import MicroBatcher

  with telemetry.tracing() as tr:
    mb = MicroBatcher(lambda n, c: n, max_batch=8, start=False)
    f1 = mb.submit(np.zeros((2, 1), np.float32),
                   [np.zeros((2, 1), np.int32)])
    f2 = mb.submit(np.zeros((3, 1), np.float32),
                   [np.zeros((3, 1), np.int32)])
    mb.flush_now()
    assert f1.result(1.0).shape[0] == 2 and f2.done()
  evs = {e["name"]: e for e in tr.to_chrome()["traceEvents"]
         if e.get("ph") == "X"}
  disp = evs["serve/dispatch"]
  ids = disp["args"].get("trace_ids", [disp["args"]["trace_id"]])
  assert len(set(ids)) == 2  # one id per admitted request
  assert evs["serve/pack"]["args"]["trace_id"] == disp["args"]["trace_id"]
  assert evs["serve/complete"]["args"]["trace_id"] \
      == disp["args"]["trace_id"]


def test_batcher_disabled_tracing_mints_nothing():
  from distributed_embeddings_tpu.serving import MicroBatcher

  mb = MicroBatcher(lambda n, c: n, max_batch=4, start=False)
  mb.submit(np.zeros((2, 1), np.float32), [np.zeros((2, 1), np.int32)])
  assert all(p.trace_id is None for p in mb._pending)
  mb.flush_now()


# ---------------------------------------------------------------------------
# /healthz readiness detail + fleet snapshot TTL
# ---------------------------------------------------------------------------


def test_healthz_reports_watermark_age():
  import time as _time
  import urllib.request

  reg = telemetry.MetricsRegistry()
  with telemetry.MetricsServer(reg) as server:
    url = f"http://{server.host}:{server.port}/healthz"
    h = json.loads(urllib.request.urlopen(url, timeout=5).read())
    assert h == {"ok": True, "served_step": None,
                 "last_promote_unix": None, "staleness_s": None}
    # the gauges the subscriber/follower set at each promote
    reg.gauge("stream/served_step").set(42)
    reg.gauge("stream/last_promote_unixtime").set(_time.time() - 5.0)
    h = json.loads(urllib.request.urlopen(url, timeout=5).read())
    assert h["ok"] is True and h["served_step"] == 42
    assert 4.0 <= h["staleness_s"] <= 120.0
    # probing must not have CREATED gauges in an empty registry
    empty = telemetry.MetricsRegistry()
    assert empty.peek("stream/served_step") is None
    telemetry.MetricsServer(empty).close()
    assert empty.peek("stream/served_step") is None


def test_fleet_rollup_snapshot_ttl_expiry():
  """Pushed member snapshots expire out of ``?scope=fleet`` after the
  TTL — counted once (the heartbeat-quorum rule on the metrics plane);
  a re-push revives the member."""
  import time as _time
  import urllib.request

  local = telemetry.MetricsRegistry()
  local.counter("serve/completed").inc(10)
  member = telemetry.MetricsRegistry()
  member.counter("serve/completed").inc(7)
  with telemetry.MetricsServer(local, snapshot_ttl_s=0.25) as server:
    server.push("owner-0", member)
    fleet = urllib.request.urlopen(server.fleet_url,
                                   timeout=5).read().decode()
    assert "serve_completed 17" in fleet
    _time.sleep(0.4)
    fleet = urllib.request.urlopen(server.fleet_url,
                                   timeout=5).read().decode()
    assert "serve_completed 10" in fleet          # member dropped
    assert "telemetry_snapshots_expired 1" in fleet
    # counted once, not once per scrape
    fleet = urllib.request.urlopen(server.fleet_url,
                                   timeout=5).read().decode()
    assert "telemetry_snapshots_expired 1" in fleet
    # a re-push revives the member (and can expire again, counted)
    server.push("owner-0", member)
    fleet = urllib.request.urlopen(server.fleet_url,
                                   timeout=5).read().decode()
    assert "serve_completed 17" in fleet
    assert "telemetry_snapshots_expired 1" in fleet


def test_registry_remove_drops_metric_without_create():
  reg = telemetry.MetricsRegistry()
  reg.gauge("stream/last_promote_unixtime/dead").set(1.0)
  assert reg.remove("stream/last_promote_unixtime/dead") is True
  assert reg.peek("stream/last_promote_unixtime/dead") is None
  # removing an absent name is a no-op, not a create
  assert reg.remove("stream/last_promote_unixtime/dead") is False
  assert reg.peek("stream/last_promote_unixtime/dead") is None


def test_healthz_deregistered_member_leaves_most_stale_scan():
  """A deliberately removed member's keyed promote gauges drop out of
  the /healthz most-stale scan (a decommissioned subscriber must not
  read as a stalled sibling forever); the survivor's freshness wins."""
  import time as _time

  reg = telemetry.MetricsRegistry()
  now = _time.time()
  reg.gauge("stream/last_promote_unixtime/dead").set(now - 3600.0)
  reg.gauge("stream/served_step/dead").set(1)
  reg.gauge("stream/last_promote_unixtime/live").set(now - 1.0)
  reg.gauge("stream/served_step/live").set(9)
  with telemetry.MetricsServer(reg) as server:
    h = server.health()
    assert h["staleness_s"] >= 3000.0  # the dead member dominates
    for stem in ("stream/served_step", "stream/last_promote_unixtime"):
      assert reg.remove(f"{stem}/dead")
    h = server.health()
    assert h["served_step"] == 9 and h["staleness_s"] < 60.0


def test_span_ids_remint_across_fork():
  """fork()ed children re-mint the process tag + counter, so two
  processes never emit colliding span ids into one merged timeline.
  Runs in a jax-free subprocess (trace.py is stdlib-only at import
  time) — forking the threaded pytest process itself would be the
  exact hazard the re-mint guards against."""
  import subprocess
  import sys

  if not hasattr(os, "fork"):
    pytest.skip("no fork on this platform")
  prog = """
import importlib.util, os, sys
spec = importlib.util.spec_from_file_location("t", sys.argv[1])
t = importlib.util.module_from_spec(spec)
sys.modules["t"] = t
spec.loader.exec_module(t)
parent_id = t._next_span_id()
r, w = os.pipe()
pid = os.fork()
if pid == 0:
    os.write(w, t._next_span_id().encode())
    os._exit(0)
os.close(w)
child_id = b""
while True:
    chunk = os.read(r, 64)
    if not chunk:
        break
    child_id += chunk
os.waitpid(pid, 0)
print(parent_id, child_id.decode())
"""
  trace_py = os.path.join(os.path.dirname(telemetry.trace.__file__),
                          "trace.py")
  out = subprocess.run([sys.executable, "-c", prog, trace_py],
                       capture_output=True, text=True, timeout=60)
  assert out.returncode == 0, out.stderr
  parent_id, child_id = out.stdout.split()
  child_tag, _, child_seq = child_id.partition("-")
  assert child_tag and child_tag != parent_id.partition("-")[0]
  assert child_seq == "1"  # the child's counter restarted


def test_fleet_snapshot_ttl_sweeps_on_push():
  """Expired member snapshots are evicted on every PUSH, not only on
  ?scope=fleet reads — a churning fleet whose operator never scrapes
  the roll-up must not accumulate dead source ids' sections forever."""
  import time as _time

  local = telemetry.MetricsRegistry()
  m1 = telemetry.MetricsRegistry()
  m1.counter("serve/completed").inc(1)
  with telemetry.MetricsServer(local, snapshot_ttl_s=0.2) as server:
    server.push("dead-member", m1)
    _time.sleep(0.3)
    server.push("live-member", m1)  # the write sweeps the store
    assert set(server._server._snapshots) == {"live-member"}
    assert local.peek("telemetry/snapshots_expired").value == 1
