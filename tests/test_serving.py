"""Serving subsystem tests (`distributed_embeddings_tpu/serving/`).

The contracts under test:

- **f32 serving is BIT-exact** against ``make_sparse_eval_step`` across
  the parity matrix — raw and dedup'd routing, ragged value streams,
  row-sliced shards, tiered residency, world 1/2/4. Stripping the
  optimizer lanes is a storage decision, not a numeric one (including
  the narrow multi-hot combine, whose fp-addition grouping the serve
  path replicates from the eval step's masked-window fast path).
- **int8 dequantization error is bounded** per output element by
  ``h * 2^-7 * max|row|`` (per-row symmetric scales bound each row's
  error at ``max|row| / 254``; the combiner sums at most ``h`` rows —
  the asserted bound carries a ~2x margin).
- **eval/serve steps never donate parameter buffers**: a repeated-call
  step against one frozen state returns identical results, with or
  without request-array donation.
- **export -> load round-trips** through the crc32-manifest-last durable
  protocol, tiered cold images included; corruption is detected with
  the file named.
- **the micro-batcher de-interleaves exactly**: every request gets
  precisely its own rows back under random arrival interleavings, and
  the bounded queue sheds load with an exactly-counted rejection.
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu import checkpoint
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    get_weights,
    set_weights,
)
from distributed_embeddings_tpu.layers.embedding import TableConfig
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM
from distributed_embeddings_tpu.models.dlrm import _dlrm_initializer
from distributed_embeddings_tpu.models.synthetic import power_law_ids
from distributed_embeddings_tpu.ops.packed_table import sparse_rule
from distributed_embeddings_tpu.ops.ragged import RaggedIds
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.parallel.lookup_engine import PAD_ID
from distributed_embeddings_tpu.serving import (
    MicroBatcher,
    Rejected,
    ServeEngine,
    ServeTierConfig,
    dequantize_rows_fp8,
    dequantize_rows_int8,
    make_serve_step,
    quantize_rows_fp8,
    quantize_rows_int8,
)
from distributed_embeddings_tpu.serving.export import (
    freeze,
    frozen_device_state,
)
from distributed_embeddings_tpu.serving.export import export as serve_export
from distributed_embeddings_tpu.serving.export import load as serve_load
from distributed_embeddings_tpu.tiering import (
    HostTierStore,
    TieringConfig,
    TieringPlan,
    init_tiered_state_from_params,
)
from distributed_embeddings_tpu.training import (
    init_sparse_state,
    make_sparse_eval_step,
    shard_batch,
    shard_params,
)


class ActsModel:
  """Model stub returning the concatenated embedding activations —
  eval/serve parity at the lookup layer, every table visible."""

  def apply(self, variables, numerical, cats, emb_acts=None):
    del variables, numerical, cats
    return jnp.concatenate(list(emb_acts), axis=-1)


SIZES = [131, 97, 53, 40, 67]
WIDTHS = [16, 16, 8, 8, 16]
HOTNESS = [3, 1, 3, 2, 1]


def _fixture(world, combiner="sum", rule_name="adagrad", seed=0,
             batch_per_dev=4, **plan_kw):
  """Mixed fixture: multi-hot narrow w16 (the masked-combine fast path
  under adagrad), w8 classes, PAD holes; known weights for bounds."""
  rng = np.random.default_rng(seed)
  tables = [TableConfig(s, w, combiner=combiner)
            for s, w in zip(SIZES, WIDTHS)]
  plan = DistEmbeddingStrategy(tables, world, "memory_balanced",
                               dense_row_threshold=0,
                               input_hotness=HOTNESS, **plan_kw)
  weights = [rng.standard_normal((s, w)).astype(np.float32)
             for s, w in zip(SIZES, WIDTHS)]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule(rule_name, 0.05)
  opt = optax.sgd(0.01)
  mesh = create_mesh(world) if world > 1 else None
  state = shard_params(init_sparse_state(plan, params, rule, opt), mesh)
  b = batch_per_dev * world
  ids = []
  for s, h in zip(SIZES, HOTNESS):
    x = rng.integers(0, s, (b, h)).astype(np.int32)
    x[rng.random(x.shape) < 0.25] = PAD_ID
    ids.append(x)
  numerical = rng.standard_normal((b, 4)).astype(np.float32)
  labels = rng.integers(0, 2, b).astype(np.float32)
  batch = (jnp.asarray(numerical), tuple(jnp.asarray(x) for x in ids),
           jnp.asarray(labels))
  return plan, rule, mesh, state, batch, weights


def _eval_preds(plan, rule, mesh, state, batch):
  ev = make_sparse_eval_step(ActsModel(), plan, rule, mesh, state, batch)
  bt = shard_batch(batch, mesh)
  return np.asarray(ev(state, *bt[:2])), bt


def _serve_preds(plan, rule, mesh, state, batch, quantize,
                 donate_batch=False):
  frozen = freeze(plan, rule, state, quantize=quantize)
  sstate = frozen_device_state(frozen, plan, mesh)
  step = make_serve_step(ActsModel(), plan, frozen.meta, mesh, sstate,
                         (batch[0], batch[1]), donate_batch=donate_batch)
  bt = shard_batch(batch, mesh)
  return np.asarray(step(sstate, *bt[:2])), (step, sstate, frozen)


# ---------------------------------------------------------------------------
# int8 row codec
# ---------------------------------------------------------------------------


def test_int8_roundtrip_error_bound():
  rng = np.random.default_rng(1)
  table = rng.standard_normal((200, 16)).astype(np.float32) * \
      rng.uniform(0.01, 10.0, (200, 1)).astype(np.float32)
  table[7] = 0.0  # all-zero row stays exactly zero
  q = quantize_rows_int8(table)
  assert q.dtype == np.int8 and q.shape == (200, 20)
  deq = dequantize_rows_int8(q)
  amax = np.abs(table).max(axis=1, keepdims=True)
  assert np.all(np.abs(deq - table) <= amax / 254.0 + 1e-12)
  np.testing.assert_array_equal(deq[7], 0.0)


# ---------------------------------------------------------------------------
# f32 parity matrix: bit-exact vs make_sparse_eval_step
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("world", [1, 2, 4])
@pytest.mark.parametrize("dedup", [False, True])
def test_f32_serve_bitexact(world, dedup):
  plan, rule, mesh, state, batch, _ = _fixture(
      world, combiner="sum", dedup_exchange=dedup)
  want, _ = _eval_preds(plan, rule, mesh, state, batch)
  got, _ = _serve_preds(plan, rule, mesh, state, batch, "f32")
  np.testing.assert_array_equal(want, got)


def test_f32_serve_bitexact_mean_combiner():
  plan, rule, mesh, state, batch, _ = _fixture(4, combiner="mean")
  want, _ = _eval_preds(plan, rule, mesh, state, batch)
  got, _ = _serve_preds(plan, rule, mesh, state, batch, "f32")
  np.testing.assert_array_equal(want, got)


def test_f32_serve_bitexact_row_sliced():
  sizes = [96, 64, 48, 88]
  tables = [TableConfig(s, 8, combiner="mean") for s in sizes]
  plan = DistEmbeddingStrategy(tables, 4, "basic",
                               row_slice_threshold=16 * 8,
                               dense_row_threshold=0,
                               input_hotness=[3, 3, 1, 2])
  assert any(sh.row_sliced for shards in plan.rank_shards for sh in shards)
  rng = np.random.default_rng(3)
  weights = [rng.standard_normal((s, 8)).astype(np.float32) for s in sizes]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  mesh = create_mesh(4)
  state = shard_params(
      init_sparse_state(plan, params, rule, optax.sgd(0.01)), mesh)
  b = 8
  ids = []
  for s, h in zip(sizes, [3, 3, 1, 2]):
    x = rng.integers(0, s, (b, h)).astype(np.int32)
    x[rng.random(x.shape) < 0.2] = PAD_ID
    ids.append(jnp.asarray(x))
  batch = (jnp.zeros((b, 2), jnp.float32), tuple(ids),
           jnp.zeros((b,), jnp.float32))
  want, _ = _eval_preds(plan, rule, mesh, state, batch)
  got, _ = _serve_preds(plan, rule, mesh, state, batch, "f32")
  np.testing.assert_array_equal(want, got)


def test_f32_serve_bitexact_ragged():
  """A ragged value-stream input mixed with padded ones: the serve
  lookup rides the raw stream exactly like eval (segment-sum combine
  over identical values)."""
  world = 4
  tables = [TableConfig(60, 8, combiner="sum"),
            TableConfig(40, 8, combiner="sum")]
  plan = DistEmbeddingStrategy(tables, world, "basic",
                               input_hotness=[-8, 2],
                               dense_row_threshold=0)
  rng = np.random.default_rng(5)
  weights = [rng.standard_normal((c.input_dim, 8)).astype(np.float32)
             for c in tables]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  mesh = create_mesh(world)
  state = shard_params(
      init_sparse_state(plan, params, rule, optax.sgd(0.01)), mesh)

  b_local, cap = 4, 8
  values = rng.integers(0, 60, world * cap).astype(np.int32)
  lengths = np.minimum(rng.integers(0, 5, (world, b_local)),
                       cap // b_local)
  splits = np.concatenate([np.concatenate([[0], np.cumsum(l)])
                           for l in lengths]).astype(np.int32)
  rg = RaggedIds(jnp.asarray(values), jnp.asarray(splits))
  dense = jnp.asarray(
      rng.integers(0, 40, (world * b_local, 2)).astype(np.int32))
  b = world * b_local
  batch = (jnp.zeros((b, 2), jnp.float32), (rg, dense),
           jnp.zeros((b,), jnp.float32))
  want, _ = _eval_preds(plan, rule, mesh, state, batch)
  got, _ = _serve_preds(plan, rule, mesh, state, batch, "f32")
  np.testing.assert_array_equal(want, got)


# ---------------------------------------------------------------------------
# int8 error bound vs the f32 eval step
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_int8_serve_error_bound(combiner):
  plan, rule, mesh, state, batch, weights = _fixture(4, combiner=combiner)
  want, _ = _eval_preds(plan, rule, mesh, state, batch)
  got, _ = _serve_preds(plan, rule, mesh, state, batch, "int8")
  off = 0
  for t, (w, h) in enumerate(zip(weights, HOTNESS)):
    width = w.shape[1]
    a = want[:, off:off + width]
    b = got[:, off:off + width]
    # per row |err| <= max|row| / 254; a sum-combined bag adds <= h rows
    # (mean divides by the same count) -> h * 2^-7 * max|row| carries a
    # ~2x margin
    rows = h if combiner == "sum" else 1
    bound = rows * (2.0 ** -7) * np.abs(w).max() + 1e-6
    assert np.abs(a - b).max() <= bound, (t, np.abs(a - b).max(), bound)
    off += width
  # the quantization really narrowed something
  assert np.abs(want - got).max() > 0


def test_fp8_roundtrip_error_bound():
  rng = np.random.default_rng(2)
  table = rng.standard_normal((200, 16)).astype(np.float32) * \
      rng.uniform(0.01, 10.0, (200, 1)).astype(np.float32)
  table[7] = 0.0  # all-zero row stays exactly zero
  q = quantize_rows_fp8(table)
  assert str(q.dtype) == "float8_e4m3fn" and q.shape == (200, 20)
  deq = dequantize_rows_fp8(q)
  amax = np.abs(table).max(axis=1, keepdims=True)
  # e4m3: 3 mantissa bits -> per-element error <= 2^-4 * max|row|
  assert np.all(np.abs(deq - table) <= amax * 2.0 ** -4 + 1e-12)
  np.testing.assert_array_equal(deq[7], 0.0)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_fp8_serve_error_bound(combiner):
  plan, rule, mesh, state, batch, weights = _fixture(4, combiner=combiner)
  want, _ = _eval_preds(plan, rule, mesh, state, batch)
  got, _ = _serve_preds(plan, rule, mesh, state, batch, "fp8")
  off = 0
  for t, (w, h) in enumerate(zip(weights, HOTNESS)):
    width = w.shape[1]
    a = want[:, off:off + width]
    b = got[:, off:off + width]
    # per row |err| <= 2^-4 * max|row| (the wire bound at row
    # granularity); a sum-combined bag adds <= h rows
    rows = h if combiner == "sum" else 1
    bound = rows * (2.0 ** -4) * np.abs(w).max() + 1e-6
    assert np.abs(a - b).max() <= bound, (t, np.abs(a - b).max(), bound)
    off += width
  assert np.abs(want - got).max() > 0


# ---------------------------------------------------------------------------
# tiered serving: device cache + stripped host image
# ---------------------------------------------------------------------------


def _tiered_fixture():
  vocab = [5000, 300, 40]
  width = 16
  world = 4
  mktab = lambda: [TableConfig(v, width, initializer=_dlrm_initializer(v))  # noqa: E731
                   for v in vocab]
  plan_b = DistEmbeddingStrategy(mktab(), world, "memory_balanced",
                                 dense_row_threshold=0)
  plan_t = DistEmbeddingStrategy(mktab(), world, "memory_balanced",
                                 dense_row_threshold=0,
                                 host_row_threshold=1000)
  model = DLRM(vocab_sizes=vocab, embedding_dim=width,
               bottom_mlp=(32, width), top_mlp=(32, 1), world_size=world,
               strategy="memory_balanced", dense_row_threshold=0)
  mesh = create_mesh(world)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adam(1e-3)
  r = np.random.default_rng(7)
  b = 32
  numerical = r.standard_normal((b, 13)).astype(np.float32)
  cats = [power_law_ids(r, b, 1, v, 1.05).astype(np.int32)[:, 0]
          for v in vocab]
  labels = r.integers(0, 2, b).astype(np.float32)
  batch = (numerical, cats, labels)
  params_b = model.init(jax.random.PRNGKey(0), numerical, cats)["params"]
  state_b = shard_params(init_sparse_state(plan_b, params_b, rule, opt),
                         mesh)
  tables_t = set_weights(plan_t, get_weights(plan_b,
                                             params_b["embeddings"]))
  params_t = {k: v for k, v in params_b.items() if k != "embeddings"}
  params_t["embeddings"] = {k: jnp.asarray(v) for k, v in tables_t.items()}
  tplan = TieringPlan(plan_t, rule,
                      TieringConfig(cache_fraction=0.3, staging_grps=64))
  store = HostTierStore(tplan)
  state_t = shard_params(
      init_tiered_state_from_params(tplan, store, rule, params_t, opt,
                                    mesh=mesh), mesh)
  return (plan_b, plan_t, model, mesh, rule, state_b, state_t, store,
          batch)


@pytest.mark.parametrize("quantize", ["f32", "int8"])
def test_tiered_serve_vs_all_device_eval(quantize):
  (plan_b, plan_t, model, mesh, rule, state_b, state_t, store,
   batch) = _tiered_fixture()
  numerical, cats, labels = batch
  bt = shard_batch(batch, mesh)
  ev = make_sparse_eval_step(model, plan_b, rule, mesh, state_b, batch)
  want = np.asarray(ev(state_b, *bt[:2]))

  frozen = freeze(plan_t, rule, state_t, quantize=quantize, store=store)
  eng = ServeEngine(model, plan_t, frozen, mesh=mesh,
                    tier_config=ServeTierConfig(cache_fraction=0.3,
                                                staging_grps=64),
                    with_metrics=True)
  preds, metrics = eng.predict(numerical, cats)
  for name, m in metrics["tier"].items():
    hot, staged, missed, total = (int(v) for v in m)
    assert missed == 0, (name, m)        # the prefetch contract held
    assert hot + staged == total > 0, (name, m)
  if quantize == "f32":
    np.testing.assert_array_equal(want, preds)
  else:
    assert np.abs(want - preds).max() < 1e-3
  # repeated dispatch: immutable images, persistent residency
  preds2, _ = eng.predict(numerical, cats)
  np.testing.assert_array_equal(preds, preds2)


# ---------------------------------------------------------------------------
# export -> load roundtrip (durable protocol)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("quantize", ["f32", "int8", "fp8"])
def test_export_load_roundtrip(tmp_path, quantize):
  plan, rule, mesh, state, batch, _ = _fixture(2)
  path = os.path.join(str(tmp_path), "serve_art")
  frozen = serve_export(path, plan, rule, state, quantize=quantize)
  assert checkpoint.verify(path) == []
  art = serve_load(path, plan, mesh=mesh)
  assert art.quantize == quantize
  for name, blocks in frozen.device_blocks.items():
    # byte view: the bit-packed scale lanes may hold NaN-patterned fp8
    np.testing.assert_array_equal(
        np.asarray(art.state["serve"][name]).view(np.uint8),
        np.concatenate(blocks).view(np.uint8))
  # loaded artifact predicts identically to the in-memory frozen state
  sstate = frozen_device_state(frozen, plan, mesh)
  step = make_serve_step(ActsModel(), plan, frozen.meta, mesh, sstate,
                         (batch[0], batch[1]))
  bt = shard_batch(batch, mesh)
  want = np.asarray(step(sstate, *bt[:2]))
  step2 = make_serve_step(ActsModel(), plan, art.meta, mesh, art.state,
                          (batch[0], batch[1]))
  np.testing.assert_array_equal(want, np.asarray(step2(art.state,
                                                       *bt[:2])))


def test_export_load_roundtrip_tiered(tmp_path):
  (plan_b, plan_t, model, mesh, rule, state_b, state_t, store,
   batch) = _tiered_fixture()
  numerical, cats, _ = batch
  path = os.path.join(str(tmp_path), "serve_tiered")
  frozen = serve_export(path, plan_t, rule, state_t, quantize="f32",
                        store=store)
  assert frozen.host_images and checkpoint.verify(path) == []
  # cold images really landed as files
  cold = [f for f in os.listdir(path) if f.startswith("serve_cold_")]
  assert len(cold) == plan_t.world_size * len(frozen.host_images)
  art = serve_load(path, plan_t, mesh=mesh)
  for name, images in frozen.host_images.items():
    for r, img in enumerate(images):
      np.testing.assert_array_equal(art.host_images[name][r], img)
    for r in range(plan_t.world_size):
      np.testing.assert_array_equal(art.ranking[name][r],
                                    frozen.ranking[name][r])
  cfg = ServeTierConfig(cache_fraction=0.3, staging_grps=64)
  want = ServeEngine(model, plan_t, frozen, mesh=mesh,
                     tier_config=cfg).predict(numerical, cats)
  got = ServeEngine(model, plan_t, art, mesh=mesh,
                    tier_config=cfg).predict(numerical, cats)
  np.testing.assert_array_equal(want, got)


def test_export_corruption_detected(tmp_path):
  plan, rule, mesh, state, batch, _ = _fixture(2)
  path = os.path.join(str(tmp_path), "serve_bad")
  serve_export(path, plan, rule, state, quantize="int8")
  victim = sorted(f for f in os.listdir(path)
                  if f.startswith("serve_"))[0]
  fpath = os.path.join(path, victim)
  with open(fpath, "r+b") as f:
    f.seek(os.path.getsize(fpath) - 1)
    byte = f.read(1)
    f.seek(os.path.getsize(fpath) - 1)
    f.write(bytes([byte[0] ^ 0xFF]))
  problems = checkpoint.verify(path)
  assert problems and victim in problems[0]
  with pytest.raises(ValueError, match=victim):
    serve_load(path, plan, mesh=mesh)


def test_load_refuses_plan_mismatch(tmp_path):
  plan, rule, mesh, state, batch, _ = _fixture(2)
  path = os.path.join(str(tmp_path), "serve_art")
  serve_export(path, plan, rule, state)
  other = DistEmbeddingStrategy(
      [TableConfig(s + 1, w, combiner="sum")
       for s, w in zip(SIZES, WIDTHS)], 2, "memory_balanced",
      dense_row_threshold=0, input_hotness=HOTNESS)
  with pytest.raises(ValueError, match="does not match"):
    serve_load(path, other, mesh=mesh)


# ---------------------------------------------------------------------------
# donation contract: repeated-call eval/serve steps
# ---------------------------------------------------------------------------


def test_eval_and_serve_steps_never_donate_params():
  """The regression the ISSUE names: a repeated-call eval/serve step
  must never invalidate the frozen table. Both steps run TWICE against
  the same state object — donated buffers would fail loudly on the
  second call (and the state stays usable afterwards)."""
  plan, rule, mesh, state, batch, _ = _fixture(2)
  bt = shard_batch(batch, mesh)
  ev = make_sparse_eval_step(ActsModel(), plan, rule, mesh, state, batch)
  first = np.asarray(ev(state, *bt[:2]))
  second = np.asarray(ev(state, *bt[:2]))
  np.testing.assert_array_equal(first, second)
  # serve step WITH request-array donation: params still never donated
  got, (step, sstate, _) = _serve_preds(plan, rule, mesh, state, batch,
                                        "f32", donate_batch=True)
  bt2 = shard_batch(batch, mesh)  # fresh request arrays (donated above)
  again = np.asarray(step(sstate, *bt2[:2]))
  np.testing.assert_array_equal(got, again)
  np.testing.assert_array_equal(first, got)


def test_serve_refuses_unservable_plans():
  plan, rule, mesh, state, batch, _ = _fixture(
      2, dedup_exchange=True, dedup_capacity=8)
  frozen = freeze(plan, rule, state)
  with pytest.raises(ValueError, match="dedup_capacity"):
    make_serve_step(ActsModel(), plan, frozen.meta, mesh,
                    frozen_device_state(frozen, plan, mesh),
                    (batch[0], batch[1]))
  plan_e, rule_e, mesh_e, state_e, batch_e, _ = _fixture(2, oov="error")
  frozen_e = freeze(plan_e, rule_e, state_e)
  with pytest.raises(ValueError, match="oov"):
    make_serve_step(ActsModel(), plan_e, frozen_e.meta, mesh_e,
                    frozen_device_state(frozen_e, plan_e, mesh_e),
                    (batch_e[0], batch_e[1]))


# ---------------------------------------------------------------------------
# micro-batcher
# ---------------------------------------------------------------------------


def _echo_dispatch(numerical, cats):
  """Row-identity dispatch: output row i encodes (numerical[i, 0],
  cats[0][i]) — de-interleave errors are unmissable."""
  return np.stack([numerical[:, 0], cats[0].astype(np.float64)], axis=1)


def test_batcher_deinterleave_property():
  """Every request gets exactly its own rows back under random
  arrival interleavings from concurrent submitters."""
  mb = MicroBatcher(_echo_dispatch, max_batch=32, max_delay_s=0.002)
  failures = []

  def client(tid, rng):
    for i in range(40):
      n = int(rng.integers(1, 9))
      tag = tid * 10000 + i
      numerical = np.full((n, 3), tag, np.float32)
      cats = [np.arange(n, dtype=np.int32) + tag]
      while True:
        try:
          fut = mb.submit(numerical, cats)
          break
        except Rejected:
          time.sleep(0.001)
      out = fut.result(timeout=30)
      if out.shape[0] != n or not np.all(out[:, 0] == tag) \
          or not np.all(out[:, 1] == np.arange(n) + tag):
        failures.append((tid, i, out))
      if rng.random() < 0.3:
        time.sleep(float(rng.random()) * 0.002)

  threads = [threading.Thread(target=client,
                              args=(t, np.random.default_rng(t)))
             for t in range(6)]
  for t in threads:
    t.start()
  for t in threads:
    t.join()
  mb.close()
  assert not failures
  assert mb.stats["completed"] == 6 * 40
  assert mb.stats["batches"] >= (6 * 40 * 1) // 32  # really coalesced


def test_batcher_rejection_counted_exactly():
  """Load-shed accounting: with the flusher paused, submissions past
  the row bound are rejected — each one counted, none enqueued."""
  mb = MicroBatcher(_echo_dispatch, max_batch=8, queue_rows=16,
                    start=False)
  accepted = rejected = 0
  for _ in range(10):
    try:
      mb.submit(np.zeros((3, 2), np.float32), [np.zeros(3, np.int32)])
      accepted += 1
    except Rejected:
      rejected += 1
  assert (accepted, rejected) == (5, 5)  # 5*3=15 fits 16; the 6th would be 18
  assert mb.stats["rejected"] == 5
  assert mb.stats["submitted"] == 10
  mb.flush_now()
  assert mb.stats["completed"] == 5
  mb.close()


def test_batcher_deadline_flush_and_padding():
  """A lone small request must not wait for a full batch: the deadline
  flush fires and the dispatch is padded to max_batch."""
  seen = []

  def spy(numerical, cats):
    seen.append(numerical.shape[0])
    return _echo_dispatch(numerical, cats)

  mb = MicroBatcher(spy, max_batch=16, max_delay_s=0.005)
  t0 = time.monotonic()
  fut = mb.submit(np.full((2, 1), 3.0, np.float32),
                  [np.arange(2, dtype=np.int32)])
  out = fut.result(timeout=10)
  assert time.monotonic() - t0 < 5.0
  assert out.shape[0] == 2 and np.all(out[:, 0] == 3.0)
  assert seen == [16]  # padded to the constant dispatch shape
  assert mb.stats["padded_rows"] == 14
  mb.close()


def test_batcher_reject_reasons_exact_accounting():
  """Every shed carries its reason and is counted exactly once in the
  total AND once per reason — queue-full, deadline-expired, and
  priority-shed are distinguishable at the edge."""
  mb = MicroBatcher(_echo_dispatch, max_batch=8, queue_rows=16,
                    start=False)
  # --- queue_full: default-priority overflow sheds the INCOMING request
  for _ in range(5):
    mb.submit(np.zeros((3, 2), np.float32), [np.zeros(3, np.int32)])
  with pytest.raises(Rejected) as exc:
    mb.submit(np.zeros((3, 2), np.float32), [np.zeros(3, np.int32)])
  assert exc.value.reason == "queue_full"
  assert mb.stats["rejected"] == 1
  assert mb.stats["rejected/queue_full"] == 1
  # --- priority_shed: a priority arrival evicts queued priority-0 work
  hi = mb.submit(np.zeros((3, 2), np.float32), [np.zeros(3, np.int32)],
                 priority=2)
  assert mb.stats["rejected/priority_shed"] == 1
  assert mb.stats["rejected"] == 2
  mb.flush_now()
  assert hi.result(timeout=5).shape[0] == 3  # the priority request ran
  # --- deadline_expired: an expired request is purged, never dispatched
  fut = mb.submit(np.zeros((2, 2), np.float32), [np.zeros(2, np.int32)],
                  deadline_s=0.0)
  completed0 = mb.stats["completed"]
  mb.flush_now()
  with pytest.raises(Rejected) as exc:
    fut.result(timeout=5)
  assert exc.value.reason == "deadline_expired"
  assert mb.stats["rejected/deadline_expired"] == 1
  assert mb.stats["rejected"] == 3
  assert mb.stats["completed"] == completed0  # it consumed no dispatch
  assert mb.stats["rejected"] == sum(
      mb.stats[f"rejected/{r}"] for r in
      ("queue_full", "deadline_expired", "priority_shed"))
  mb.close()


def test_batcher_priority_shed_fails_victim_and_packs_priority_first():
  """The evicted victim's future fails with reason 'priority_shed';
  flushes pack higher priorities first (FIFO within a priority)."""
  order = []

  def spy(numerical, cats):
    order.append(numerical[:, 0].copy())
    return _echo_dispatch(numerical, cats)

  mb = MicroBatcher(spy, max_batch=4, queue_rows=8, start=False)
  lo1 = mb.submit(np.full((4, 2), 1.0, np.float32),
                  [np.zeros(4, np.int32)], priority=0)
  lo2 = mb.submit(np.full((4, 2), 2.0, np.float32),
                  [np.zeros(4, np.int32)], priority=0)
  hi = mb.submit(np.full((4, 2), 9.0, np.float32),
                 [np.zeros(4, np.int32)], priority=5)
  # the YOUNGEST low-priority request was evicted; the older kept its place
  with pytest.raises(Rejected) as exc:
    lo2.result(timeout=5)
  assert exc.value.reason == "priority_shed"
  mb.flush_now()
  assert hi.result(timeout=5) is not None
  assert lo1.result(timeout=5) is not None
  # priority 5 dispatched before the remaining priority 0
  assert [int(b[0]) for b in order] == [9, 1]
  mb.close()


def test_batcher_rejects_oversize_and_close():
  mb = MicroBatcher(_echo_dispatch, max_batch=4, start=False)
  with pytest.raises(ValueError, match="max_batch"):
    mb.submit(np.zeros((5, 1), np.float32), [np.zeros(5, np.int32)])
  fut = mb.submit(np.ones((2, 1), np.float32),
                  [np.arange(2, dtype=np.int32)])
  mb.close(drain=True)
  assert fut.result(timeout=5).shape[0] == 2
  with pytest.raises(RuntimeError, match="closed"):
    mb.submit(np.ones((1, 1), np.float32), [np.zeros(1, np.int32)])


def test_batcher_drain_failure_fails_queued_waiters():
  """A dispatch failure mid-drain must fail every still-queued request's
  future — a stranded waiter with no timeout would block forever."""
  def boom(numerical, cats):
    raise RuntimeError("kaput")

  mb = MicroBatcher(boom, max_batch=4, start=False)
  f1 = mb.submit(np.zeros((4, 1), np.float32), [np.zeros(4, np.int32)])
  f2 = mb.submit(np.zeros((4, 1), np.float32), [np.zeros(4, np.int32)])
  with pytest.raises(RuntimeError):
    mb.close(drain=True)
  for f in (f1, f2):
    assert f.done()
    with pytest.raises(RuntimeError):
      f.result(timeout=1)


def test_batcher_flusher_death_fails_queued_requests():
  """A flusher thread killed by an UNEXPECTED exception (machinery
  death, not a dispatch failure) must fail every queued request with a
  counted ``flusher_died`` shed instead of leaving the waiters hanging
  forever, close the batcher, and set the /healthz dead-thread gauge
  (ISSUE 15 satellite)."""
  mb = MicroBatcher(_echo_dispatch, max_batch=8, max_delay_s=0.002)

  def wrenched():
    raise RuntimeError("wrenched machinery")

  mb._take_batch_locked = wrenched  # dies on its next flush wakeup
  futs = [mb.submit(np.zeros((2, 2), np.float32), [np.zeros(2, np.int32)])
          for _ in range(3)]
  for f in futs:
    with pytest.raises(Rejected) as exc:
      f.result(timeout=10)  # bounded: the death handler failed them
    assert exc.value.reason == "flusher_died"
    assert "serve-batcher-flush" in str(exc.value)
  assert mb.stats["rejected/flusher_died"] == 3
  assert mb.stats["rejected"] == 3
  # new submissions shed with the same counted reason, naming the thread
  with pytest.raises(Rejected) as exc:
    mb.submit(np.zeros((1, 2), np.float32), [np.zeros(1, np.int32)])
  assert exc.value.reason == "flusher_died"
  assert mb.stats["rejected/flusher_died"] == 4
  # the dead thread is surfaced for /healthz
  from distributed_embeddings_tpu.telemetry.http import (
      DEAD_THREAD_GAUGE_STEM,
  )
  assert mb.telemetry.gauge(DEAD_THREAD_GAUGE_STEM).value == 1
  key = f"{DEAD_THREAD_GAUGE_STEM}/serve-batcher-flush"
  assert mb.telemetry.gauge(key).value == 1
  mb.close()


def test_batcher_completer_death_fails_inflight_requests():
  """The completer dying mid-item must fail THAT item's waiters too
  (it was already popped from the in-flight queue), and the flusher
  must not wedge behind a dead completer."""
  mb = MicroBatcher(_echo_dispatch, max_batch=4, max_delay_s=0.002,
                    pipeline_depth=1)

  def wrenched(*a, **k):
    raise RuntimeError("completer wrenched")

  mb._complete = wrenched
  fut = mb.submit(np.zeros((2, 2), np.float32), [np.zeros(2, np.int32)])
  with pytest.raises(Rejected) as exc:
    fut.result(timeout=10)
  assert exc.value.reason == "flusher_died"
  assert mb.stats["rejected/flusher_died"] >= 1
  mb.close()


def test_healthz_reports_dead_batcher_thread():
  """A MetricsServer sharing the batcher's registry turns the dead
  thread into ok=False + its name in the /healthz body — readiness
  fails instead of the process answering 'ok' while every request
  sheds."""
  from distributed_embeddings_tpu.telemetry import (
      MetricsRegistry,
      MetricsServer,
  )
  reg = MetricsRegistry()
  with MetricsServer(registry=reg) as srv:
    assert srv.health()["ok"] is True
    mb = MicroBatcher(_echo_dispatch, max_batch=4, max_delay_s=0.002,
                      registry=reg)

    def wrenched():
      raise RuntimeError("boom")

    mb._take_batch_locked = wrenched
    fut = mb.submit(np.zeros((1, 2), np.float32), [np.zeros(1, np.int32)])
    with pytest.raises(Rejected):
      fut.result(timeout=10)
    health = srv.health()
    assert health["ok"] is False
    assert health["dead_threads"] == ["serve-batcher-flush"]
    mb.close()
    # the sanctioned recovery ("rebuild the batcher") restores
    # readiness: a replacement on the same registry clears the gauges
    mb2 = MicroBatcher(_echo_dispatch, max_batch=4, max_delay_s=0.002,
                       registry=reg)
    health = srv.health()
    assert health["ok"] is True
    assert "dead_threads" not in health
    fut = mb2.submit(np.zeros((1, 2), np.float32),
                     [np.zeros(1, np.int32)])
    assert fut.result(timeout=10).shape[0] == 1
    mb2.close()
    # the clear is scoped to the rebuilt batcher's OWN thread names: a
    # still-dead SIBLING (distinct name=) keeps readiness failing even
    # while another batcher is rebuilt on the shared registry
    sib = MicroBatcher(_echo_dispatch, max_batch=4, max_delay_s=0.002,
                       registry=reg, name="sibling")
    sib._take_batch_locked = wrenched
    with pytest.raises(Rejected):
      sib.submit(np.zeros((1, 2), np.float32),
                 [np.zeros(1, np.int32)]).result(timeout=10)
    assert srv.health()["dead_threads"] == ["sibling-flush"]
    mb3 = MicroBatcher(_echo_dispatch, max_batch=4, max_delay_s=0.002,
                       registry=reg)  # rebuild of the DEFAULT batcher
    health = srv.health()
    assert health["ok"] is False
    assert health["dead_threads"] == ["sibling-flush"]
    mb3.close()
    sib.close()


@pytest.mark.slow
def test_profile_serve_full_sweep():
  """The full serve-bench sweep (throughput + latency-vs-QPS across
  {f32,int8} x {all-device,tiered} x batcher deadlines) passes its
  acceptance bars; the smoke tier rides `make verify` instead."""
  import subprocess
  import sys
  repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
  env = dict(os.environ)
  env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
  r = subprocess.run(
      [sys.executable, os.path.join(repo, "tools", "profile_serve.py")],
      env=env, capture_output=True, text=True, timeout=1800)
  assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-4000:]


def test_batcher_end_to_end_with_engine():
  """Real engine behind the batcher: concurrent variable-size requests
  against a frozen DLRM, each result matching a direct dispatch of the
  same rows.  The batcher's locks run instrumented (telemetry.lockorder)
  and the observed acquisition order must stay consistent with
  threadlint's static lock graph."""
  from distributed_embeddings_tpu.analysis import threadlint
  from distributed_embeddings_tpu.telemetry import LockOrderMonitor
  (plan_b, plan_t, model, mesh, rule, state_b, state_t, store,
   batch) = _tiered_fixture()
  numerical, cats, _ = batch
  frozen = freeze(plan_b, rule, state_b, quantize="int8")
  eng = ServeEngine(model, plan_b, frozen, mesh=mesh)
  max_batch = 16
  mb = MicroBatcher(eng.dispatch, max_batch=max_batch, max_delay_s=0.005)
  mon = LockOrderMonitor()
  # _nonempty is Condition(self._lock): one lock, one name
  mb._lock = mon.wrap(mb._lock, "MicroBatcher._lock")
  mb._nonempty = mon.wrap(mb._nonempty, "MicroBatcher._lock")
  eng.lock = mon.wrap(eng.lock, "ServeEngine.lock")

  def direct(rows):
    n = rows[0].shape[0]
    pad = max_batch - n
    num_p = np.concatenate(
        [rows[0], np.zeros((pad,) + rows[0].shape[1:], np.float32)])
    cats_p = [np.concatenate([c, np.full((pad,), PAD_ID, c.dtype)])
              for c in rows[1]]
    return np.asarray(eng.dispatch(num_p, cats_p))[:n]

  futs, wants = [], []
  rng = np.random.default_rng(11)
  for _ in range(12):
    n = int(rng.integers(1, 6))
    lo = int(rng.integers(0, numerical.shape[0] - n))
    req = (numerical[lo:lo + n], [c[lo:lo + n] for c in cats])
    futs.append(mb.submit(*req))
    wants.append(direct(req))
  for fut, want in zip(futs, wants):
    got = fut.result(timeout=60)
    np.testing.assert_allclose(got, want, atol=1e-5)
  mb.close()
  # the runtime sanitizer saw real flush/complete/submit interleavings;
  # merged with the static graph the order must still be acyclic
  mon.assert_consistent_with(threadlint.static_lock_edges())
