"""Criteo split-binary reader + dummy data + LR schedule tests."""

import numpy as np
import pytest

from distributed_embeddings_tpu.utils import (
    DummyDataset,
    RawBinaryCriteoDataset,
    categorical_dtype,
    dlrm_lr_schedule,
    write_dummy_criteo_split,
)


def test_categorical_dtype_selection():
  assert categorical_dtype(100) == np.int8
  assert categorical_dtype(30_000) == np.int16
  assert categorical_dtype(1_000_000) == np.int32
  assert categorical_dtype(3_000_000_000) == np.int64


def test_raw_binary_roundtrip(tmp_path):
  vocab = [50, 20_000, 1_000_000]
  write_dummy_criteo_split(str(tmp_path), num_samples=64, vocab_sizes=vocab,
                           seed=5)
  ds = RawBinaryCriteoDataset(str(tmp_path), batch_size=16,
                              numerical_features=13,
                              categorical_features=[0, 1, 2],
                              categorical_feature_sizes=vocab)
  assert len(ds) == 4
  numerical, cats, labels = ds[0]
  assert numerical.shape == (16, 13) and numerical.dtype == np.float32
  assert labels.shape == (16,)
  assert len(cats) == 3
  for c, v in zip(cats, vocab):
    assert c.dtype == np.int32
    assert c.min() >= 0 and c.max() < v
  # dtype widths on disk follow vocabulary size
  assert (tmp_path / "train" / "cat_0.bin").stat().st_size == 64  # int8
  assert (tmp_path / "train" / "cat_1.bin").stat().st_size == 128  # int16
  assert (tmp_path / "train" / "cat_2.bin").stat().st_size == 256  # int32


def test_raw_binary_dp_slicing(tmp_path):
  vocab = [100]
  write_dummy_criteo_split(str(tmp_path), num_samples=64, vocab_sizes=vocab)
  full = RawBinaryCriteoDataset(str(tmp_path), batch_size=8,
                                categorical_features=[0],
                                categorical_feature_sizes=vocab)
  r0 = RawBinaryCriteoDataset(str(tmp_path), batch_size=4,
                              categorical_features=[0],
                              categorical_feature_sizes=vocab,
                              rank=0, world_size=2)
  r1 = RawBinaryCriteoDataset(str(tmp_path), batch_size=4,
                              categorical_features=[0],
                              categorical_feature_sizes=vocab,
                              rank=1, world_size=2)
  assert len(r0) == len(r1) == 8
  _, full_cats, _ = full[0]
  _, c0, _ = r0[0]
  _, c1, _ = r1[0]
  np.testing.assert_array_equal(np.concatenate([c0[0], c1[0]]), full_cats[0])


def test_raw_binary_prefetch_iteration(tmp_path):
  vocab = [100]
  write_dummy_criteo_split(str(tmp_path), num_samples=32, vocab_sizes=vocab)
  ds = RawBinaryCriteoDataset(str(tmp_path), batch_size=8,
                              categorical_features=[0],
                              categorical_feature_sizes=vocab,
                              prefetch_depth=2)
  batches = list(ds)
  assert len(batches) == 4
  for i, (num, cats, labels) in enumerate(batches):
    want_num, want_cats, want_labels = ds[i]
    np.testing.assert_array_equal(cats[0], want_cats[0])
    np.testing.assert_array_equal(labels, want_labels)


def test_raw_binary_size_mismatch_raises(tmp_path):
  vocab = [100]
  write_dummy_criteo_split(str(tmp_path), num_samples=32, vocab_sizes=vocab)
  # truncate a cat file -> mismatch must raise
  p = tmp_path / "train" / "cat_0.bin"
  p.write_bytes(p.read_bytes()[:-8])
  with pytest.raises(ValueError):
    RawBinaryCriteoDataset(str(tmp_path), batch_size=8,
                           categorical_features=[0],
                           categorical_feature_sizes=vocab)


def test_dummy_dataset_deterministic():
  a = DummyDataset(8, 13, [10, 20], num_batches=3, seed=1)
  b = DummyDataset(8, 13, [10, 20], num_batches=3, seed=1)
  na, ca, la = a[1]
  nb, cb, lb = b[1]
  np.testing.assert_array_equal(na, nb)
  np.testing.assert_array_equal(ca[0], cb[0])
  np.testing.assert_array_equal(la, lb)


def test_lr_schedule_phases():
  import jax.numpy as jnp

  sched = dlrm_lr_schedule(24.0, warmup_steps=10, decay_start_step=100,
                           decay_steps=50)
  # warmup ramps linearly
  assert float(sched(0)) == pytest.approx(2.4)
  assert float(sched(9)) == pytest.approx(24.0)
  # plateau
  assert float(sched(50)) == pytest.approx(24.0)
  # poly decay to zero
  assert float(sched(125)) == pytest.approx(24.0 * 0.25, rel=1e-5)
  assert float(sched(150)) == pytest.approx(0.0, abs=1e-6)
  assert float(sched(1000)) == pytest.approx(0.0, abs=1e-6)
