"""Reusable spawned-process harness: a world-2 CPU pod over
``jax.distributed``.

Multi-controller behavior cannot be tested by monkeypatching
``jax.process_count`` — the collectives (barrier syncs, verdict
broadcasts, the checkpoint clock handshake) only exist across REAL
processes. This module spawns them: two ``jax.distributed`` processes
on a localhost coordinator, 4 virtual CPU devices each (an 8-device
global mesh), sharing the test's tmpdir as the "pod filesystem".

Usage::

    from multiproc import spawn_world2
    BODY = r'''
    # ... runs after the PRELUDE on both processes; `proc_id`, `port`
    # and `tmpdir` are in scope, jax.distributed is initialized ...
    print("PROC", proc_id, "OK")
    '''
    def test_something(tmp_path):
      spawn_world2(tmp_path, BODY)

The worker body must end by printing ``PROC <i> OK`` on success;
``spawn_world2`` asserts both processes exit 0 with that marker and
returns their interleaved stdout+stderr for extra assertions.
"""

import os
import socket
import subprocess
import sys

PRELUDE = r"""
import os, sys, json
proc_id = int(sys.argv[1]); port = sys.argv[2]; tmpdir = sys.argv[3]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.pop("JAX_PLATFORMS", None)
import jax
jax.config.update("jax_platforms", "cpu")
# real cross-process collectives on the CPU backend (barrier syncs,
# verdict broadcasts, the checkpoint clock handshake) run over gloo
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=proc_id)
assert jax.process_count() == 2 and len(jax.devices()) == 8
"""


def free_port() -> int:
  """A port the coordinator can bind (raced only by the whole OS)."""
  with socket.socket() as s:
    s.bind(("127.0.0.1", 0))
    return s.getsockname()[1]


def spawn_world2(tmp_path, body: str, timeout_s: float = 300.0):
  """Run ``PRELUDE + body`` as two real jax.distributed processes.

  Asserts both exit 0 and print their ``PROC <i> OK`` marker; a hung
  worker is killed at ``timeout_s`` so it cannot leak past the test.
  Returns ``[stdout_0, stdout_1]`` (stderr folded in).
  """
  script = os.path.join(str(tmp_path), "worker.py")
  with open(script, "w") as f:
    f.write(PRELUDE + "\n" + body)
  port = free_port()
  env = {k: v for k, v in os.environ.items()
         if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PYTHONPATH")}
  env["PYTHONPATH"] = os.path.dirname(
      os.path.dirname(os.path.abspath(__file__)))
  procs = [subprocess.Popen(
      [sys.executable, script, str(i), str(port), str(tmp_path)],
      env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
      for i in range(2)]
  outs = []
  try:
    for p in procs:
      out, _ = p.communicate(timeout=timeout_s)
      outs.append(out)
  finally:
    for p in procs:  # a hung worker must not leak past the test
      if p.poll() is None:
        p.kill()
        p.wait()
  for i, (p, out) in enumerate(zip(procs, outs)):
    assert p.returncode == 0, f"proc {i} rc={p.returncode}\n{out[-3000:]}"
    assert f"PROC {i} OK" in out, out[-3000:]
  return outs
