"""Dynamic-vocabulary tests (`distributed_embeddings_tpu/dynvocab/`).

The contract under test: ``oov='allocate'`` replaces the static id space
with a host-side translated one WITHOUT touching the traced step —

- the open-addressing translation table round-trips any id stream within
  capacity losslessly and deterministically;
- the count-min sketch never undercounts (admission can only err toward
  early admission, never starvation), and its overcount is bounded;
- a dynvocab run over a pre-admitted in-capacity stream is BIT-EXACT
  against the static-vocab run it shadows (forward, loss, and update
  trajectory) — across worlds, guarded, and micro-batched;
- eviction recycles rows in place: a re-admitted id lands on a row whose
  table AND optimizer lanes were re-zeroed on device;
- the id space persists through the checkpoint manifest's ``vocab``
  section: auto-resume restores table/sketch/freelist exactly, and the
  cumulative lifecycle counters survive restarts un-double-counted;
- eval and serve builders refuse ``'allocate'`` plans at build time (an
  inference path must never mutate the id space).
"""

import numpy as np
import optax
import pytest

import jax

from distributed_embeddings_tpu import checkpoint
from distributed_embeddings_tpu.dynvocab import (
    CountMinSketch,
    DynVocabTrainer,
    DynVocabTranslator,
    IdTranslationTable,
)
from distributed_embeddings_tpu.layers.embedding import TableConfig
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM, bce_loss
from distributed_embeddings_tpu.models.dlrm import _dlrm_initializer
from distributed_embeddings_tpu.ops.packed_table import sparse_rule
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.training import (
    init_sparse_state_direct,
    make_sparse_eval_step,
    make_sparse_train_step,
    make_train_step,
    shard_batch,
    shard_params,
)

WIDTH = 16
VOCAB = [500, 300]
RULE = sparse_rule("adagrad", 0.05)


def _tables(vocab=VOCAB):
  return [TableConfig(input_dim=v, output_dim=WIDTH,
                      initializer=_dlrm_initializer(v)) for v in vocab]


def _plan(world, vocab=VOCAB, **kw):
  return DistEmbeddingStrategy(_tables(vocab), world, "memory_balanced",
                               dense_row_threshold=0, **kw)


def _model(world, vocab=VOCAB):
  return DLRM(vocab_sizes=vocab, embedding_dim=WIDTH,
              bottom_mlp=(32, WIDTH), top_mlp=(32, 1), world_size=world,
              strategy="memory_balanced", dense_row_threshold=0)


def _batch(seed, vocab=VOCAB, batch=32):
  r = np.random.default_rng(seed)
  numerical = r.standard_normal((batch, 13)).astype(np.float32)
  cats = [r.integers(0, v, batch, dtype=np.int64) for v in vocab]
  labels = r.integers(0, 2, batch).astype(np.float32)
  return numerical, cats, labels


def _dense_params(model, batch0):
  num, cats, _ = batch0
  dummy = [np.zeros((2, WIDTH), np.float32) for _ in cats]
  return model.init(jax.random.PRNGKey(0), num[:2], [c[:2] for c in cats],
                    emb_acts=dummy)["params"]


def _fresh(world, plan, batch0, guard=True, micro_batches=1):
  model = _model(world)
  mesh = create_mesh(world) if world > 1 else None
  dense = _dense_params(model, batch0)
  state = shard_params(
      init_sparse_state_direct(plan, RULE, dense, optax.adam(1e-3),
                               jax.random.PRNGKey(1)), mesh)
  translator = DynVocabTranslator(plan, RULE)
  trainer = DynVocabTrainer(model, plan, translator, bce_loss,
                            optax.adam(1e-3), RULE, mesh, state, batch0,
                            guard=guard, micro_batches=micro_batches,
                            donate=False)
  return model, mesh, trainer


# ---------------------------------------------------------------------------
# units: translation table
# ---------------------------------------------------------------------------


def test_table_roundtrip_lossless_and_deterministic():
  """Any distinct-id set within capacity maps losslessly: distinct rows
  in [0, capacity), stable across repeated lookups, and identical when a
  fresh table replays the same insertion sequence."""
  rng = np.random.default_rng(3)
  cap = 512
  ids = rng.choice(10 ** 12, size=cap, replace=False).astype(np.int64)
  t1 = IdTranslationTable(cap)
  t2 = IdTranslationTable(cap)
  for row, i in enumerate(ids.tolist()):
    t1.insert(i, row)
    t2.insert(i, row)
  for t in (t1, t2):
    rows = t.lookup(ids)
    assert np.array_equal(rows, np.arange(cap, dtype=np.int32))
    assert np.array_equal(rows, t.lookup(ids))  # stable
  # unmapped ids miss, mapped ids hit, interleaved
  probe = np.concatenate([ids[:7], ids[:7] + 1])
  got = t1.lookup(probe)
  assert np.array_equal(got[:7], np.arange(7, dtype=np.int32))
  assert np.all(got[7:] == -1)


def test_table_remove_tombstones_and_rebuild():
  """Insert/remove churn (forcing tombstone compaction) never corrupts
  the surviving mapping, and items() captures exactly the live set."""
  cap = 64
  t = IdTranslationTable(cap)
  live = {}
  next_row = list(range(cap))
  rng = np.random.default_rng(11)
  for step in range(2000):
    if live and (len(live) == cap or rng.random() < 0.5):
      rid = sorted(live)[int(rng.integers(len(live)))]
      row = t.remove(rid)
      assert row == live.pop(rid)
      next_row.append(row)
    else:
      rid = int(rng.integers(10 ** 9))
      if rid in live:
        continue
      row = next_row.pop(0)
      t.insert(rid, row)
      live[rid] = row
  ids, rows = t.items()
  assert dict(zip(ids.tolist(), rows.tolist())) == live
  if live:
    keys = np.asarray(sorted(live), np.int64)
    assert np.array_equal(t.lookup(keys),
                          np.asarray([live[k] for k in sorted(live)],
                                     np.int32))


def test_table_serialization_is_mapping_not_probe_history():
  t = IdTranslationTable(32)
  for i, rid in enumerate([5, 99, 12345, 7 * 10 ** 11]):
    t.insert(rid, i)
  t.remove(99)  # leaves a tombstone in t but not in the serialized form
  ids, rows = t.items()
  t2 = IdTranslationTable(32)
  t2.load_items(ids, rows)
  probe = np.asarray([5, 99, 12345, 7 * 10 ** 11], np.int64)
  assert np.array_equal(t.lookup(probe), t2.lookup(probe))


# ---------------------------------------------------------------------------
# units: count-min sketch
# ---------------------------------------------------------------------------


def test_sketch_never_undercounts_and_bounds_overcount():
  rng = np.random.default_rng(5)
  sk = CountMinSketch(width=1 << 12, depth=4)
  ids = rng.integers(0, 10 ** 12, size=5000).astype(np.int64)
  sk.update(ids)
  uniq, true = np.unique(ids, return_counts=True)
  est = sk.estimate(uniq)
  assert np.all(est >= true), "count-min must NEVER undercount"
  # classic bound: overcount per cell ~ N/width in expectation; min over
  # 4 rows makes 8x that a generous deterministic-seed ceiling
  assert np.max(est - true) <= max(8 * ids.size // (1 << 12), 4)


def test_sketch_exact_for_sparse_streams():
  """A distinct-id stream far below the width collides with nothing at
  these fixed seeds: estimates are exact."""
  sk = CountMinSketch(width=1 << 14, depth=4)
  ids = np.arange(100, dtype=np.int64) * 7919
  for _ in range(3):
    sk.update(ids)
  assert np.array_equal(sk.estimate(ids), np.full(100, 3, np.int64))


def test_sketch_state_roundtrip():
  sk = CountMinSketch(width=1 << 8, depth=2)
  sk.update(np.asarray([1, 2, 2, 3], np.int64))
  sk2 = CountMinSketch(width=1 << 8, depth=2)
  sk2.load_state(sk.state())
  assert np.array_equal(sk2.estimate(np.asarray([2], np.int64)), [2])
  with pytest.raises(ValueError, match="width/depth"):
    CountMinSketch(width=1 << 9, depth=2).load_state(sk.state())


# ---------------------------------------------------------------------------
# planner knobs + builder refusals
# ---------------------------------------------------------------------------


def test_planner_knob_validation():
  with pytest.raises(ValueError, match="clip.*error.*allocate"):
    _plan(2, oov="allocat")
  with pytest.raises(ValueError, match="only apply to"):
    _plan(2, admit_threshold=3)
  with pytest.raises(ValueError, match="only apply to"):
    _plan(2, evict_ttl=10)
  with pytest.raises(ValueError, match="admit_threshold"):
    _plan(2, oov="allocate", admit_threshold=0)
  with pytest.raises(ValueError, match="evict_ttl"):
    _plan(2, oov="allocate", evict_ttl=0)
  with pytest.raises(ValueError, match="exceeds table"):
    _plan(2, oov="allocate", vocab_capacity=10 ** 6)
  p = _plan(2, oov="allocate", vocab_capacity=200, admit_threshold=2,
            evict_ttl=5)
  assert p.table_vocab_capacity(0) == 200
  assert _plan(2, oov="allocate").table_vocab_capacity(0) == VOCAB[0]


def test_per_table_vocab_capacity():
  import dataclasses
  tbls = _tables()
  tbls[0] = dataclasses.replace(tbls[0], vocab_capacity=64)
  with pytest.raises(ValueError, match="static-vocab plan"):
    DistEmbeddingStrategy(tbls, 2, "memory_balanced",
                          dense_row_threshold=0)
  p = DistEmbeddingStrategy(tbls, 2, "memory_balanced",
                            dense_row_threshold=0, oov="allocate",
                            vocab_capacity=200)
  assert p.table_vocab_capacity(0) == 64   # per-table cap wins downward
  assert p.table_vocab_capacity(1) == 200  # plan cap covers the rest
  bad = dataclasses.replace(tbls[0], vocab_capacity=10 ** 7)
  with pytest.raises(ValueError, match="exceeds table"):
    DistEmbeddingStrategy([bad] + tbls[1:], 2, "memory_balanced",
                          dense_row_threshold=0, oov="allocate")
  # the translator honors the refined capacity
  tr = DynVocabTranslator(p, RULE)
  assert tr.tables[0].capacity == 64
  assert tr.recyclers[1].capacity == 200


def test_eval_and_serve_builders_refuse_allocate():
  world = 2
  plan = _plan(world, oov="allocate")
  model = _model(world)
  mesh = create_mesh(world)
  batch0 = _batch(0)
  dense = _dense_params(model, batch0)
  state = shard_params(
      init_sparse_state_direct(plan, RULE, dense, optax.adam(1e-3),
                               jax.random.PRNGKey(1)), mesh)
  with pytest.raises(ValueError, match="not evaluable.*mutate"):
    make_sparse_eval_step(model, plan, RULE, mesh, state, batch0)
  from distributed_embeddings_tpu.serving.engine import make_serve_step
  with pytest.raises(ValueError, match="not servable.*mutate"):
    make_serve_step(model, plan, {}, mesh, state, batch0[:2])
  with pytest.raises(NotImplementedError, match="allocate"):
    make_train_step(lambda p, *b: 0.0, optax.adam(1e-3), mesh,
                    {}, {}, batch0, plan=plan)


def test_tiered_builder_refuses_allocate():
  from distributed_embeddings_tpu.tiering import TieringConfig, TieringPlan
  from distributed_embeddings_tpu.training import make_tiered_train_step
  plan = DistEmbeddingStrategy(_tables([5000, 300]), 4, "memory_balanced",
                               dense_row_threshold=0,
                               host_row_threshold=1000, oov="allocate")
  tplan = TieringPlan(plan, RULE, TieringConfig(staging_grps=64))
  with pytest.raises(NotImplementedError, match="tiered"):
    make_tiered_train_step(None, tplan, bce_loss, optax.adam(1e-3), RULE,
                           None, {}, None)
  with pytest.raises(NotImplementedError, match="host-tier"):
    DynVocabTranslator(plan, RULE)


# ---------------------------------------------------------------------------
# bit-exact parity vs the static-vocab run
# ---------------------------------------------------------------------------


def _paired_losses(world, micro_batches=1, steps=4):
  """Train dynvocab (pre-admitted identity id space) and the static run
  on one stream from identical params; return losses + final fused."""
  batch0 = _batch(100)
  plan_dv = _plan(world, oov="allocate")
  plan_st = _plan(world)
  model, mesh, trainer = _fresh(world, plan_dv, batch0, guard=True,
                                micro_batches=micro_batches)
  # pre-admit the identity mapping: threshold 1 admits on sight, and
  # np.unique + sequential fresh allocation maps id k -> row k
  trainer.translator.translate_batch(
      [np.arange(v, dtype=np.int64) for v in VOCAB])
  dense = _dense_params(model, batch0)
  state_st = shard_params(
      init_sparse_state_direct(plan_st, RULE, dense, optax.adam(1e-3),
                               jax.random.PRNGKey(1)), mesh)
  step_st = make_sparse_train_step(model, plan_st, bce_loss,
                                   optax.adam(1e-3), RULE, mesh, state_st,
                                   batch0, donate=False, guard=True,
                                   micro_batches=micro_batches)
  losses_dv, losses_st = [], []
  for s in range(steps):
    b = _batch(200 + s)
    losses_dv.append(trainer.step(*b))
    sb = shard_batch(b, mesh)
    state_st, loss, _ = step_st(state_st, *sb)
    losses_st.append(float(np.asarray(loss)))
  return losses_dv, losses_st, trainer.state, state_st, trainer


@pytest.mark.parametrize("world", [1, 2, 4])
def test_bit_exact_vs_static(world):
  """Acceptance: a dynvocab run whose ids are all pre-admitted and
  within capacity is BIT-EXACT vs the static-vocab run — losses AND the
  full fused trajectory (tables + optimizer lanes)."""
  losses_dv, losses_st, st_dv, st_st, trainer = _paired_losses(world)
  assert losses_dv == losses_st
  for name in st_st["fused"]:
    assert np.array_equal(np.asarray(st_dv["fused"][name]),
                          np.asarray(st_st["fused"][name])), name
  assert int(np.asarray(st_dv["step"])) == int(np.asarray(st_st["step"]))
  # nothing was denied or evicted on an in-capacity pre-admitted stream
  per = trainer.metrics_summary()["per_class"]
  assert all(v["evictions"] == 0 and v["admit_denied"] == 0
             for v in per.values())


def test_bit_exact_vs_static_micro_batched():
  losses_dv, losses_st, st_dv, st_st, _ = _paired_losses(
      4, micro_batches=2)
  assert losses_dv == losses_st
  for name in st_st["fused"]:
    assert np.array_equal(np.asarray(st_dv["fused"][name]),
                          np.asarray(st_st["fused"][name])), name


def test_unguarded_step_matches_guarded_numerics():
  batch0 = _batch(100)
  plan = _plan(2, oov="allocate")
  _, _, tg = _fresh(2, plan, batch0, guard=True)
  plan2 = _plan(2, oov="allocate")
  _, _, tu = _fresh(2, plan2, batch0, guard=False)
  for s in range(3):
    b = _batch(300 + s)
    assert tg.step(*b) == tu.step(*b)


# ---------------------------------------------------------------------------
# eviction, recycling, zeroed reuse
# ---------------------------------------------------------------------------


def test_eviction_then_reuse_lands_on_zeroed_row():
  """Train a dynamic id, let its TTL expire, and check (a) the freed
  row's lanes — table AND interleaved optimizer state — are zero on
  device in every shard window, (b) a newly admitted id recycles the
  freed row (FIFO), starting from the zeroed state."""
  world = 4
  batch0 = _batch(100)
  plan = _plan(world, oov="allocate", evict_ttl=2)
  _, _, trainer = _fresh(world, plan, batch0, guard=True)
  tr = trainer.translator
  b = batch0[0].shape[0]
  hot_id = 7_000_000_001
  # step 1 maps hot_id ONCE plus a filler set; later steps reuse only
  # the fillers, so no new allocation recycles the expired row before
  # the test inspects it
  fillers = (np.arange(b, dtype=np.int64) % 60) + 1
  cats1 = fillers.copy()
  cats1[0] = hot_id
  trainer.step(batch0[0], [cats1, np.full(b, 42, np.int64)], batch0[2])
  row = int(tr.tables[0].lookup(np.asarray([hot_id]))[0])
  assert row >= 0
  # the trained row is nonzero before eviction
  layouts = trainer.layouts
  def lanes_of(table_row):
    out = []
    for (name, base, rs0, nrows, off, rpp) in tr._recipe[0]:
      if not (rs0 <= table_row < rs0 + nrows):
        continue
      local = table_row - rs0 + off
      lay = layouts[name]
      phys = np.asarray(trainer.state["fused"][name])[base + local // rpp]
      out.append(phys[(local % rpp) * lay.stride:
                      (local % rpp + 1) * lay.stride])
    assert out, "no shard window covers the row"
    return out
  assert any(np.any(w != 0.0) for w in lanes_of(row))
  # steps without hot_id, past the TTL: only the already-mapped fillers
  for s in range(4):
    bb = _batch(400 + s)
    trainer.step(bb[0], [fillers, np.full(b, 42, np.int64)], bb[2])
  assert tr.tables[0].lookup(np.asarray([hot_id]))[0] == -1
  assert row in tr.recyclers[0].freelist
  for w in lanes_of(row):
    assert np.all(w == 0.0), "evicted row's lanes must re-zero in place"
  # FIFO recycling: the oldest freed row is handed out first
  expect = tr.recyclers[0].freelist[0]
  new_id = 8_000_000_008
  trainer.step(batch0[0],
               [np.full(b, new_id, np.int64), np.full(b, 42, np.int64)],
               batch0[2])
  assert int(tr.tables[0].lookup(np.asarray([new_id]))[0]) == expect
  per = trainer.metrics_summary()["per_class"]
  assert sum(v["evictions"] for v in per.values()) > 0


def test_admission_threshold_denies_one_shot_ids():
  world = 2
  batch0 = _batch(100)
  plan = _plan(world, oov="allocate", admit_threshold=3)
  _, _, trainer = _fresh(world, plan, batch0, guard=True)
  tr = trainer.translator
  b = batch0[0].shape[0]
  one_shot = np.arange(b, dtype=np.int64) + 10 ** 10  # b distinct ids
  cats = [one_shot, np.full(b, 1, np.int64)]
  trainer.step(batch0[0], cats, batch0[2])
  assert tr.recyclers[0].occupancy == 0, "one-shot ids must not allocate"
  # the hot singleton in input 1 appears `batch` times per step: admitted
  # on the FIRST step (estimate b >= 3), occupying exactly one row
  assert tr.recyclers[1].occupancy == 1
  per = trainer.metrics_summary()["per_class"]
  assert sum(v["admit_denied"] for v in per.values()) >= b


def test_capacity_cap_denies_and_counts():
  world = 2
  batch0 = _batch(100)
  plan = _plan(world, oov="allocate", vocab_capacity=8)
  _, _, trainer = _fresh(world, plan, batch0, guard=True)
  b = batch0[0].shape[0]
  ids = np.arange(b, dtype=np.int64) + 5 * 10 ** 9
  trainer.step(batch0[0], [ids, ids + 777], batch0[2])
  tr = trainer.translator
  assert tr.recyclers[0].occupancy == 8
  assert tr.recyclers[1].occupancy == 8
  per = trainer.metrics_summary()["per_class"]
  assert sum(v["admit_denied"] for v in per.values()) > 0


# ---------------------------------------------------------------------------
# guard: raw ids leaking past the translator
# ---------------------------------------------------------------------------


def test_untranslated_oov_leak_is_gated_and_raised():
  """Feeding RAW out-of-range ids straight to a guarded allocate step
  (bypassing the translator) must commit NOTHING and raise host-side
  with the leak named."""
  from distributed_embeddings_tpu.resilience import guards
  world = 2
  batch0 = _batch(100)
  plan = _plan(world, oov="allocate")
  model = _model(world)
  mesh = create_mesh(world)
  dense = _dense_params(model, batch0)
  state = shard_params(
      init_sparse_state_direct(plan, RULE, dense, optax.adam(1e-3),
                               jax.random.PRNGKey(1)), mesh)
  step = make_sparse_train_step(model, plan, bce_loss, optax.adam(1e-3),
                                RULE, mesh, state, batch0, donate=False,
                                guard=True)
  b = batch0[0].shape[0]
  bad = (batch0[0], [np.full(b, VOCAB[0] + 50, np.int64),
                     np.zeros(b, np.int64)], batch0[2])
  sb = shard_batch(bad, mesh)
  new_state, _, metrics = step(state, *sb)
  assert sum(int(np.asarray(v)) for v in metrics["oov"].values()) > 0
  assert int(np.asarray(new_state["step"])) == 0, "leak must not commit"
  with pytest.raises(ValueError, match="leaked past the dynvocab"):
    guards.check_oov(plan, metrics["oov"], where="test")


# ---------------------------------------------------------------------------
# checkpoint: the vocab manifest section
# ---------------------------------------------------------------------------


def test_checkpoint_vocab_roundtrip(tmp_path):
  world = 2
  batch0 = _batch(100)
  plan = _plan(world, oov="allocate", admit_threshold=2, evict_ttl=50)
  _, mesh, trainer = _fresh(world, plan, batch0, guard=True)
  for s in range(3):
    trainer.step(*_batch(500 + s))
  path = str(tmp_path / "ckpt")
  checkpoint.save(path, plan, RULE, trainer.state,
                  vocab=trainer.translator)
  manifest = checkpoint.read_manifest(path)
  assert manifest["vocab"]["admit_threshold"] == 2
  assert manifest["vocab"]["evict_ttl"] == 50
  assert set(manifest["vocab"]["tables"]) == {"0", "1"}
  assert checkpoint.verify(path) == []
  # restore into a fresh translator: mapping, sketch, recycler, counters
  tr2 = DynVocabTranslator(plan, RULE)
  state2 = checkpoint.restore(path, plan, RULE, trainer.state, mesh=mesh,
                              vocab=tr2)
  tr = trainer.translator
  for t in tr.dynamic_tables:
    a, b = tr.tables[t].items(), tr2.tables[t].items()
    assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
    assert np.array_equal(tr.sketches[t].state(), tr2.sketches[t].state())
    assert tr.recyclers[t].freelist == tr2.recyclers[t].freelist
    assert np.array_equal(tr.recyclers[t].row_to_id,
                          tr2.recyclers[t].row_to_id)
    assert np.array_equal(tr.totals[t], tr2.totals[t])
  assert tr2.steps == tr.steps
  assert int(np.asarray(state2["step"])) == int(np.asarray(
      trainer.state["step"]))


def test_checkpoint_vocab_mismatches_refuse(tmp_path):
  world = 2
  batch0 = _batch(100)
  plan = _plan(world, oov="allocate", admit_threshold=2)
  _, mesh, trainer = _fresh(world, plan, batch0, guard=True)
  trainer.step(*_batch(1))
  path = str(tmp_path / "ckpt")
  # allocate plan without the translator: refused at save
  with pytest.raises(ValueError, match="no DynVocabTranslator"):
    checkpoint.save(path, plan, RULE, trainer.state)
  checkpoint.save(path, plan, RULE, trainer.state,
                  vocab=trainer.translator)
  # restoring without the translator: refused, names the section
  with pytest.raises(ValueError, match="'vocab'"):
    checkpoint.restore(path, plan, RULE, trainer.state, mesh=mesh)
  # knob mismatch: refused with the knob named
  plan3 = _plan(world, oov="allocate", admit_threshold=5)
  tr3 = DynVocabTranslator(plan3, RULE)
  with pytest.raises(ValueError, match="admit_threshold"):
    checkpoint.restore(path, plan3, RULE, trainer.state, mesh=mesh,
                       vocab=tr3)
  # vocab= on a static plan: refused at save
  plan_st = _plan(world)
  with pytest.raises(ValueError, match="static-vocab plan"):
    checkpoint.save(str(tmp_path / "c2"), plan_st, RULE, trainer.state,
                    vocab=trainer.translator)


def test_vocab_state_survives_elastic_reshard(tmp_path):
  """The id space is table-id-keyed, so a world resize restores it
  verbatim while the rank blocks re-shard."""
  batch0 = _batch(100)
  plan4 = _plan(4, oov="allocate")
  _, mesh4, trainer = _fresh(4, plan4, batch0, guard=True)
  for s in range(2):
    trainer.step(*_batch(600 + s))
  path = str(tmp_path / "ckpt")
  checkpoint.save(path, plan4, RULE, trainer.state,
                  vocab=trainer.translator)
  plan2 = _plan(2, oov="allocate")
  model2 = _model(2)
  mesh2 = create_mesh(2)
  dense2 = _dense_params(model2, batch0)
  like2 = shard_params(
      init_sparse_state_direct(plan2, RULE, dense2, optax.adam(1e-3),
                               jax.random.PRNGKey(9)), mesh2)
  tr2 = DynVocabTranslator(plan2, RULE)
  checkpoint.restore(path, plan2, RULE, like2, mesh=mesh2, vocab=tr2)
  tr = trainer.translator
  for t in tr.dynamic_tables:
    a, b = tr.tables[t].items(), tr2.tables[t].items()
    assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
    assert np.array_equal(tr.totals[t], tr2.totals[t])


# ---------------------------------------------------------------------------
# resilience: auto-resume restores the id space exactly
# ---------------------------------------------------------------------------


def _resilient(world, batch0, root, ttl=None):
  from distributed_embeddings_tpu.resilience import ResilientTrainer
  kw = {} if ttl is None else {"evict_ttl": ttl}
  plan = _plan(world, oov="allocate", admit_threshold=1, **kw)
  _, mesh, dvt = _fresh(world, plan, batch0, guard=True)
  return plan, ResilientTrainer(
      None, None, plan, RULE, root, mesh=mesh, snapshot_every=2,
      resume=True, dynvocab=dvt)


def test_resilient_resume_restores_id_space_and_trajectory(tmp_path):
  """Kill-and-resume contract: an interrupted dynvocab run, resumed by a
  FRESH trainer from its snapshots, continues to the same losses, the
  same id space, and the same lifecycle counters as an uninterrupted
  reference (allocs/evictions never double-counted)."""
  world = 2
  batch0 = _batch(100)
  stream = [_batch(700 + s) for s in range(6)]
  # uninterrupted reference
  _, ref = _resilient(world, batch0, str(tmp_path / "ref"), ttl=3)
  ref_losses = ref.run(stream)
  # interrupted: consume 3 batches, then "die" (drop the trainer) and
  # resume a fresh one from the snapshots
  _, t1 = _resilient(world, batch0, str(tmp_path / "run"), ttl=3)
  first = [t1.step(*b) for b in stream[:3]]
  assert t1.consumed == 3
  _, t2 = _resilient(world, batch0, str(tmp_path / "run"), ttl=3)
  assert t2.resumed_from is not None
  resumed_at = t2.consumed  # stepping advances it — capture the resume point
  rest = [t2.step(*b) for b in stream[resumed_at:]]
  stitched = first[:resumed_at] + rest
  assert stitched == ref_losses
  # id spaces agree exactly
  tr_ref = ref.dynvocab.translator
  tr_res = t2.dynvocab.translator
  for t in tr_ref.dynamic_tables:
    a, b = tr_ref.tables[t].items(), tr_res.tables[t].items()
    assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
    assert np.array_equal(tr_ref.totals[t], tr_res.totals[t]), \
        "cumulative lifecycle counters must survive the restart exactly"
    assert tr_ref.recyclers[t].freelist == tr_res.recyclers[t].freelist
  for name in ref.state["fused"]:
    assert np.array_equal(np.asarray(ref.state["fused"][name]),
                          np.asarray(t2.state["fused"][name])), name


def test_resilient_dynvocab_validation(tmp_path):
  from distributed_embeddings_tpu.resilience import ResilientTrainer
  world = 2
  batch0 = _batch(100)
  plan = _plan(world, oov="allocate")
  _, mesh, dvt_unguarded = _fresh(world, plan, batch0, guard=False)
  with pytest.raises(ValueError, match="guard=True"):
    ResilientTrainer(None, None, plan, RULE, str(tmp_path / "a"),
                     mesh=mesh, dynvocab=dvt_unguarded)
  plan2 = _plan(world, oov="allocate")
  _, mesh2, dvt = _fresh(world, plan2, batch0, guard=True)
  with pytest.raises(NotImplementedError, match="async"):
    ResilientTrainer(None, None, plan2, RULE, str(tmp_path / "b"),
                     mesh=mesh2, dynvocab=dvt, async_snapshots=True)
