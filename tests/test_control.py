"""Control-plane tests (`distributed_embeddings_tpu/control/` + hedging).

The contracts under test:

- **decisions are deterministic and replayable**: every loop's decision
  is a pure function of its logged ``inputs`` + config — feeding the
  same snapshot sequence through a fresh loop reproduces the logged
  actions exactly (``decision_key`` strips the two stamp fields; the
  rest must match byte-for-byte);
- **the autoscaler never flaps**: consecutive-streak hysteresis plus a
  post-action cooldown — a single noisy tick moves nothing, and a
  scale action is followed by a hold window no matter what the signals
  do;
- **the compactor daemon never folds past a live subscriber**: the
  ``through_seq`` it picks is clamped to the slowest LIVE heartbeat,
  expired heartbeats drop out of the floor, and the fold only happens
  when the backlog is worth it;
- **admission tightens before the SLO breaks and re-admits after**:
  deadline-class budgets map to ``set_admission`` moves with a
  hysteresis dead-band, never below the batch size, never above the
  operator's configured bound;
- **hedged gathers are bit-exact and exactly-once counted**: a slow
  replica's request is duplicated, the first answer wins (f32 bitwise
  vs the single-process engine), ``fleet/hedges{,_won,_wasted}`` count
  each logical gather once (retries inside an attempt do not
  double-count), and a rank whose every replica is dead still FAILS
  the request.
"""

import json
import math
import os

import numpy as np
import pytest

from distributed_embeddings_tpu.control import (
    AdmissionConfig,
    AutoscalerConfig,
    CompactorConfig,
    CompactorDaemon,
    ControlPolicy,
    ControlSnapshot,
    CounterRate,
    DecisionLog,
    FleetAutoscaler,
    decision_key,
    replay_decisions,
)
from distributed_embeddings_tpu.telemetry import MetricsRegistry


# ---------------------------------------------------------------------------
# DecisionLog: durable, replayable, counted
# ---------------------------------------------------------------------------


def test_decision_log_roundtrip_and_counters(tmp_path):
  reg = MetricsRegistry()
  path = os.path.join(str(tmp_path), "decisions.jsonl")
  with DecisionLog(path, telemetry=reg) as log:
    r1 = log.record("autoscaler", 1, "hold", "in_band",
                    inputs={"qps": 10.0}, target_replicas=2)
    r2 = log.record("compactor", 1, "fold", "backlog",
                    inputs={"run_end": 7}, through_seq=5)
  assert r1["log_seq"] == 0 and r2["log_seq"] == 1
  assert reg.counter("control/decisions").value == 2
  assert reg.counter("control/decisions/autoscaler").value == 1
  assert reg.counter("control/decisions/compactor").value == 1
  back = replay_decisions(path)
  assert [decision_key(r) for r in back] == [decision_key(r1),
                                             decision_key(r2)]
  # every line is self-contained JSON (the fsync-per-line contract)
  with open(path) as f:
    for line in f:
      json.loads(line)


def test_decision_key_strips_only_the_stamps():
  rec = {"source": "x", "tick": 1, "action": "hold", "reason": "r",
         "inputs": {"a": 1}, "wall": 123.4, "log_seq": 9, "extra": "kept"}
  key = decision_key(rec)
  assert "wall" not in key and "log_seq" not in key
  assert key["extra"] == "kept" and key["inputs"] == {"a": 1}


# ---------------------------------------------------------------------------
# signals: rates and snapshots
# ---------------------------------------------------------------------------


def test_counter_rate_samples():
  r = CounterRate()
  assert r.sample(100, 1.0) == 0.0  # first sample: no interval yet
  assert r.sample(150, 2.0) == pytest.approx(50.0)
  assert r.sample(150, 2.0) == 0.0  # non-advancing clock: no rate
  assert r.sample(140, 3.0) == 0.0  # counter reset: clamped, not negative
  assert r.sample(200, 4.0) == pytest.approx(60.0)


def test_snapshot_inputs_are_json_safe():
  snap = ControlSnapshot(tick=3, qps=12.5, replicas=2)
  inputs = snap.to_inputs()
  assert inputs["p99_s"] is None and inputs["p999_s"] is None  # NaN -> None
  assert inputs["tick"] == 3 and inputs["qps"] == 12.5
  json.dumps(inputs)  # the record must be JSON-serializable


# ---------------------------------------------------------------------------
# autoscaler: hysteresis, cooldown, determinism
# ---------------------------------------------------------------------------

ASCFG = AutoscalerConfig(qps_high_per_replica=100.0,
                         qps_low_per_replica=30.0,
                         min_replicas=1, max_replicas=3,
                         up_after=2, down_after=3, cooldown_ticks=2)


def _snaps(qps_seq, replicas_seq=None):
  out = []
  r = 1
  for i, q in enumerate(qps_seq):
    if replicas_seq is not None:
      r = replicas_seq[i]
    out.append(ControlSnapshot(tick=i + 1, qps=q, replicas=r))
  return out


def test_autoscaler_requires_consecutive_breaches():
  a = FleetAutoscaler(ASCFG)
  # one high tick, one in-band, one high: never two CONSECUTIVE -> hold
  acts = [a.decide(s)["action"]
          for s in _snaps([150.0, 50.0, 150.0, 50.0])]
  assert acts == ["hold"] * 4


def test_autoscaler_scales_up_then_cools_down():
  actuations = []
  a = FleetAutoscaler(ASCFG, actuate=lambda t, rec: actuations.append(t))
  recs = [a.tick(s) for s in _snaps(
      [150.0, 150.0, 150.0, 150.0, 150.0], [1, 1, 2, 2, 2])]
  acts = [(r["action"], r["reason"]) for r in recs]
  # up after 2 consecutive highs, then cooldown_ticks=2 holds even
  # though qps/replica (75) is back in band — then in_band
  assert acts[0] == ("hold", "in_band")
  assert acts[1] == ("scale_up", "qps_high")
  assert acts[2] == ("hold", "cooldown")
  assert acts[3] == ("hold", "cooldown")
  assert acts[4] == ("hold", "in_band")
  assert actuations == [2]


def test_autoscaler_scale_down_is_slower_and_bounded():
  a = FleetAutoscaler(ASCFG)
  # 3 consecutive lows at 2 replicas -> down; at min it refuses by name
  recs = [a.decide(s) for s in _snaps(
      [10.0, 10.0, 10.0, 10.0, 10.0, 10.0, 10.0, 10.0],
      [2, 2, 2, 1, 1, 1, 1, 1])]
  acts = [(r["action"], r["reason"]) for r in recs]
  assert acts[2] == ("scale_down", "qps_low")
  assert acts[3] == ("hold", "cooldown")
  assert acts[4] == ("hold", "cooldown")
  # streak kept advancing through cooldown: first eligible tick decides
  assert acts[5] == ("hold", "at_min_replicas")
  assert recs[2]["target_replicas"] == 1


def test_autoscaler_staleness_triggers_and_names_itself():
  cfg = AutoscalerConfig(qps_high_per_replica=100.0,
                         qps_low_per_replica=30.0,
                         staleness_high_s=5.0, up_after=1,
                         cooldown_ticks=0, max_replicas=3)
  a = FleetAutoscaler(cfg)
  rec = a.decide(ControlSnapshot(tick=1, qps=50.0, replicas=1,
                                 staleness_s=30.0))
  assert (rec["action"], rec["reason"]) == ("scale_up", "staleness_high")
  # a stale fleet never scales DOWN, however low the qps
  a2 = FleetAutoscaler(dataclasses_replace(cfg, down_after=1))
  rec = a2.decide(ControlSnapshot(tick=1, qps=0.0, replicas=3,
                                  staleness_s=30.0))
  assert rec["action"] != "scale_down"


def dataclasses_replace(cfg, **kw):
  import dataclasses
  return dataclasses.replace(cfg, **kw)


def test_autoscaler_at_max_holds_by_name():
  cfg = dataclasses_replace(ASCFG, up_after=1, cooldown_ticks=0)
  a = FleetAutoscaler(cfg)
  rec = a.decide(ControlSnapshot(tick=1, qps=900.0, replicas=3))
  assert (rec["action"], rec["reason"]) == ("hold", "at_max_replicas")


def test_autoscaler_config_refusals():
  with pytest.raises(ValueError, match="inverted band"):
    AutoscalerConfig(qps_high_per_replica=10.0, qps_low_per_replica=20.0)
  with pytest.raises(ValueError, match="min_replicas"):
    AutoscalerConfig(qps_high_per_replica=10.0, qps_low_per_replica=1.0,
                     min_replicas=5, max_replicas=2)
  with pytest.raises(ValueError, match="up_after"):
    AutoscalerConfig(qps_high_per_replica=10.0, qps_low_per_replica=1.0,
                     up_after=0)


def test_autoscaler_decisions_replay_deterministically(tmp_path):
  """The pinned replay contract: the same snapshots through a fresh
  loop reproduce the logged decisions exactly (minus the stamps)."""
  snaps = _snaps([150.0, 150.0, 150.0, 10.0, 10.0, 10.0, 10.0, 10.0],
                 [1, 1, 2, 2, 2, 2, 2, 2])
  path = os.path.join(str(tmp_path), "d.jsonl")
  with DecisionLog(path, telemetry=MetricsRegistry()) as log:
    a = FleetAutoscaler(ASCFG, decisions=log)
    for s in snaps:
      a.decide(s)
  logged = [decision_key(r) for r in replay_decisions(path)]
  fresh = FleetAutoscaler(ASCFG, decisions=DecisionLog(
      telemetry=MetricsRegistry()))
  replayed = [decision_key(fresh.decide(s)) for s in snaps]
  assert replayed == logged


def test_autoscaler_actuate_failure_is_logged_and_raised():
  log = DecisionLog(telemetry=MetricsRegistry())

  def boom(target, rec):
    raise RuntimeError("transport down")

  a = FleetAutoscaler(dataclasses_replace(ASCFG, up_after=1),
                      actuate=boom, decisions=log)
  with pytest.raises(RuntimeError, match="transport down"):
    a.tick(ControlSnapshot(tick=1, qps=900.0, replicas=1))
  acts = [r["action"] for r in log.records]
  assert acts == ["scale_up", "actuate_failed"]


# ---------------------------------------------------------------------------
# compactor daemon: lag-aware through_seq, worth-it threshold
# ---------------------------------------------------------------------------

CDCFG = CompactorConfig(min_deltas=3, heartbeat_ttl_s=30.0)


def _daemon(tmp_path, **kw):
  return CompactorDaemon(os.path.join(str(tmp_path), "pub"),
                         config=kw.pop("config", CDCFG),
                         decisions=DecisionLog(telemetry=MetricsRegistry()),
                         telemetry=MetricsRegistry(), **kw)


def test_compactor_decide_clamps_to_live_floor(tmp_path):
  d = _daemon(tmp_path)
  # backlog of 6 but the slowest live subscriber sits at seq 4
  rec = d.decide({"anchor_seq": 0, "run_end": 6, "live_floor": 4,
                  "live_subscribers": 2, "expired_subscribers": 0}, 1)
  assert rec["action"] == "fold" and rec["through_seq"] == 4
  # the laggard pins the chain: floor below the worth-it threshold
  rec = d.decide({"anchor_seq": 0, "run_end": 6, "live_floor": 1,
                  "live_subscribers": 1, "expired_subscribers": 0}, 2)
  assert (rec["action"], rec["reason"]) == ("hold", "subscriber_lag")
  # no live subscriber at all: the full backlog folds
  rec = d.decide({"anchor_seq": 0, "run_end": 6, "live_floor": None,
                  "live_subscribers": 0, "expired_subscribers": 1}, 3)
  assert rec["action"] == "fold" and rec["through_seq"] == 6
  # thin backlog: not worth a full-image rewrite
  rec = d.decide({"anchor_seq": 4, "run_end": 6, "live_floor": None,
                  "live_subscribers": 0, "expired_subscribers": 0}, 4)
  assert (rec["action"], rec["reason"]) == ("hold", "backlog_below_min")
  # no base yet: nothing to fold onto
  rec = d.decide({"anchor_seq": None, "run_end": None, "live_floor": None,
                  "live_subscribers": 0, "expired_subscribers": 0}, 5)
  assert (rec["action"], rec["reason"]) == ("hold", "no_base")


def test_compactor_fold_priority_is_deterministic(tmp_path):
  d = _daemon(tmp_path, class_priority={"cold": 0.5, "hot": 3.0,
                                        "warm": 1.0, "also_warm": 1.0})
  rec = d.decide({"anchor_seq": 0, "run_end": 5, "live_floor": None,
                  "live_subscribers": 0, "expired_subscribers": 0}, 1)
  # hot first; ties broken by name — the order is a pure function
  assert rec["fold_priority"] == ["hot", "also_warm", "warm", "cold"]


def test_compactor_observe_on_empty_dir(tmp_path):
  d = _daemon(tmp_path)
  state = d.observe()
  assert state["anchor_seq"] is None
  rec = d.tick()
  assert (rec["action"], rec["reason"]) == ("hold", "no_base")


def test_compactor_config_refusal():
  with pytest.raises(ValueError, match="min_deltas"):
    CompactorConfig(min_deltas=0)


@pytest.mark.slow
def test_compactor_daemon_folds_real_chain(tmp_path):
  """Integration: observe/decide/actuate over an actual delta chain —
  the fold respects a live heartbeat and the result matches what the
  manual compactor reports."""
  from test_streaming import _chain_of
  from distributed_embeddings_tpu.streaming import (
      published_delta_seqs,
      write_heartbeat,
  )
  plan, rule, mesh, state, publisher, sub, rng, b = _chain_of(
      tmp_path, 4)
  write_heartbeat(sub.path, "live_sub", 3)
  d = CompactorDaemon(sub.path, config=CompactorConfig(min_deltas=2),
                      decisions=DecisionLog(telemetry=MetricsRegistry()),
                      telemetry=MetricsRegistry())
  st = d.observe()
  assert st["run_end"] == 4 and st["live_floor"] == 3
  rec = d.tick()
  assert rec["action"] == "fold" and rec["through_seq"] == 3
  assert rec["result"]["through_seq"] == 3
  # GC keeps only what the live subscriber still needs (it has applied
  # through 3, so only the un-folded tail survives)
  assert published_delta_seqs(sub.path) == [4]
  # the very next tick holds: backlog is now thin
  rec = d.tick()
  assert rec["action"] == "hold"


# ---------------------------------------------------------------------------
# admission: budgets -> shed thresholds
# ---------------------------------------------------------------------------


class _FakeBatcher:
  """The admission surface only: queue_rows/max_batch + set_admission
  (the real MicroBatcher's refusal semantics included)."""

  def __init__(self, max_batch=8, queue_rows=64):
    self.max_batch = max_batch
    self.queue_rows = queue_rows
    self.calls = []

  def set_admission(self, queue_rows=None, max_delay_s=None):
    if queue_rows is not None:
      if queue_rows < self.max_batch:
        raise ValueError("queue_rows below max_batch")
      self.queue_rows = int(queue_rows)
      self.calls.append(int(queue_rows))


def _policy(batcher=None, budgets=None, **cfg_kw):
  b = batcher if batcher is not None else _FakeBatcher()
  cfg = AdmissionConfig(**cfg_kw) if cfg_kw else AdmissionConfig()
  return ControlPolicy(b, budgets if budgets is not None
                       else {"realtime": 0.010}, config=cfg,
                       decisions=DecisionLog(telemetry=MetricsRegistry())), b


def test_admission_tightens_under_breach_and_relaxes_after():
  pol, b = _policy()
  # sustained p99 of 50ms against a 10ms budget: tighten
  for _ in range(30):
    pol.observe_latency(0.050)
  rec = pol.tick()
  assert rec["action"] == "tighten" and b.queue_rows < 64
  tightened = b.queue_rows
  # recovery: fast requests dominate a fresh window -> relax back up
  for _ in range(8):
    pol._window.rotate()  # age the breach out of the recent window
  for _ in range(30):
    pol.observe_latency(0.001)
  rec = pol.tick()
  assert rec["action"] == "relax" and b.queue_rows > tightened
  # relax never exceeds the operator's configured bound
  for _ in range(20):
    for _ in range(30):
      pol.observe_latency(0.001)
    pol.tick()
  assert b.queue_rows == 64
  last = pol.decisions.records[-1]
  assert (last["action"], last["reason"]) == ("hold", "at_baseline")


def test_admission_floor_is_the_batch_size():
  pol, b = _policy(batcher=_FakeBatcher(max_batch=8, queue_rows=16))
  for tick in range(10):
    for _ in range(30):
      pol.observe_latency(0.050)
    pol.tick()
  assert b.queue_rows == 8  # never below max_batch, however bad the p99
  last = pol.decisions.records[-1]
  assert (last["action"], last["reason"]) == ("hold", "at_floor")


def test_admission_effective_budget_is_the_tightest_class():
  pol, _ = _policy(budgets={"bulk": 0.5, "realtime": 0.010})
  assert pol.effective_budget_s == 0.010


def test_admission_holds_without_signal():
  pol, b = _policy()
  rec = pol.tick()  # no observations at all
  assert (rec["action"], rec["reason"]) == ("hold", "insufficient_samples")
  pol2, b2 = _policy(budgets={})
  for _ in range(30):
    pol2.observe_latency(0.050)
  rec = pol2.tick()  # no budgets: a declared no-op, never a surprise
  assert (rec["action"], rec["reason"]) == ("hold", "no_budgets")
  assert b2.calls == []


def test_admission_in_band_dead_zone_does_not_flap():
  pol, b = _policy()
  # p99 ~ 8ms against a 10ms budget: inside [relax*b, slack*b) -> hold
  for _ in range(30):
    pol.observe_latency(0.008)
  rec = pol.tick()
  assert (rec["action"], rec["reason"]) == ("hold", "in_band")
  assert b.calls == []


def test_admission_config_refusals():
  with pytest.raises(ValueError, match="dead-band"):
    AdmissionConfig(slack=0.5, relax=0.9)
  with pytest.raises(ValueError, match="step"):
    AdmissionConfig(step=1.5)
  with pytest.raises(ValueError, match="budget"):
    ControlPolicy(_FakeBatcher(), {"rt": -1.0})


def test_admission_decisions_replay(tmp_path):
  path = os.path.join(str(tmp_path), "adm.jsonl")
  seq = [(0.050, 30), (0.050, 30), (0.001, 30), (0.008, 30)]
  with DecisionLog(path, telemetry=MetricsRegistry()) as log:
    pol = ControlPolicy(_FakeBatcher(), {"rt": 0.010},
                        decisions=log)
    for i, (p99, n) in enumerate(seq):
      pol.decide(p99, n, i + 1, pol.batcher.queue_rows)
      if pol.decisions.records[-1]["action"] in ("tighten", "relax"):
        pol.batcher.queue_rows = pol.decisions.records[-1]["target_rows"]
  logged = [decision_key(r) for r in replay_decisions(path)]
  fresh = ControlPolicy(_FakeBatcher(), {"rt": 0.010},
                        decisions=DecisionLog(telemetry=MetricsRegistry()))
  replayed = []
  for i, (p99, n) in enumerate(seq):
    rec = fresh.decide(p99, n, i + 1, fresh.batcher.queue_rows)
    if rec["action"] in ("tighten", "relax"):
      fresh.batcher.queue_rows = rec["target_rows"]
    replayed.append(decision_key(rec))
  assert replayed == logged
