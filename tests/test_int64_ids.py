"""int64 id routing for >int32 tables (reference registers Tindices in
{int32, int64}, `embedding_lookup_ops.cc:24-88`).

Global ids above 2^31 only exist for row-sliced tables: the engine keeps
int64 inputs wide through the routing arithmetic and narrows to int32
after the row-slice window subtraction localizes them
(`lookup_engine._normalize_input` / `_build_routing`). The planner
rejects >int32 tables unless row slicing is enabled.

Needs x64 (int64 arrays do not exist otherwise); scoped via the
compat.enable_x64 context (jax.enable_x64 was removed; the supported
spelling is jax.experimental.enable_x64) so the rest of the suite keeps
default dtypes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_embeddings_tpu.compat import enable_x64
from distributed_embeddings_tpu.layers import DistEmbeddingStrategy, TableConfig
from distributed_embeddings_tpu.parallel.lookup_engine import (
    DistributedLookup,
    _normalize_input,
    padded_rows,
)

BIG = 2_200_000_000  # > 2^31 - 1


def test_planner_rejects_big_table_without_row_slice():
  with pytest.raises(ValueError, match="int64 routing path"):
    DistEmbeddingStrategy([TableConfig(BIG, 8)], 16, "basic")


def test_planner_accepts_big_table_with_row_slice():
  plan = DistEmbeddingStrategy([TableConfig(BIG, 8)], 16, "basic",
                               row_slice_threshold=1)
  shards = [sh for rank in plan.rank_shards for sh in rank]
  assert all(sh.row_sliced for sh in shards)
  assert sum(sh.input_dim for sh in shards) >= BIG
  # every shard's LOCAL id window must fit int32
  for sh in shards:
    assert sh.input_dim <= 2 ** 31 - 1


def test_int64_routing_localizes_to_int32():
  plan = DistEmbeddingStrategy([TableConfig(BIG, 8)], 16, "basic",
                               row_slice_threshold=1)
  engine = DistributedLookup(plan)
  key = plan.class_keys[0]
  (bucket,) = engine._buckets(key, lambda i: 1)
  sentinel = padded_rows(plan, key)

  with enable_x64(True):
    ids = jnp.asarray(
        np.array([0, 7, BIG - 1, 2_000_000_123, -1], np.int64))
    assert _normalize_input(ids).dtype == jnp.int64
    routed = engine._build_routing(key, bucket, [ids[:, None]])
    assert routed.dtype == jnp.int32

  routed = np.asarray(routed)  # [world, n_b, B]
  world = plan.world_size

  # reconstruct each id's serving shard and check the local id round-trips
  for col, gid in enumerate([0, 7, BIG - 1, 2_000_000_123]):
    hits = []
    for rank in range(world):
      idxs = bucket.slot_idx_per_rank[rank]
      for k, idx in enumerate(idxs):
        slot = plan.classes[key].slots_per_rank[rank][idx]
        local = routed[rank, k, col]
        if local != sentinel:
          sh = slot.shard
          hits.append(int(local) - slot.row_offset + sh.row_start)
    # exactly one shard serves the id, and the global id reconstructs
    assert hits == [gid], (gid, hits)

  # PAD (-1) routes to the sentinel everywhere
  for rank in range(world):
    for k in range(len(bucket.slot_idx_per_rank[rank])):
      assert routed[rank, k, 4] == sentinel
