"""Layer-level tests, mirroring reference `tests/embedding_test.py` coverage:
shape/semantics for 1D/2D/3D x {None,sum,mean}, ragged, sparse, grad-through-
optimizer equivalence vs a plain gather layer, ConcatOneHotEmbedding smoke."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_embeddings_tpu.layers import (
    ConcatOneHotEmbedding,
    Embedding,
    TableConfig,
)
from distributed_embeddings_tpu.ops import RaggedIds, SparseIds


def _init(layer, sample):
  return layer.init(jax.random.PRNGKey(0), sample)


@pytest.mark.parametrize("combiner", [None, "sum", "mean"])
@pytest.mark.parametrize("shape", [(7,), (4, 3), (2, 3, 4)])
def test_shapes(shape, combiner):
  if combiner is not None and len(shape) == 1:
    return  # covered by test_1d_with_combiner_raises
  layer = Embedding(input_dim=20, output_dim=5, combiner=combiner)
  ids = jnp.asarray(np.random.default_rng(0).integers(0, 20, shape))
  params = _init(layer, ids)
  out = layer.apply(params, ids)
  if combiner is None:
    expected = shape + (5,) if len(shape) > 1 else (shape[0], 5)
  else:
    expected = shape[:-1] + (5,)
  assert out.shape == expected


def test_1d_no_combiner_gives_2d_output():
  layer = Embedding(input_dim=10, output_dim=3)
  ids = jnp.asarray([1, 2, 3])
  params = _init(layer, ids)
  out = layer.apply(params, ids)
  assert out.shape == (3, 3)


def test_1d_with_combiner_raises():
  layer = Embedding(input_dim=10, output_dim=3, combiner="sum")
  ids = jnp.asarray([1, 2, 3])
  with pytest.raises(ValueError):
    _init(layer, ids)


def test_semantics_vs_manual_gather():
  rng = np.random.default_rng(1)
  layer = Embedding(input_dim=30, output_dim=4, combiner="mean")
  ids = jnp.asarray(rng.integers(0, 30, (6, 5)))
  params = _init(layer, ids)
  table = params["params"]["embeddings"]
  out = layer.apply(params, ids)
  np.testing.assert_allclose(
      out, np.asarray(table)[np.asarray(ids)].mean(1), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_ragged_input(combiner):
  layer = Embedding(input_dim=25, output_dim=4, combiner=combiner)
  ids = RaggedIds(
      jnp.asarray([1, 2, 3, 4, 5, 6], jnp.int32),
      jnp.asarray([0, 2, 3, 6], jnp.int32))
  params = _init(layer, ids)
  out = layer.apply(params, ids)
  assert out.shape == (3, 4)
  table = np.asarray(params["params"]["embeddings"])
  expect0 = table[[1, 2]].sum(0) if combiner == "sum" else table[[1, 2]].mean(0)
  np.testing.assert_allclose(out[0], expect0, rtol=1e-5)


def test_sparse_input():
  layer = Embedding(input_dim=25, output_dim=4, combiner="sum")
  sp = SparseIds(
      jnp.asarray([[0, 0], [0, 1], [2, 0]], jnp.int32),
      jnp.asarray([5, 6, 7], jnp.int32), (3, 2))
  params = _init(layer, sp)
  out = layer.apply(params, sp)
  table = np.asarray(params["params"]["embeddings"])
  np.testing.assert_allclose(out[0], table[5] + table[6], rtol=1e-5)
  np.testing.assert_allclose(out[1], 0.0)
  np.testing.assert_allclose(out[2], table[7], rtol=1e-5)


def test_training_equivalence_vs_plain_gather():
  """Fused layer and a plain take+sum train identically under adagrad.

  Mirrors reference `tests/embedding_test.py:134-181` (grad-through-optimizer
  equivalence vs `tf.keras.layers.Embedding` with Adagrad)."""
  rng = np.random.default_rng(2)
  vocab, width, batch, hot, steps = 40, 8, 16, 3, 4
  init_table = jnp.asarray(rng.standard_normal((vocab, width)), jnp.float32)
  layer = Embedding(input_dim=vocab, output_dim=width, combiner="sum")

  def loss_fused(table, ids):
    return jnp.sum(layer.lookup(table, ids) ** 2)

  def loss_plain(table, ids):
    return jnp.sum(jnp.sum(jnp.take(table, ids, axis=0), axis=1) ** 2)

  opt = optax.adagrad(0.1)

  def train(loss_fn):
    table = init_table
    state = opt.init(table)
    for step in range(steps):
      ids = jnp.asarray(
          np.random.default_rng(step).integers(0, vocab, (batch, hot)))
      g = jax.grad(loss_fn)(table, ids)
      updates, state = opt.update(g, state)
      table = optax.apply_updates(table, updates)
    return table

  np.testing.assert_allclose(
      train(loss_fused), train(loss_plain), rtol=1e-4, atol=1e-5)


def test_concat_one_hot_embedding():
  layer = ConcatOneHotEmbedding(feature_sizes=(4, 6, 3), embedding_width=5)
  ids = jnp.asarray([[0, 1, 2], [3, 5, 0]], jnp.int32)
  params = _init(layer, ids)
  out = layer.apply(params, ids)
  assert out.shape == (2, 3, 5)
  table = np.asarray(params["params"]["embeddings"])
  np.testing.assert_allclose(out[0, 1], table[4 + 1], rtol=1e-6)
  np.testing.assert_allclose(out[1, 2], table[4 + 6 + 0], rtol=1e-6)


def test_bad_dims_raise():
  with pytest.raises(ValueError):
    Embedding(input_dim=0, output_dim=5)
  with pytest.raises(ValueError):
    Embedding(input_dim=5, output_dim=-1)


def test_table_config_roundtrip():
  layer = Embedding(input_dim=12, output_dim=6, combiner="mean")
  cfg = TableConfig.from_layer(layer)
  assert cfg.input_dim == 12 and cfg.output_dim == 6 and cfg.combiner == "mean"
  layer2 = cfg.to_layer()
  assert layer2.input_dim == 12 and layer2.combiner == "mean"
  cfg2 = Embedding.from_config(
      {"input_dim": 3, "output_dim": 2, "mask_zero": False, "input_length": 5})
  assert cfg2.input_dim == 3
