"""Host-device overlap scheduler tests (`distributed_embeddings_tpu/pipeline.py`).

The contract under test: running batch k+1's host pass (tiered
classify + cold-row gather, dynvocab translation) on the pipeline
worker while step k executes on device is a SCHEDULING change, never a
numerics change —

- a tiered overlapped run is bit-exact vs the serial loop it shadows:
  losses, fused device state, host images, and the tier counters — with
  the guard on, across guard-skipped (NaN) steps, and across re-rank
  boundaries (where overlap is deferred like the serial loop defers its
  look-ahead classify);
- a dynvocab overlapped run is bit-exact vs serial — losses, fused
  state, AND the translator's id space (the worker mutates it in batch
  order, exactly the serial sequence) — across worlds and micro-batch
  accumulation;
- `overlap_host=False` (the default) never calls into pipeline.py at
  all: the serial paths are a true no-op, proven by poisoning the
  schedulers and running serially anyway;
- a worker-job failure FAILS THE STEP: the exception re-raises out of
  ``run`` on the main thread — there is no silent fall-back to the
  serial path;
- under the ResilientTrainer, the overlapped run snapshots/accounts
  identically to serial, an async snapshot of the live tiered store
  goes through the copy-on-snapshot view and restores to the same
  trajectory, and an injected kill mid-overlap (crash during the
  checkpoint write while a worker job is in flight) auto-resumes to a
  bit-exact tail.
"""

import os

import jax
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu import pipeline
from distributed_embeddings_tpu import telemetry
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    get_weights,
    set_weights,
)
from distributed_embeddings_tpu.models import DLRM, bce_loss
from distributed_embeddings_tpu.ops.packed_table import sparse_rule
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.pipeline import HostWorker
from distributed_embeddings_tpu.resilience import (
    FaultInjector,
    InjectedCrash,
    durable,
    faultinject,
)
from distributed_embeddings_tpu.resilience.trainer import ResilientTrainer
from distributed_embeddings_tpu.tiering import (
    HostTierStore,
    TieredTrainer,
    TieringConfig,
    TieringPlan,
    init_tiered_state_from_params,
)
from distributed_embeddings_tpu.training import shard_params

import test_dynvocab as tdv
import test_tiering as tt


# ---------------------------------------------------------------------------
# HostWorker unit behavior
# ---------------------------------------------------------------------------


def test_worker_runs_jobs_in_submission_order():
  seen = []
  with HostWorker("t") as w:
    jobs = [w.submit(lambda i=i: (seen.append(i), i * i)[1], label="j")
            for i in range(16)]
    results = [w.result(j)[0] for j in jobs]
  assert seen == list(range(16))  # one thread, FIFO — never reordered
  assert results == [i * i for i in range(16)]
  assert all(w.result(j)[1] >= 0.0 for j in jobs)


def test_worker_reraises_job_error_and_survives():
  def boom():
    raise ValueError("job exploded")
  with HostWorker("t") as w:
    bad = w.submit(boom, label="j")
    ok = w.submit(lambda: 7, label="j")
    with pytest.raises(ValueError, match="job exploded"):
      w.result(bad)
    # one failed job does not poison the worker: later jobs still run
    assert w.result(ok)[0] == 7


def test_worker_submit_after_close_refuses():
  w = HostWorker("t")
  w.close()
  w.close()  # idempotent
  with pytest.raises(RuntimeError, match="closed"):
    w.submit(lambda: None)


def test_worker_close_drains_discarded_jobs():
  # a prepared-ahead job whose result is deliberately dropped (SIGTERM
  # drain) must not wedge or raise at close
  done = []
  w = HostWorker("t")
  w.submit(lambda: done.append(1), label="j")
  w.close()
  assert done == [1]


# ---------------------------------------------------------------------------
# tiered: overlap-ON is bit-exact vs serial (guard, NaN skip, re-rank)
# ---------------------------------------------------------------------------


def _tiered_trainer(overlap, batch0):
  """A guarded tiered trainer from DETERMINISTIC params, with re-rank
  every 3 steps so the paired runs cross re-rank boundaries."""
  plan_b = tt._plan(None)
  plan_t = tt._plan(1000)
  model = tt._model()
  mesh = create_mesh(tt.WORLD)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adam(1e-3)
  params_b = model.init(jax.random.PRNGKey(0), batch0[0], batch0[1])["params"]
  tables_t = set_weights(plan_t, get_weights(plan_b, params_b["embeddings"]))
  params_t = {k: v for k, v in params_b.items() if k != "embeddings"}
  params_t["embeddings"] = {k: np.asarray(v) for k, v in tables_t.items()}
  tplan = TieringPlan(plan_t, rule, TieringConfig(cache_fraction=0.3,
                                                  staging_grps=64,
                                                  rerank_interval=3))
  store = HostTierStore(tplan)
  state = shard_params(
      init_tiered_state_from_params(tplan, store, rule, params_t, opt,
                                    mesh=mesh), mesh)
  return TieredTrainer(model, tplan, store, bce_loss, opt, rule, mesh,
                       state, batch0, donate=False, guard=True,
                       overlap_host=overlap)


def test_tiered_overlap_bit_exact_vs_serial_with_guard_skip(monkeypatch):
  """Serial vs overlapped tiered runs over one stream with a NaN batch
  in the middle and re-rank boundaries inside the window: losses, fused
  state, host images, and guard/tier accounting all bit-identical. The
  serial arm runs with the scheduler poisoned — overlap_host=False
  must never touch pipeline.py."""
  batch0 = tt._batch(100)
  batches = list(faultinject.nan_batches(
      [tt._batch(200 + i) for i in range(7)], at_steps={2}))

  t_ser = _tiered_trainer(False, batch0)
  with monkeypatch.context() as m:
    m.setattr(pipeline, "run_tiered_overlapped",
              lambda *a, **k: pytest.fail("serial run called the scheduler"))
    losses_ser = t_ser.run(batches)

  t_ovl = _tiered_trainer(True, batch0)
  repairs = {"n": 0}
  orig_repair = t_ovl.prefetcher.repair_conflicts

  def counted_repair(*a, **k):
    repairs["n"] += 1
    return orig_repair(*a, **k)
  t_ovl.prefetcher.repair_conflicts = counted_repair
  reg = telemetry.get_registry()
  h0 = reg.histogram("tiered/overlap_hidden_s").count
  losses_ovl = t_ovl.run(batches)

  # float-for-float identical (equal_nan covers the skipped step's NaN)
  np.testing.assert_allclose(losses_ser, losses_ovl, rtol=0, atol=0)
  assert not np.isfinite(losses_ovl[2])  # the poison batch skipped
  assert t_ser.bad_steps == t_ovl.bad_steps == 1
  assert t_ser.steps == t_ovl.steps
  for name in t_ser.hits:
    assert np.array_equal(t_ser.hits[name], t_ovl.hits[name]), name
  # the scheduler actually overlapped (and repaired write-back hazards)
  assert reg.histogram("tiered/overlap_hidden_s").count > h0
  assert repairs["n"] >= 1
  # full state parity: fused device buffers and flushed host images
  for name in t_ser.state["fused"]:
    assert np.array_equal(np.asarray(t_ser.state["fused"][name]),
                          np.asarray(t_ovl.state["fused"][name])), name
  t_ser.flush()
  t_ovl.flush()
  for name, imgs in t_ser.store.images.items():
    for r, img in enumerate(imgs):
      np.testing.assert_array_equal(img, t_ovl.store.images[name][r],
                                    err_msg=f"{name} rank {r}")


def test_tiered_worker_failure_fails_the_run():
  """A broken host pass on the worker must surface as the step's
  exception — never a silent serial fall-back."""
  batch0 = tt._batch(100)
  t = _tiered_trainer(True, batch0)

  def broken_gather(cold):
    raise RuntimeError("cold store unreachable")
  t.prefetcher.gather_cold = broken_gather  # only the worker job calls it
  with pytest.raises(RuntimeError, match="cold store unreachable"):
    t.run([tt._batch(300 + i) for i in range(3)])


# ---------------------------------------------------------------------------
# dynvocab: translate-ahead is bit-exact vs serial
# ---------------------------------------------------------------------------


def _dynvocab_trainer(world, overlap, batch0, micro_batches=1):
  plan = tdv._plan(world, oov="allocate")
  _, _, trainer = tdv._fresh(world, plan, batch0, guard=True,
                             micro_batches=micro_batches)
  trainer.overlap_host = overlap
  # pre-admit the identity mapping so both arms train the same rows
  trainer.translator.translate_batch(
      [np.arange(v, dtype=np.int64) for v in tdv.VOCAB])
  return trainer


@pytest.mark.parametrize("world,micro_batches", [(1, 1), (2, 1), (4, 2)])
def test_dynvocab_overlap_bit_exact_vs_serial(world, micro_batches,
                                              monkeypatch):
  batch0 = tdv._batch(100)
  batches = [tdv._batch(200 + s) for s in range(5)]

  t_ser = _dynvocab_trainer(world, False, batch0, micro_batches)
  with monkeypatch.context() as m:
    m.setattr(pipeline, "run_dynvocab_overlapped",
              lambda *a, **k: pytest.fail("serial run called the scheduler"))
    losses_ser = t_ser.run(batches)

  t_ovl = _dynvocab_trainer(world, True, batch0, micro_batches)
  reg = telemetry.get_registry()
  h0 = reg.histogram("dynvocab/overlap_hidden_s").count
  losses_ovl = t_ovl.run(batches)

  assert losses_ser == losses_ovl
  assert reg.histogram("dynvocab/overlap_hidden_s").count > h0
  for name in t_ser.state["fused"]:
    assert np.array_equal(np.asarray(t_ser.state["fused"][name]),
                          np.asarray(t_ovl.state["fused"][name])), name
  # the id space evolved through the identical mutation sequence
  tr_s, tr_o = t_ser.translator, t_ovl.translator
  for t in tr_s.dynamic_tables:
    a, b = tr_s.tables[t].items(), tr_o.tables[t].items()
    assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
    assert np.array_equal(tr_s.totals[t], tr_o.totals[t])


def test_dynvocab_worker_failure_fails_the_run():
  batch0 = tdv._batch(100)
  t = _dynvocab_trainer(2, True, batch0)
  orig = t.engine.translate_dynamic_ids
  calls = {"n": 0}

  def flaky(cats, translator):
    calls["n"] += 1
    if calls["n"] >= 2:  # first call serves batch 0 on the main thread
      raise RuntimeError("translator wedged")
    return orig(cats, translator)
  t.engine.translate_dynamic_ids = flaky
  with pytest.raises(RuntimeError, match="translator wedged"):
    t.run([tdv._batch(300 + s) for s in range(3)])
  assert calls["n"] == 2  # the failure came from the worker's call


# ---------------------------------------------------------------------------
# ResilientTrainer: overlap x snapshots x chaos
# ---------------------------------------------------------------------------

_RVOCAB = [5000, 300, 40]


def _resilient_tiered(tmp_path, root, seed, overlap, async_snapshots=False):
  """The test_resilience tiered fixture, with the overlap/async knobs."""
  world = 4
  mesh = create_mesh(world)
  plan = tt._plan(1000, _RVOCAB)
  model = DLRM(vocab_sizes=_RVOCAB, embedding_dim=16, bottom_mlp=(32, 16),
               top_mlp=(32, 1), world_size=world,
               strategy="memory_balanced", dense_row_threshold=0)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adam(1e-3)
  batch0 = tt._batch(0, _RVOCAB)
  tplan = TieringPlan(plan, rule, TieringConfig(cache_fraction=0.3,
                                                staging_grps=64,
                                                rerank_interval=3))
  store = HostTierStore(tplan)
  params = model.init(jax.random.PRNGKey(seed), batch0[0],
                      batch0[1])["params"]
  plan_b = tt._plan(None, _RVOCAB)
  params_b = model.init(jax.random.PRNGKey(0), batch0[0],
                        batch0[1])["params"]
  tables_t = set_weights(tplan.plan,
                         get_weights(plan_b, params_b["embeddings"]))
  params = {k: v for k, v in params.items() if k != "embeddings"}
  params["embeddings"] = {k: np.asarray(v) for k, v in tables_t.items()}
  state = shard_params(
      init_tiered_state_from_params(tplan, store, rule, params, opt,
                                    mesh=mesh), mesh)
  tt_trainer = TieredTrainer(model, tplan, store, bce_loss, opt, rule,
                             mesh, state, batch0, donate=False, guard=True,
                             overlap_host=overlap)
  return ResilientTrainer(None, None, plan, rule,
                          os.path.join(str(tmp_path), root), mesh=mesh,
                          snapshot_every=2, tiered=tt_trainer,
                          overlap_host=overlap,
                          async_snapshots=async_snapshots)


def _rstream():
  batches = [tt._batch(500 + i, _RVOCAB) for i in range(6)]
  return list(faultinject.nan_batches(batches, at_steps={3}))


def test_resilient_tiered_overlap_parity_async_and_kill_resume(tmp_path):
  """One stream (NaN batch included), three arms against a serial sync
  reference: (a) overlapped + ASYNC snapshots lands the identical
  trajectory and accounting — the copy-on-snapshot store view snapshots
  a live mutating store mid-overlap; (b) a resume from those
  async-written snapshots replays a bit-exact tail; (c) an injected
  crash during the second snapshot's writes — mid-run, worker job in
  flight — auto-resumes from the first snapshot to a bit-exact tail."""
  batches = _rstream()

  ref = _resilient_tiered(tmp_path, "ref", 7, overlap=False)
  with faultinject.injected(FaultInjector()) as probe:
    ref_losses = ref.run(batches)
  writes = probe.count("ckpt_write")
  n_snaps = len(durable.list_checkpoints(os.path.join(str(tmp_path),
                                                      "ref")))
  assert n_snaps > 0 and writes % n_snaps == 0
  per_snap = writes // n_snaps

  # (a) overlap + async snapshots: identical losses and accounting
  ovl = _resilient_tiered(tmp_path, "run", 7, overlap=True,
                          async_snapshots=True)
  losses = ovl.run(batches)
  ovl.close()
  np.testing.assert_allclose(losses, ref_losses, rtol=0, atol=0)
  assert not np.isfinite(losses[3])
  assert (ovl.step_count, ovl.skipped_steps, ovl.consumed) == \
      (ref.step_count, ref.skipped_steps, ref.consumed)
  steps_ref = [s for s, _ in durable.list_checkpoints(
      os.path.join(str(tmp_path), "ref"))]
  steps_run = [s for s, _ in durable.list_checkpoints(
      os.path.join(str(tmp_path), "run"))]
  assert steps_ref == steps_run  # async view published the same snapshots

  # (b) resume from the async-written root: bit-exact tail (different
  # init seed — the restore must overwrite it)
  res = _resilient_tiered(tmp_path, "run", 99, overlap=True)
  assert res.resumed_from is not None
  start = res.consumed
  assert 0 < start <= len(batches)
  tail = res.run(batches[start:])
  np.testing.assert_allclose(tail, ref_losses[start:], rtol=0, atol=0)

  # (c) crash on the second write of the SECOND snapshot: mid-run, with
  # the overlap worker active; snapshot 1 is durable, the run dies
  kill = _resilient_tiered(tmp_path, "kill", 7, overlap=True)
  with faultinject.injected(
      FaultInjector().crash_after("ckpt_write", per_snap + 1)):
    with pytest.raises(InjectedCrash):
      kill.run(batches)
  res2 = _resilient_tiered(tmp_path, "kill", 98, overlap=True)
  assert res2.resumed_from is not None
  start2 = res2.consumed
  assert 0 < start2 < len(batches)
  tail2 = res2.run(batches[start2:])
  np.testing.assert_allclose(tail2, ref_losses[start2:], rtol=0, atol=0)


def _resilient_dynvocab(tmp_path, root, overlap, batch0):
  plan = tdv._plan(2, oov="allocate", admit_threshold=1)
  _, mesh, dvt = tdv._fresh(2, plan, batch0, guard=True)
  dvt.overlap_host = overlap
  return ResilientTrainer(None, None, plan, tdv.RULE,
                          os.path.join(str(tmp_path), root), mesh=mesh,
                          snapshot_every=2, resume=True, dynvocab=dvt,
                          overlap_host=overlap)


def test_resilient_dynvocab_overlap_parity_and_resume(tmp_path):
  """Overlapped dynvocab under the ResilientTrainer: same losses, fused
  state, and id space as serial (the snapshot-deferral predicate keeps
  every snapshot's translator at the consumed-stream position), and an
  interrupted overlapped run resumes from its snapshots bit-exactly."""
  batch0 = tdv._batch(100)
  stream = [tdv._batch(700 + s) for s in range(6)]

  ref = _resilient_dynvocab(tmp_path, "ref", False, batch0)
  ref_losses = ref.run(stream)

  ovl = _resilient_dynvocab(tmp_path, "run", True, batch0)
  losses = ovl.run(stream)
  assert losses == ref_losses
  assert (ovl.step_count, ovl.consumed) == (ref.step_count, ref.consumed)
  tr_ref, tr_ovl = ref.dynvocab.translator, ovl.dynvocab.translator
  for t in tr_ref.dynamic_tables:
    a, b = tr_ref.tables[t].items(), tr_ovl.tables[t].items()
    assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
    assert np.array_equal(tr_ref.totals[t], tr_ovl.totals[t])
  for name in ref.state["fused"]:
    assert np.array_equal(np.asarray(ref.state["fused"][name]),
                          np.asarray(ovl.state["fused"][name])), name

  # interrupted overlapped run: consume 4 batches, drop the trainer,
  # resume a fresh overlapped one from the snapshots
  t1 = _resilient_dynvocab(tmp_path, "cut", True, batch0)
  first = t1.run(stream[:4])
  t2 = _resilient_dynvocab(tmp_path, "cut", True, batch0)
  assert t2.resumed_from is not None
  start = t2.consumed
  assert 0 < start <= 4
  rest = t2.run(stream[start:])
  assert first[:start] + rest == ref_losses
