"""Convergence proxy (VERDICT item 7): no real Criteo data is available
here, so this is the stand-in for the reference's AUC-parity bar — train
~300 steps on LEARNABLE synthetic data (labels are a seeded logit function
of the ids) and assert that the three execution paths reach matching loss
curves and rank-AUC:

1. single-device dense-autodiff path (make_train_step);
2. single-device fused sparse path (make_sparse_train_step);
3. 8-virtual-device fused sparse path.

All paths start from IDENTICAL weights (the fused state is unpacked to
seed the dense path) and see identical data streams.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest
import flax.linen as nn

from distributed_embeddings_tpu.layers import DistEmbeddingStrategy, TableConfig
from distributed_embeddings_tpu.models import bce_loss
from distributed_embeddings_tpu.ops.packed_table import sgd_rule
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.parallel.lookup_engine import DistributedLookup
from distributed_embeddings_tpu.training import (
    init_sparse_state_direct,
    make_sparse_train_step,
    make_train_step,
    shard_batch,
    shard_params,
    unpack_sparse_state,
)

WORLD = 8
VOCAB = [96, 144, 80]
WIDTH = 16
BATCH = 128
STEPS = 300
LR = 0.5


class Head(nn.Module):
  """Concat embedding activations (+ numerical passthrough) -> logit."""

  @nn.compact
  def __call__(self, numerical, cats, emb_acts=None):
    x = jnp.concatenate([numerical] + list(emb_acts), axis=1)
    x = nn.relu(nn.Dense(32, name="dense_0")(x))
    return jnp.squeeze(nn.Dense(1, name="dense_1")(x), -1)


def _data_stream(seed):
  """Seeded learnable task: logit = sum_t score_t[id_t] + small noise."""
  rng = np.random.default_rng(seed)
  scores = [rng.standard_normal(v).astype(np.float32) * 2.0 for v in VOCAB]

  def batch(step, n=BATCH):
    r = np.random.default_rng(seed * 100003 + step)
    cats = [r.integers(0, v, n).astype(np.int32) for v in VOCAB]
    logit = sum(s[c] for s, c in zip(scores, cats))
    labels = (r.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float32)
    numerical = r.standard_normal((n, 4)).astype(np.float32)
    return (jnp.asarray(numerical), [jnp.asarray(c) for c in cats],
            jnp.asarray(labels))

  return batch


def _rank_auc(scores, labels):
  order = np.argsort(scores)
  ranks = np.empty_like(order, dtype=np.float64)
  ranks[order] = np.arange(1, len(scores) + 1)
  pos = labels > 0.5
  n_pos, n_neg = pos.sum(), (~pos).sum()
  if n_pos == 0 or n_neg == 0:
    return 0.5
  return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


@pytest.mark.slow
def test_three_paths_converge_together():
  tables = [TableConfig(v, WIDTH) for v in VOCAB]
  rule = sgd_rule(LR)
  opt = optax.sgd(LR)
  model = Head()
  stream = _data_stream(7)
  numerical, cats, labels = stream(0)

  dummy = [jnp.zeros((2, WIDTH), jnp.float32) for _ in VOCAB]
  dense_params = model.init(jax.random.PRNGKey(0), numerical[:2], None,
                            emb_acts=dummy)["params"]

  def run_sparse(world, mesh):
    plan = DistEmbeddingStrategy(tables, world, "basic",
                                 dense_row_threshold=0)
    state = init_sparse_state_direct(plan, rule, dense_params, opt,
                                     jax.random.PRNGKey(1))
    if mesh is not None:
      state = shard_params(state, mesh)
    batch0 = shard_batch((numerical, cats, labels), mesh)
    step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                  state, batch0, donate=False)
    losses = []
    for i in range(STEPS):
      b = shard_batch(stream(i), mesh)
      state, loss = step(state, *b)
      losses.append(float(loss))
    # eval: logits on a held-out batch
    from distributed_embeddings_tpu.training import make_sparse_eval_step
    ev = make_sparse_eval_step(model, plan, rule, mesh, state, batch0)
    n_eval, c_eval, l_eval = stream(10_000, n=BATCH * 4)
    eb = shard_batch((n_eval, c_eval, l_eval), mesh)
    logits = np.asarray(jax.device_get(ev(state, eb[0], eb[1])))
    return losses, _rank_auc(logits, np.asarray(l_eval)), plan, state

  def run_dense():
    plan = DistEmbeddingStrategy(tables, 1, "basic", dense_row_threshold=0)
    engine = DistributedLookup(plan)
    # identical init: unpack the fused state the sparse paths start from
    state0 = init_sparse_state_direct(plan, rule, dense_params, opt,
                                      jax.random.PRNGKey(1))
    emb0, _ = unpack_sparse_state(plan, rule, state0)
    params = {"mlp": dense_params, "embeddings": emb0["embeddings"]}

    def loss_fn(p, numerical, cats, labels):
      acts = engine.forward(p["embeddings"], cats)
      logits = model.apply({"params": p["mlp"]}, numerical, None,
                           emb_acts=acts)
      return bce_loss(logits, labels)

    opt_state = opt.init(params)
    step = make_train_step(loss_fn, opt, None, params, opt_state,
                           (numerical, cats, labels), donate=False)
    losses = []
    for i in range(STEPS):
      n_, c_, l_ = stream(i)
      params, opt_state, loss = step(params, opt_state, n_, c_, l_)
      losses.append(float(loss))
    n_eval, c_eval, l_eval = stream(10_000, n=BATCH * 4)
    acts = engine.forward(params["embeddings"], c_eval)
    logits = np.asarray(model.apply({"params": params["mlp"]}, n_eval, None,
                                    emb_acts=acts))
    return losses, _rank_auc(logits, np.asarray(l_eval))

  losses_dense, auc_dense = run_dense()
  losses_s1, auc_s1, _, _ = run_sparse(1, None)
  losses_s8, auc_s8, _, _ = run_sparse(WORLD, create_mesh(WORLD))

  def tail(xs):
    return float(np.mean(xs[-20:]))

  # 1. everyone learns: the tail loss is well below the start
  for name, ls in (("dense", losses_dense), ("sparse1", losses_s1),
                   ("sparse8", losses_s8)):
    assert tail(ls) < np.mean(ls[:5]) - 0.05, \
        f"{name} did not learn: {np.mean(ls[:5]):.4f} -> {tail(ls):.4f}"

  # 2. the three loss curves end in the same place
  t = [tail(losses_dense), tail(losses_s1), tail(losses_s8)]
  assert max(t) - min(t) < 0.02, f"tail losses diverge: {t}"

  # 3. AUCs match within tolerance and beat chance decisively
  aucs = [auc_dense, auc_s1, auc_s8]
  assert min(aucs) > 0.65, f"AUCs too weak: {aucs}"
  assert max(aucs) - min(aucs) < 0.03, f"AUCs diverge: {aucs}"


@pytest.mark.slow
def test_per_occurrence_vs_exact_power_law():
  """VERDICT r3 item 5: quantify the training-quality effect of the
  default per-occurrence update semantics vs exact=True (the reference
  fused backward's dedup) under power-law id duplication.

  Adagrad on zipf(1.2) ids (heavy within-batch duplication, ~Tiny's
  regime): per-occurrence applies compound the accumulator once per
  occurrence, exact applies once per unique row — the semantics differ
  most exactly here. The dense-autodiff path has dedup semantics by
  construction (XLA sums cotangents per row before the optimizer), so it
  anchors exact=True; the test asserts all three loss curves land
  together and per-occurrence stays within a bounded gap of exact."""
  vocab = [2000, 1200]
  width = 16
  batch = 256
  steps = 300
  tables = [TableConfig(v, width) for v in vocab]
  from distributed_embeddings_tpu.ops.packed_table import adagrad_rule
  rule = adagrad_rule(0.08)
  opt = optax.adagrad(0.08)
  model = Head()

  rng = np.random.default_rng(11)
  scores = [rng.standard_normal(v).astype(np.float32) * 2.0 for v in vocab]

  def stream(step, n=batch):
    r = np.random.default_rng(11 * 100003 + step)
    cats = [np.minimum(r.zipf(1.2, n).astype(np.int64) - 1, v - 1)
            .astype(np.int32) for v in vocab]
    logit = sum(s[c] for s, c in zip(scores, cats))
    labels = (r.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float32)
    numerical = r.standard_normal((n, 4)).astype(np.float32)
    return (jnp.asarray(numerical), [jnp.asarray(c) for c in cats],
            jnp.asarray(labels))

  numerical, cats, labels = stream(0)
  # measured duplication of the stream (documentation value): unique/total,
  # with each table's ids offset into its own range so equal ids from
  # DIFFERENT tables never count as duplicates of each other
  base = np.cumsum([0] + vocab[:-1])
  all_ids = np.concatenate(
      [np.asarray(c) + b for c, b in zip(cats, base)])
  dup = all_ids.size / max(1, len(np.unique(all_ids)))

  dummy = [jnp.zeros((2, width), jnp.float32) for _ in vocab]
  dense_params = model.init(jax.random.PRNGKey(0), numerical[:2], None,
                            emb_acts=dummy)["params"]
  plan = DistEmbeddingStrategy(tables, 1, "basic", dense_row_threshold=0)

  def run(exact):
    state = init_sparse_state_direct(plan, rule, dense_params, opt,
                                     jax.random.PRNGKey(1))
    step = make_sparse_train_step(model, plan, bce_loss, opt, rule, None,
                                  state, (numerical, cats, labels),
                                  exact=exact, donate=False)
    losses = []
    for i in range(steps):
      state, loss = step(state, *stream(i))
      losses.append(float(loss))
    from distributed_embeddings_tpu.training import make_sparse_eval_step
    ev = make_sparse_eval_step(model, plan, rule, None, state,
                               (numerical, cats, labels))
    n_e, c_e, l_e = stream(10_000, n=batch * 4)
    logits = np.asarray(jax.device_get(ev(state, n_e, c_e)))
    return losses, _rank_auc(logits, np.asarray(l_e))

  losses_occ, auc_occ = run(False)
  losses_ex, auc_ex = run(True)

  def tail(xs):
    return float(np.mean(xs[-20:]))

  for name, ls in (("per-occurrence", losses_occ), ("exact", losses_ex)):
    assert tail(ls) < np.mean(ls[:5]) - 0.05, \
        f"{name} did not learn: {np.mean(ls[:5]):.4f} -> {tail(ls):.4f}"
  gap = abs(tail(losses_occ) - tail(losses_ex))
  assert gap < 0.02, (
      f"per-occurrence vs exact tail-loss gap {gap:.4f} "
      f"(dup {dup:.1f}x): semantics diverge in training quality")
  assert min(auc_occ, auc_ex) > 0.65, (auc_occ, auc_ex)
  assert abs(auc_occ - auc_ex) < 0.03, (auc_occ, auc_ex)
  print(f"dup {dup:.2f}x; tail loss occ {tail(losses_occ):.4f} vs "
        f"exact {tail(losses_ex):.4f} (gap {gap:.4f}); "
        f"AUC {auc_occ:.4f} vs {auc_ex:.4f}")
