"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference tests multi-worker behavior by launching real processes under
horovodrun against real GPUs (`/root/reference/tests/dist_model_parallel_test.py:97-103`).
JAX gives us a fake-backend capability the reference lacks: N virtual CPU
devices in one process via XLA flags, so distributed tests run anywhere.

This environment force-registers a real-TPU PJRT backend ('axon') for every
Python process at interpreter startup and pins ``jax_platforms`` to it, so we
must override the already-imported jax config — plain env vars are read too
early to help. Unit tests must never touch the single real TPU (bench.py owns
it).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache, scoped to this pytest run.  The in-memory
# pjit cache is keyed on function identity, so the same DLRM step function
# re-traced in a different test module recompiles from scratch; the
# persistent cache is keyed on the HLO hash, so those duplicate compiles
# become disk hits.  A fresh per-run directory keeps runs hermetic (no
# stale artifacts across jax/XLA upgrades) while still deduping the many
# identical step functions the suite compiles across files.
import tempfile  # noqa: E402

_cache_dir = tempfile.mkdtemp(prefix="jax_test_compile_cache_")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

assert len(jax.devices()) == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}")

# markers are registered in pyproject.toml [tool.pytest.ini_options]
# (with --strict-markers, so an unregistered marker fails collection)
