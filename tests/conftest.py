"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference tests multi-worker behavior by launching real processes under
horovodrun against real GPUs (`/root/reference/tests/dist_model_parallel_test.py:97-103`).
JAX gives us a fake-backend capability the reference lacks: N virtual CPU
devices in one process via XLA flags, so distributed tests run anywhere.

This environment force-registers a real-TPU PJRT backend ('axon') for every
Python process at interpreter startup and pins ``jax_platforms`` to it, so we
must override the already-imported jax config — plain env vars are read too
early to help. Unit tests must never touch the single real TPU (bench.py owns
it).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

assert len(jax.devices()) == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}")

# markers are registered in pyproject.toml [tool.pytest.ini_options]
# (with --strict-markers, so an unregistered marker fails collection)
