"""DLRM + synthetic model tests: shapes, interaction math, distributed
training convergence, data pipeline."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_embeddings_tpu.models import (
    DLRM,
    SYNTHETIC_MODELS,
    SyntheticModel,
    bce_loss,
    dot_interact,
    expand_tables,
    generate_batch,
    model_size_gib,
    power_law_ids,
)
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.training import (
    make_eval_step,
    make_train_step,
    shard_batch,
    shard_params,
)

WORLD = 8


def test_dot_interact_matches_naive():
  rng = np.random.default_rng(0)
  b, f, d = 4, 5, 8
  bottom = rng.standard_normal((b, d)).astype(np.float32)
  embs = [rng.standard_normal((b, d)).astype(np.float32) for _ in range(f - 1)]
  out = dot_interact(jnp.asarray(bottom), [jnp.asarray(e) for e in embs])
  feats = np.stack([bottom] + embs, 1)
  gram = np.einsum("bfd,bgd->bfg", feats, feats)
  rows, cols = np.tril_indices(f, k=-1)
  want = np.concatenate([gram[:, rows, cols], bottom], axis=1)
  assert out.shape == (b, f * (f - 1) // 2 + d)
  np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("self_interaction", [False, True])
@pytest.mark.parametrize("pack", [1, 2, 4])
def test_dot_interact_grad_matches_autodiff(self_interaction, pack):
  """The hand-written VJP (sentinel zero column, symmetrized inv map,
  self-interaction diagonal 2x, packed cross-sample zero blocks) must match
  plain autodiff of the naive formulation exactly."""
  rng = np.random.default_rng(1)
  b, f, d = 8, 5, 16
  bottom = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
  embs = [jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
          for _ in range(f - 1)]

  def naive(bo, es):
    feats = jnp.stack([bo] + list(es), axis=1)
    gram = jnp.einsum("bfd,bgd->bfg", feats, feats)
    rows, cols = np.tril_indices(f, k=0 if self_interaction else -1)
    acts = jnp.take(gram.reshape(b, f * f),
                    jnp.asarray(rows * f + cols), axis=1)
    return jnp.concatenate([acts, bo], axis=1)

  def loss_custom(bo, es):
    return jnp.sum(jnp.tanh(dot_interact(
        bo, es, self_interaction=self_interaction, pack=pack)))

  def loss_naive(bo, es):
    return jnp.sum(jnp.tanh(naive(bo, es)))

  np.testing.assert_allclose(loss_custom(bottom, embs),
                             loss_naive(bottom, embs), rtol=1e-5)
  g_c = jax.grad(loss_custom, argnums=(0, 1))(bottom, embs)
  g_n = jax.grad(loss_naive, argnums=(0, 1))(bottom, embs)
  np.testing.assert_allclose(np.asarray(g_c[0]), np.asarray(g_n[0]),
                             rtol=1e-4, atol=1e-5)
  for got, want in zip(g_c[1], g_n[1]):
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_dot_interact_grad_bf16_close():
  """AMP path: the backward rounds the cotangent to bf16 (documented AMP
  convention); grads must still match autodiff within bf16 tolerance."""
  rng = np.random.default_rng(2)
  b, f, d = 8, 5, 16
  bottom = jnp.asarray(rng.standard_normal((b, d)), jnp.bfloat16)
  embs = [jnp.asarray(rng.standard_normal((b, d)), jnp.bfloat16)
          for _ in range(f - 1)]

  def naive(bo, es):
    feats = jnp.stack([bo] + list(es), axis=1)
    gram = jnp.einsum("bfd,bgd->bfg", feats, feats,
                      preferred_element_type=jnp.float32)
    rows, cols = np.tril_indices(f, k=-1)
    acts = jnp.take(gram.reshape(b, f * f),
                    jnp.asarray(rows * f + cols), axis=1)
    return jnp.concatenate([acts, bo.astype(acts.dtype)], axis=1)

  loss_c = lambda bo: jnp.sum(jnp.tanh(dot_interact(bo, embs)))  # noqa: E731
  loss_n = lambda bo: jnp.sum(jnp.tanh(naive(bo, embs)))  # noqa: E731
  g_c = np.asarray(jax.grad(loss_c)(bottom), np.float32)
  g_n = np.asarray(jax.grad(loss_n)(bottom), np.float32)
  np.testing.assert_allclose(g_c, g_n, rtol=2e-2, atol=2e-2)


def test_dot_interact_rejects_bad_pack():
  x = jnp.zeros((4, 8))
  with pytest.raises(ValueError, match="pack"):
    dot_interact(x, [x], pack=0)


def test_dlrm_single_device_forward_and_loss():
  rng = np.random.default_rng(1)
  vocab = [50, 60, 70, 80]
  model = DLRM(vocab_sizes=vocab, embedding_dim=16, bottom_mlp=(32, 16),
               top_mlp=(32, 1))
  b = 8
  numerical = jnp.asarray(rng.standard_normal((b, 13)), jnp.float32)
  cats = [jnp.asarray(rng.integers(0, v, b), jnp.int32) for v in vocab]
  params = model.init(jax.random.PRNGKey(0), numerical, cats)
  logits = model.apply(params, numerical, cats)
  assert logits.shape == (b,) and logits.dtype == jnp.float32
  labels = jnp.asarray(rng.integers(0, 2, b), jnp.float32)
  loss = bce_loss(logits, labels)
  assert np.isfinite(float(loss))


def test_dlrm_bad_bottom_mlp_raises():
  model = DLRM(vocab_sizes=[10], embedding_dim=16, bottom_mlp=(32, 8))
  with pytest.raises(ValueError):
    model.init(jax.random.PRNGKey(0), jnp.zeros((2, 4)),
               [jnp.zeros((2,), jnp.int32)])


def test_dlrm_distributed_training_converges():
  rng = np.random.default_rng(2)
  vocab = [64] * 8
  mesh = create_mesh(WORLD)
  model = DLRM(vocab_sizes=vocab, embedding_dim=8, bottom_mlp=(16, 8),
               top_mlp=(16, 1), world_size=WORLD, strategy="memory_balanced")
  b = 4 * WORLD
  numerical = jnp.asarray(rng.standard_normal((b, 13)), jnp.float32)
  cats = [jnp.asarray(rng.integers(0, v, b), jnp.int32) for v in vocab]
  labels = jnp.asarray(rng.integers(0, 2, b), jnp.float32)
  params = model.init(jax.random.PRNGKey(0), numerical, cats)["params"]
  optimizer = optax.sgd(0.1)
  opt_state = optimizer.init(params)
  params = shard_params(params, mesh)
  opt_state = shard_params(opt_state, mesh)

  def loss_fn(p, numerical, cats, labels):
    return bce_loss(model.apply({"params": p}, numerical, cats), labels)

  batch = (numerical, cats, labels)
  step = make_train_step(loss_fn, optimizer, mesh, params, opt_state, batch)
  sharded = shard_batch(batch, mesh)
  losses = []
  for _ in range(8):
    params, opt_state, loss = step(params, opt_state, *sharded)
    losses.append(float(loss))
  assert losses[-1] < losses[0], losses

  def pred_fn(p, numerical, cats):
    return jax.nn.sigmoid(model.apply({"params": p}, numerical, cats))

  eval_step = make_eval_step(pred_fn, mesh, params, (numerical, cats))
  preds = eval_step(params, *shard_batch((numerical, cats), mesh))
  assert preds.shape == (b,)
  assert np.all((np.asarray(preds) >= 0) & (np.asarray(preds) <= 1))


def test_dlrm_amp_bf16():
  vocab = [32, 32]
  model = DLRM(vocab_sizes=vocab, embedding_dim=8, bottom_mlp=(8,),
               top_mlp=(8, 1), compute_dtype=jnp.bfloat16)
  numerical = jnp.zeros((4, 4))
  cats = [jnp.zeros((4,), jnp.int32)] * 2
  params = model.init(jax.random.PRNGKey(0), numerical, cats)
  logits = model.apply(params, numerical, cats)
  assert logits.dtype == jnp.float32  # output upcast


def test_synthetic_zoo_table_counts():
  # published counts: SURVEY.md §6 / reference synthetic_models README
  expected = {"tiny": 55, "small": 107, "medium": 311, "large": 612,
              "jumbo": 1022, "colossal": 2002}
  for name, count in expected.items():
    tables, _, _ = expand_tables(SYNTHETIC_MODELS[name])
    assert len(tables) == count, (name, len(tables))


def test_synthetic_zoo_sizes_match_published_gib():
  published = {"tiny": 4.2, "small": 26.3, "medium": 206.2, "large": 773.8,
               "jumbo": 3109.5, "colossal": 22327.4}
  for name, gib in published.items():
    got = model_size_gib(SYNTHETIC_MODELS[name])
    assert abs(got - gib) / gib < 0.02, (name, got, gib)


def test_power_law_distribution_skews_low():
  rng = np.random.default_rng(3)
  ids = power_law_ids(rng, 2000, 1, 10_000, alpha=1.1)
  assert ids.min() >= 0 and ids.max() < 10_000
  # strong skew: majority of mass in the lowest decile
  frac_low = (ids < 1000).mean()
  assert frac_low > 0.5, frac_low
  uniform = power_law_ids(rng, 2000, 1, 10_000, alpha=0)
  assert (uniform < 1000).mean() < 0.2


def test_synthetic_model_trains_distributed():
  cfg = SYNTHETIC_MODELS["tiny"]
  # shrink tables for test speed but keep structure (incl. shared multi-hot)
  import dataclasses
  groups = tuple(
      dataclasses.replace(g, num_rows=min(g.num_rows, 1000))
      for g in cfg.embedding_groups)
  cfg = dataclasses.replace(cfg, embedding_groups=groups)
  mesh = create_mesh(WORLD)
  model = SyntheticModel(config=cfg, world_size=WORLD)
  numerical, cats, labels = generate_batch(cfg, 2 * WORLD, alpha=1.05, seed=4)
  # shrink ids to the shrunk tables
  tables, tmap, _ = expand_tables(cfg)
  cats = [np.minimum(c, tables[t].input_dim - 1) for c, t in zip(cats, tmap)]
  batch = (jnp.asarray(numerical), [jnp.asarray(c) for c in cats],
           jnp.asarray(labels))
  params = model.init(jax.random.PRNGKey(0), batch[0], batch[1])["params"]
  optimizer = optax.adagrad(0.002)
  opt_state = optimizer.init(params)
  params = shard_params(params, mesh)
  opt_state = shard_params(opt_state, mesh)

  def loss_fn(p, numerical, cats, labels):
    return bce_loss(model.apply({"params": p}, numerical, cats), labels)

  step = make_train_step(loss_fn, optimizer, mesh, params, opt_state, batch)
  sharded = shard_batch(batch, mesh)
  losses = []
  for _ in range(10):
    params, opt_state, loss = step(params, opt_state, *sharded)
    losses.append(float(loss))
  assert losses[-1] < losses[0], losses
  assert np.isfinite(losses).all()
