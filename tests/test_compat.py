"""Direct unit tests of the version-portability shims in `compat.py`.

Every distributed module routes through these four names; until now they
were exercised only transitively (a shim regression surfaced as 11
modules failing at import). These tests pin each shim's CONTRACT so they
hold on both jax homes (0.4.x experimental shard_map vs the promoted
``jax.shard_map``):

- ``shard_map``: resolves to whichever home exists and maps a body over
  the mesh;
- ``enable_x64``: context-manages 64-bit mode on and back off;
- ``axis_size``: static mesh-axis size inside a mapped body (no
  collective at runtime — it must constant-fold under jit);
- ``psum_replicated_grads``: grads of a REPLICATED param, taken inside a
  shard_map body over device-sharded data, come out as the global sum
  EXACTLY ONCE — the explicit psum on 0.4.x, a no-op where shard_map's
  autodiff already inserted it (summing twice would double-count; zero
  times would train on 1/world of the gradient).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_embeddings_tpu import compat
from distributed_embeddings_tpu.parallel import create_mesh

WORLD = 4


def test_shard_map_home_resolution():
  if hasattr(jax, "shard_map"):
    assert compat.shard_map is jax.shard_map
    assert compat.SHARD_MAP_PSUMS_REPLICATED_GRADS
  else:
    from jax.experimental.shard_map import shard_map as exp_shard_map
    assert compat.shard_map is exp_shard_map
    assert not compat.SHARD_MAP_PSUMS_REPLICATED_GRADS


def test_shard_map_maps_body_over_mesh():
  mesh = create_mesh(WORLD)
  x = jnp.arange(2 * WORLD, dtype=jnp.float32).reshape(WORLD, 2)
  f = compat.shard_map(lambda xl: xl * 2.0, mesh=mesh,
                       in_specs=(P("mp", None),), out_specs=P("mp", None))
  np.testing.assert_array_equal(np.asarray(f(x)), np.asarray(x) * 2.0)


def test_enable_x64_context_roundtrip():
  import warnings
  with warnings.catch_warnings():
    # outside the context an explicit int64 request truncates (and warns)
    warnings.simplefilter("ignore", UserWarning)
    assert jnp.asarray(1, jnp.int64).dtype == jnp.int32  # x64 off (default)
    with compat.enable_x64():
      assert jnp.asarray(1, jnp.int64).dtype == jnp.int64
      assert jnp.asarray(1.0, jnp.float64).dtype == jnp.float64
    assert jnp.asarray(1, jnp.int64).dtype == jnp.int32  # restored


def test_axis_size_is_static_inside_shard_map():
  mesh = create_mesh(WORLD)

  def body(xl):
    # a Python int at trace time — usable as a shape/scale constant
    world = compat.axis_size("mp")
    return xl + jnp.float32(world)

  f = jax.jit(compat.shard_map(body, mesh=mesh, in_specs=(P("mp"),),
                               out_specs=P("mp")))
  out = np.asarray(f(jnp.zeros(WORLD, jnp.float32)))
  np.testing.assert_array_equal(out, np.full(WORLD, WORLD, np.float32))


def test_psum_replicated_grads_sums_exactly_once():
  """The hybrid-backward convention `training.py` is built on: the
  replicated param's grad equals the sum of every device's local grad —
  not 1x the local grad (0.4.x without the shim) and not world x the
  global sum (double-psum)."""
  mesh = create_mesh(WORLD)
  x = jnp.arange(1.0, WORLD + 1.0)          # one element per device
  p0 = jnp.asarray(2.0)

  def local_step(p, xl):
    loss, g = jax.value_and_grad(lambda q: jnp.sum(q * xl))(p)
    g = compat.psum_replicated_grads(g, "mp")
    return g, jax.lax.psum(loss, "mp")

  f = jax.jit(compat.shard_map(
      local_step, mesh=mesh, in_specs=(P(), P("mp")),
      out_specs=(P(), P())))
  g, loss = f(p0, x)
  assert float(g) == float(np.sum(np.asarray(x)))          # 10.0
  assert float(loss) == float(p0) * float(np.sum(np.asarray(x)))


def test_psum_replicated_grads_tree():
  """Applies leaf-wise over grad pytrees (the call sites hand it the
  whole dense-grad tree)."""
  mesh = create_mesh(WORLD)
  x = jnp.ones(WORLD)

  def body(tree, xl):
    def loss(t):
      return jnp.sum(t["a"] * xl) + jnp.sum(t["b"] * xl) * 2.0
    g = jax.grad(loss)(tree)
    return compat.psum_replicated_grads(g, "mp")

  f = jax.jit(compat.shard_map(
      body, mesh=mesh, in_specs=({"a": P(), "b": P()}, P("mp")),
      out_specs={"a": P(), "b": P()}))
  g = f({"a": jnp.zeros(()), "b": jnp.zeros(())}, x)
  assert float(g["a"]) == WORLD
  assert float(g["b"]) == 2.0 * WORLD
