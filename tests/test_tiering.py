"""Tiered embedding storage tests (`distributed_embeddings_tpu/tiering/`).

The contract under test: host-offloading a class (cold rows in host RAM,
a frequency-ranked hot cache + per-step staging buffer on device) is a
pure STORAGE decision — the training math is the all-device fused path
unchanged. So every test here is a parity test at heart:

- hot/cold routing parity: a tiered run over a fixed skewed id stream
  produces the same losses and the same final weights as the all-device
  run it shadows (bit-identical losses; fp32 tolerance on weights after
  the pack/unpack round trips);
- the acceptance scenario: a DLRM whose table bytes exceed the
  configured per-device HBM budget trains end-to-end on the CPU mesh
  simulator with > 80% hot-tier hit rate;
- staging-buffer overflow takes the deterministic spill path (bigger
  host gather, retrace) and never drops an update;
- periodic re-ranking (promotion/eviction) is value-preserving;
- checkpoint save -> restore of a tiered plan resumes bit-identically,
  and geometry / tier mismatches fail loudly instead of corrupting.
"""

import os
import shutil
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu import checkpoint
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    get_weights,
    set_weights,
)
from distributed_embeddings_tpu.layers.embedding import TableConfig
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM, bce_loss
from distributed_embeddings_tpu.models.dlrm import _dlrm_initializer
from distributed_embeddings_tpu.models.synthetic import power_law_ids
from distributed_embeddings_tpu.ops.packed_table import sparse_rule
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.tiering import (
    HostTierStore,
    TieredTrainer,
    TieringConfig,
    TieringPlan,
    init_tiered_state,
    init_tiered_state_from_params,
    unpack_tiered_state,
)
from distributed_embeddings_tpu.training import (
    init_sparse_state,
    make_sparse_train_step,
    shard_batch,
    shard_params,
    unpack_sparse_state,
)

WORLD = 4
VOCAB = [5000, 300, 40]
WIDTH = 16


def _tables(vocab=VOCAB):
  return [TableConfig(input_dim=v, output_dim=WIDTH,
                      initializer=_dlrm_initializer(v)) for v in vocab]


def _plan(host_thr, vocab=VOCAB, **kw):
  return DistEmbeddingStrategy(_tables(vocab), WORLD, "memory_balanced",
                               dense_row_threshold=0,
                               host_row_threshold=host_thr, **kw)


def _model(vocab=VOCAB):
  return DLRM(vocab_sizes=vocab, embedding_dim=WIDTH, bottom_mlp=(32, WIDTH),
              top_mlp=(32, 1), world_size=WORLD, strategy="memory_balanced",
              dense_row_threshold=0)


def _batch(seed, vocab=VOCAB, batch=32, alpha=1.05):
  r = np.random.default_rng(seed)
  numerical = r.standard_normal((batch, 13)).astype(np.float32)
  cats = [power_law_ids(r, batch, 1, v, alpha).astype(np.int32)[:, 0]
          for v in vocab]
  labels = r.integers(0, 2, batch).astype(np.float32)
  return numerical, cats, labels


def _paired_runs(cfg, n_steps=6, vocab=VOCAB, alpha=1.05, batch=32,
                 plan_kw=None):
  """Train the all-device baseline and the tiered run from identical
  params on an identical skewed stream; return (losses_b, losses_t,
  weights_b, weights_t, trainer). ``plan_kw`` applies to the TIERED
  plan only (wire-knob composition tests)."""
  plan_b = _plan(None, vocab)
  plan_t = _plan(1000, vocab, **(plan_kw or {}))
  model = _model(vocab)
  mesh = create_mesh(WORLD)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adam(1e-3)
  batch0 = _batch(100, vocab, batch, alpha)

  params_b = model.init(jax.random.PRNGKey(0), batch0[0], batch0[1])["params"]
  tables_t = set_weights(plan_t, get_weights(plan_b, params_b["embeddings"]))
  params_t = {k: v for k, v in params_b.items() if k != "embeddings"}
  params_t["embeddings"] = {k: jnp.asarray(v) for k, v in tables_t.items()}

  state_b = shard_params(init_sparse_state(plan_b, params_b, rule, opt), mesh)
  step_b = make_sparse_train_step(model, plan_b, bce_loss, opt, rule, mesh,
                                  state_b, batch0, donate=False)

  tplan = TieringPlan(plan_t, rule, cfg)
  store = HostTierStore(tplan)
  state_t = shard_params(
      init_tiered_state_from_params(tplan, store, rule, params_t, opt,
                                    mesh=mesh), mesh)
  trainer = TieredTrainer(model, tplan, store, bce_loss, opt, rule, mesh,
                          state_t, batch0, donate=False)

  batches = [_batch(100 + i, vocab, batch, alpha) for i in range(n_steps)]
  losses_b = []
  for b in batches:
    sb = shard_batch(b, mesh)
    state_b, lb = step_b(state_b, *sb)
    losses_b.append(float(lb))
  losses_t = trainer.run(batches)

  trainer.flush()
  p_b, _ = unpack_sparse_state(plan_b, rule, jax.device_get(state_b))
  p_t = unpack_tiered_state(tplan, store, rule, trainer.state)
  w_b = get_weights(plan_b, p_b["embeddings"])
  w_t = get_weights(plan_t, p_t["embeddings"])
  return losses_b, losses_t, w_b, w_t, trainer


def _assert_parity(losses_b, losses_t, w_b, w_t):
  np.testing.assert_allclose(losses_b, losses_t, rtol=1e-5, atol=1e-6)
  for t, (a, b) in enumerate(zip(w_b, w_t)):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                               atol=1e-5, err_msg=f"table {t}")


# ---------------------------------------------------------------------------
# planner: the third placement tier
# ---------------------------------------------------------------------------

def test_planner_host_tier_classes():
  plan = _plan(1000)
  # table 0 (5000 rows) is host-tier; tables 1-2 stay on device, and the
  # host-tier table must land in its own class (generation separation)
  assert plan.table_tier(0) == "host"
  assert plan.table_tier(1) == plan.table_tier(2) == "device"
  host_keys = plan.host_tier_class_keys()
  assert len(host_keys) == 1
  tiers = set(plan.class_tiers.values())
  assert tiers == {"host", "device"}
  for key in host_keys:
    for shards in plan.classes[key].shards_per_rank:
      assert all(sh.table_id == 0 for sh in shards)

  report = plan.tier_capacity_report(n_aux=1)
  assert report["host_bytes_per_rank"] > 0
  assert report["device_bytes_per_rank"] > 0
  assert report["classes"][host_keys[0]]["tier"] == "host"


def test_planner_host_threshold_validation():
  with pytest.raises(ValueError, match="must be positive"):
    _plan(0)
  with pytest.raises(ValueError, match="must exceed"):
    DistEmbeddingStrategy(_tables(), WORLD, "memory_balanced",
                          dense_row_threshold=50, host_row_threshold=50)


def test_plan_fingerprint_pins_tiering():
  from distributed_embeddings_tpu.checkpoint import _plan_fingerprint
  fp_dev = _plan_fingerprint(_plan(None))
  fp_host = _plan_fingerprint(_plan(1000))
  assert "class_tiers" not in fp_dev  # pre-tiering checkpoints unaffected
  assert "host" in fp_host["class_tiers"].values()
  assert fp_dev != fp_host
  # a threshold no table crosses leaves the layout untiered: the
  # fingerprint (and so checkpoint compatibility) must match the
  # untiered plan exactly
  assert _plan_fingerprint(_plan(1_000_000)) == fp_dev


def test_tiering_plan_geometry():
  rule = sparse_rule("adagrad", 0.05)
  plan = _plan(1000)
  tplan = TieringPlan(plan, rule, TieringConfig(cache_fraction=0.3,
                                                staging_grps=64))
  (c,) = tplan.classes.values()
  lay = c.layout_logical
  # compact buffer strictly smaller than the vocabulary, ids under sentinel
  assert c.spec.compact_rows < lay.rows
  assert c.spec.cache_grps == int(lay.phys_rows * 0.3)
  assert c.spec.staging_grps == 64
  assert tplan.device_bytes_per_rank() < (
      lay.phys_rows * lay.phys_width * 4)
  assert tplan.host_bytes_per_rank() == lay.phys_rows * lay.phys_width * 4

  with pytest.raises(ValueError, match="no host-tier classes"):
    TieringPlan(_plan(None), rule, TieringConfig())


def test_planner_accepts_overlimit_host_table():
  # width 512: one packed device buffer caps at ~4.19M rows, so a 5M-row
  # table is untrainable all-device — host-offloading it is the whole
  # point of the tier, and the plan-time 2^31 check must not reject it
  big = [TableConfig(input_dim=5_000_000, output_dim=512,
                     initializer=_dlrm_initializer(5_000_000))]
  with pytest.raises(ValueError, match="exceeds one TPU buffer"):
    DistEmbeddingStrategy(big, 1, "basic", dense_row_threshold=0)
  plan = DistEmbeddingStrategy(big, 1, "basic", dense_row_threshold=0,
                               host_row_threshold=1_000_000)
  rule = sparse_rule("adagrad", 0.05)
  tplan = TieringPlan(plan, rule, TieringConfig(cache_fraction=0.01,
                                                staging_grps=256))
  (c,) = tplan.classes.values()
  # the device side (compact buffer) stays under the element limit
  assert (c.layout_compact.phys_rows * c.layout_compact.phys_width
          <= 2 ** 31)


def test_save_tiered_plan_requires_store(tmp_path):
  # forgetting the store must refuse, not silently drop the cold rows
  with pytest.raises(ValueError, match="HostTierStore"):
    checkpoint.save(str(tmp_path / "ck"), _plan(1000),
                    sparse_rule("adagrad", 0.05), {"fused": {}})


def test_tiering_plan_budget_sizing():
  rule = sparse_rule("adagrad", 0.05)
  plan = _plan(1000)
  report = plan.tier_capacity_report(rule.n_aux)
  # a budget below the fixed device-tier footprint cannot host any cache
  with pytest.raises(ValueError, match="leaves no room"):
    TieringPlan(plan, rule, TieringConfig(
        hbm_budget_bytes=report["device_bytes_per_rank"], staging_grps=16))
  # a budget between fixed and fixed+cold sizes a partial cache
  cold = report["host_bytes_per_rank"]
  budget = report["device_bytes_per_rank"] + cold // 2
  tplan = TieringPlan(plan, rule, TieringConfig(hbm_budget_bytes=budget,
                                                staging_grps=16))
  (c,) = tplan.classes.values()
  assert 0 < c.spec.cache_grps < c.layout_logical.phys_rows
  assert tplan.device_bytes_per_rank() + (
      report["device_bytes_per_rank"]) <= budget + c.layout_logical.phys_rows * 4


# ---------------------------------------------------------------------------
# hot/cold routing parity + the acceptance scenario
# ---------------------------------------------------------------------------

def test_tiered_parity_vs_all_device():
  cfg = TieringConfig(cache_fraction=0.3, staging_grps=64, rerank_interval=3)
  losses_b, losses_t, w_b, w_t, trainer = _paired_runs(cfg)
  _assert_parity(losses_b, losses_t, w_b, w_t)
  m = trainer.metrics_summary()
  assert m["steps"] == 6
  assert all(v["missed"] == 0 for v in m["per_class"].values())
  assert m["host_gather_bytes"] > 0


def test_tiered_fused_wire_parity():
  """The tiered trainer composes with ``overlap='fused'``: the device
  tier's exchange runs the just-in-time per-(round, chunk) schedule and
  parity vs the all-device baseline still holds (the schedule is pure
  data movement, so the tiered run's numerics are unchanged)."""
  cfg = TieringConfig(cache_fraction=0.3, staging_grps=64, rerank_interval=3)
  losses_b, losses_t, w_b, w_t, _ = _paired_runs(
      cfg, n_steps=4, plan_kw={"overlap": "fused", "exchange_chunks": 2})
  _assert_parity(losses_b, losses_t, w_b, w_t)


def test_hbm_budget_end_to_end():
  """The acceptance scenario: total table bytes exceed the per-device HBM
  budget, yet the model trains on the CPU mesh simulator, matches the
  all-device baseline, and the hot tier serves > 80% of lookups."""
  rule = sparse_rule("adagrad", 0.05)
  plan = _plan(1000)
  report = plan.tier_capacity_report(rule.n_aux)
  total = report["device_bytes_per_rank"] + report["host_bytes_per_rank"]
  budget = report["device_bytes_per_rank"] + report["host_bytes_per_rank"] // 2
  assert total > budget  # the tables do NOT fit the device budget
  cfg = TieringConfig(hbm_budget_bytes=budget, staging_grps=64,
                      rerank_interval=3)
  losses_b, losses_t, w_b, w_t, trainer = _paired_runs(cfg, n_steps=6)
  _assert_parity(losses_b, losses_t, w_b, w_t)
  assert trainer.tplan.device_bytes_per_rank() + \
      report["device_bytes_per_rank"] <= budget + 4 * max(
          c.layout_logical.phys_rows
          for c in trainer.tplan.classes.values())
  assert trainer.hit_rate() > 0.8, trainer.metrics_summary()


# ---------------------------------------------------------------------------
# staging overflow: the spill path
# ---------------------------------------------------------------------------

def test_staging_overflow_spills_without_dropping():
  # staging_grps=2 is far below the per-step deduped cold rows, so every
  # step spills into a power-of-two bucket — parity must still hold
  cfg = TieringConfig(cache_fraction=0.3, staging_grps=2)
  losses_b, losses_t, w_b, w_t, trainer = _paired_runs(cfg)
  _assert_parity(losses_b, losses_t, w_b, w_t)
  m = trainer.metrics_summary()
  # most steps overflow the 2-row region (a fully-warmed step may not)
  assert m["spill_steps"] >= m["steps"] - 1 > 0
  assert all(v["missed"] == 0 for v in m["per_class"].values())


def test_spill_past_hard_cap_raises():
  # By construction a real batch always fits: the spill cap equals the
  # worst-case cold-row count (hard_cap - cache >= phys_rows - cache). To
  # exercise the never-drop guard, fake the impossible case — every row
  # cold while the cache claims most of the capacity.
  rule = sparse_rule("adagrad", 0.05)
  plan = _plan(1000)
  cfg = TieringConfig(cache_fraction=0.9, staging_grps=1, spill_factor_max=1)
  tplan = TieringPlan(plan, rule, cfg)
  store = HostTierStore(tplan)
  from distributed_embeddings_tpu.tiering import TieredPrefetcher
  pf = TieredPrefetcher(tplan, store)
  (c,) = tplan.classes.values()
  for r in range(WORLD):
    store.resident_map[c.name][r][:] = -1  # nothing resident
  cats = [np.arange(v, dtype=np.int32) for v in VOCAB]
  with pytest.raises(ValueError, match="cannot serve"):
    pf.stage(pf.classify(cats))


# ---------------------------------------------------------------------------
# promotion / eviction
# ---------------------------------------------------------------------------

def test_rerank_is_value_preserving():
  rule = sparse_rule("adagrad", 0.05)
  plan = _plan(1000)
  tplan = TieringPlan(plan, rule, TieringConfig(cache_fraction=0.2,
                                                staging_grps=8))
  store = HostTierStore(tplan)
  store.init_uniform(3)
  fused = store.build_fused()
  from distributed_embeddings_tpu.tiering import TieredPrefetcher
  pf = TieredPrefetcher(tplan, store)
  (c,) = tplan.classes.values()
  name = c.name
  before = {r: store.images[name][r].copy() for r in range(WORLD)}
  store.flush(fused)  # resident rows device values == image values here
  # rig the counts so the top of the table moves: high rows get traffic
  for r in range(WORLD):
    store.counts[name][r][-c.spec.cache_grps:] = 1000
  old_resident = [store.resident_grps[name][r].copy() for r in range(WORLD)]
  fused2 = pf.rerank(dict({name: fused[name]}), decay=False)
  moved = any(not np.array_equal(old_resident[r],
                                 store.resident_grps[name][r])
              for r in range(WORLD))
  assert moved
  # the global view (image ∪ cache) is unchanged by the re-rank
  store.flush(fused2)
  for r in range(WORLD):
    np.testing.assert_array_equal(store.images[name][r], before[r])
  # resident maps are consistent inverses
  for r in range(WORLD):
    rmap = store.resident_map[name][r]
    grps = store.resident_grps[name][r]
    assert np.array_equal(np.where(rmap >= 0)[0], np.sort(grps))


# ---------------------------------------------------------------------------
# checkpoint: save -> restore of a tiered plan
# ---------------------------------------------------------------------------

def test_tiered_checkpoint_roundtrip():
  vocab = VOCAB
  plan = _plan(1000)
  model = _model()
  mesh = create_mesh(WORLD)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adam(1e-3)
  cfg = TieringConfig(cache_fraction=0.3, staging_grps=64, rerank_interval=3)
  batch0 = _batch(100)

  def fresh(seed):
    tplan = TieringPlan(plan, rule, cfg)
    store = HostTierStore(tplan)
    params = model.init(jax.random.PRNGKey(0), batch0[0],
                        batch0[1])["params"]
    dense = {k: v for k, v in params.items() if k != "embeddings"}
    state = shard_params(
        init_tiered_state(tplan, store, rule, dense, opt,
                          jax.random.PRNGKey(seed), mesh=mesh), mesh)
    return tplan, store, TieredTrainer(model, tplan, store, bce_loss, opt,
                                       rule, mesh, state, batch0,
                                       donate=False)

  batches = [_batch(100 + i) for i in range(8)]
  _, _, tr_ref = fresh(7)
  losses_ref = tr_ref.run(batches)

  _, store_b, tr_b = fresh(7)
  losses_head = tr_b.run(batches[:4])
  tr_b.flush()
  ckpt = os.path.join(tempfile.mkdtemp(), "ck")
  try:
    checkpoint.save(ckpt, plan, rule, tr_b.state, store=store_b)
    files = set(os.listdir(ckpt))
    assert "tiering.npz" in files
    assert any(f.startswith("cold_") for f in files)
    # the tiered class's compact buffer must NOT be saved as a fused blob
    tiered = set(store_b.tplan.tier_specs)
    assert not any(f.startswith("fused_" + name) for name in tiered
                   for f in files)

    tplan_c = TieringPlan(plan, rule, cfg)
    store_c = HostTierStore(tplan_c)
    params = model.init(jax.random.PRNGKey(0), batch0[0],
                        batch0[1])["params"]
    dense = {k: v for k, v in params.items() if k != "embeddings"}
    state_like = init_tiered_state(tplan_c, store_c, rule, dense, opt,
                                   jax.random.PRNGKey(99), mesh=mesh)
    state_c = shard_params(
        checkpoint.restore(ckpt, plan, rule, state_like, mesh=mesh,
                           store=store_c), mesh)
    tr_c = TieredTrainer(model, tplan_c, store_c, bce_loss, opt, rule, mesh,
                         state_c, batch0, donate=False)
    losses_tail = tr_c.run(batches[4:])
    np.testing.assert_allclose(losses_ref, losses_head + losses_tail,
                               rtol=0, atol=0)

    # geometry mismatch (different cache sizing) must fail loudly
    bad = TieringPlan(plan, rule, TieringConfig(cache_fraction=0.2,
                                                staging_grps=64))
    with pytest.raises(ValueError, match="tier geometry"):
      checkpoint.restore(ckpt, plan, rule, state_like, mesh=mesh,
                         store=HostTierStore(bad))
    # restoring a tiered checkpoint without its store must fail loudly
    with pytest.raises(ValueError, match="tiering mismatch"):
      checkpoint.restore(ckpt, plan, rule, state_like, mesh=mesh)
  finally:
    shutil.rmtree(os.path.dirname(ckpt))
