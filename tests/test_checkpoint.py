"""Checkpoint/resume tests (full fused train state).

The reference has no optimizer-state or step checkpointing (SURVEY §5);
this subsystem snapshots everything, so the key property is bit-exact
resume: train k steps, save, restore, train k more == train 2k straight.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu import checkpoint
from distributed_embeddings_tpu.layers import TableConfig
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM, bce_loss
from distributed_embeddings_tpu.ops.packed_table import adagrad_rule, sgd_rule
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.training import (
    init_sparse_state,
    make_sparse_train_step,
    shard_batch,
    shard_params,
)

WORLD = 8
VOCAB = [300, 200, 150, 120, 100, 80, 60, 40, 30, 20]


def build(world, rule_name="adagrad"):
  model = DLRM(vocab_sizes=VOCAB, embedding_dim=16, bottom_mlp=(32, 16),
               top_mlp=(32, 1), world_size=world, dense_row_threshold=32)
  plan = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=16,
            initializer={"name": "uniform", "scale": 0.05}) for v in VOCAB],
      world, "basic", dense_row_threshold=32)
  from distributed_embeddings_tpu.ops.packed_table import sparse_rule
  rule = sparse_rule(rule_name, 0.05)
  opt = {"adagrad": lambda: optax.adagrad(0.05),
         "sgd": lambda: optax.sgd(0.05),
         "momentum": lambda: optax.sgd(0.05, momentum=0.9),
         "adam": lambda: optax.adam(0.05)}[rule_name]()
  return model, plan, rule, opt


def make_batch(world, seed=0):
  rng = np.random.default_rng(seed)
  b = 4 * world
  numerical = jnp.asarray(rng.standard_normal((b, 13)), jnp.float32)
  cats = [jnp.asarray(rng.integers(0, v, b).astype(np.int32)) for v in VOCAB]
  labels = jnp.asarray(rng.integers(0, 2, b).astype(np.float32))
  return numerical, cats, labels


def init_state(model, plan, rule, opt, batch, mesh=None):
  numerical, cats, _ = batch
  params = model.init(jax.random.PRNGKey(0), numerical, cats)["params"]
  state = init_sparse_state(plan, params, rule, opt)
  if mesh is not None:
    state = shard_params(state, mesh)
  return state


@pytest.mark.parametrize("use_mesh,rule_name",
                         [(False, "adagrad"), (True, "adagrad"),
                          (True, "sgd"), (True, "adam"),
                          (False, "momentum")])
def test_save_restore_resume_bit_exact(tmp_path, use_mesh, rule_name):
  world = WORLD if use_mesh else 1
  mesh = create_mesh(world) if use_mesh else None
  model, plan, rule, opt = build(world, rule_name)
  batch = make_batch(world)
  state = init_state(model, plan, rule, opt, batch, mesh)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, batch, donate=False)
  sb = shard_batch(batch, mesh) if mesh is not None else batch

  # straight run: 4 steps
  s = state
  for _ in range(4):
    s, _ = step(s, *sb)
  straight = jax.device_get(s)

  # interrupted run: 2 steps, save, restore, 2 more
  s = state
  for _ in range(2):
    s, _ = step(s, *sb)
  path = os.path.join(tmp_path, "ckpt")
  checkpoint.save(path, plan, rule, s)
  restored = checkpoint.restore(path, plan, rule, s, mesh=mesh)
  assert int(jax.device_get(restored["step"])) == 2
  for _ in range(2):
    restored, _ = step(restored, *sb)
  resumed = jax.device_get(restored)

  flat_a = jax.tree_util.tree_leaves(straight)
  flat_b = jax.tree_util.tree_leaves(resumed)
  assert len(flat_a) == len(flat_b)
  for a, b in zip(flat_a, flat_b):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_rejects_wrong_rule(tmp_path):
  model, plan, rule, opt = build(1)
  batch = make_batch(1)
  state = init_state(model, plan, rule, opt, batch)
  path = os.path.join(tmp_path, "ckpt")
  checkpoint.save(path, plan, rule, state)
  with pytest.raises(ValueError, match="rule"):
    checkpoint.restore(path, plan, sgd_rule(0.05), state)


def test_restore_rejects_wrong_plan(tmp_path):
  model, plan, rule, opt = build(1)
  batch = make_batch(1)
  state = init_state(model, plan, rule, opt, batch)
  path = os.path.join(tmp_path, "ckpt")
  checkpoint.save(path, plan, rule, state)
  other = DistEmbeddingStrategy(
      [dict(input_dim=v + 1, output_dim=16) for v in VOCAB], 1, "basic")
  with pytest.raises(ValueError, match="plan"):
    checkpoint.restore(path, other, rule, state)


def test_save_is_atomic_and_keeps_backup(tmp_path):
  model, plan, rule, opt = build(1)
  batch = make_batch(1)
  state = init_state(model, plan, rule, opt, batch)
  path = os.path.join(tmp_path, "ckpt")
  checkpoint.save(path, plan, rule, state)
  first_manifest = open(os.path.join(path, "manifest.json")).read()
  # second save replaces, keeps .old
  checkpoint.save(path, plan, rule, state)
  assert os.path.isdir(path + ".old")
  assert open(os.path.join(path + ".old",
                           "manifest.json")).read() == first_manifest
