"""Pallas TPU lookup kernel tests (interpreter mode on the CPU mesh).

The kernel (`distributed_embeddings_tpu/ops/pallas_lookup.py`) is the
TPU-native counterpart of the reference's fused CUDA lookup
(`/root/reference/distributed_embeddings/cc/kernels/embedding_lookup_kernels.cu`);
these tests mirror the reference op tests' numerical-equivalence strategy
(`python/ops/embedding_lookup_ops_test.py:22-115`): the fused kernel must
match the composed XLA ops, forward and backward.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.ops.pallas_lookup import (
    choose_tile_b,
    multihot_lookup,
)


def _ref(params, ids, combiner, mode):
  params = np.asarray(params)
  ids = np.asarray(ids)
  v = params.shape[0]
  if mode == "clip":
    ids = np.clip(ids, 0, v - 1)
  valid = (ids >= 0) & (ids < v)
  rows = params[np.clip(ids, 0, v - 1)] * valid[..., None]
  out = rows.sum(1)
  if combiner == "mean":
    out = out / np.maximum(valid.sum(1), 1)[:, None]
  return out.astype(np.float32)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
@pytest.mark.parametrize("mode", ["drop", "clip"])
def test_forward_matches_reference(combiner, mode):
  rng = np.random.default_rng(0)
  v, d, b, h = 50, 16, 21, 3
  params = jnp.asarray(rng.standard_normal((v, d)), jnp.float32)
  ids = jnp.asarray(rng.integers(-3, v + 3, (b, h)).astype(np.int32))
  out = multihot_lookup(params, ids, combiner, mode=mode, tile_b=8,
                        interpret=True)
  np.testing.assert_allclose(np.asarray(out), _ref(params, ids, combiner, mode),
                             rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("width", [8, 16, 128, 130])
def test_widths(width):
  rng = np.random.default_rng(1)
  v, b = 40, 17
  params = jnp.asarray(rng.standard_normal((v, width)), jnp.float32)
  ids = jnp.asarray(rng.integers(0, v, (b, 1)).astype(np.int32))
  out = multihot_lookup(params, ids, "sum", tile_b=8, interpret=True)
  np.testing.assert_allclose(np.asarray(out), _ref(params, ids, "sum", "drop"),
                             rtol=1e-5, atol=1e-5)


def test_hotness_one_fast_path_and_padding():
  rng = np.random.default_rng(2)
  v, d = 30, 8
  params = jnp.asarray(rng.standard_normal((v, d)), jnp.float32)
  for b in (5, 8, 9):  # unaligned batches exercise sentinel padding
    ids = jnp.asarray(rng.integers(0, v, (b, 1)).astype(np.int32))
    out = multihot_lookup(params, ids, "sum", tile_b=8, interpret=True)
    assert out.shape == (b, d)
    np.testing.assert_allclose(np.asarray(out),
                               _ref(params, ids, "sum", "drop"),
                               rtol=1e-5, atol=1e-5)


def test_bf16_params():
  rng = np.random.default_rng(3)
  v, d, b, h = 32, 16, 16, 2
  params = jnp.asarray(rng.standard_normal((v, d)), jnp.bfloat16)
  ids = jnp.asarray(rng.integers(0, v, (b, h)).astype(np.int32))
  out = multihot_lookup(params, ids, "sum", tile_b=8, interpret=True)
  assert out.dtype == jnp.bfloat16
  ref = _ref(params.astype(jnp.float32), ids, "sum", "drop")
  np.testing.assert_allclose(np.asarray(out.astype(jnp.float32)), ref,
                             rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_grad_matches_xla_autodiff(combiner):
  rng = np.random.default_rng(4)
  v, d, b, h = 25, 8, 12, 3
  params = jnp.asarray(rng.standard_normal((v, d)), jnp.float32)
  # include duplicates and invalid ids
  ids = jnp.asarray(rng.integers(-2, v + 2, (b, h)).astype(np.int32))
  cot = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)

  def pallas_loss(p):
    out = multihot_lookup(p, ids, combiner, mode="drop", tile_b=8,
                          interpret=True)
    return jnp.vdot(out, cot)

  def xla_loss(p):
    valid = ((ids >= 0) & (ids < v)).astype(p.dtype)
    rows = jnp.take(p, jnp.clip(ids, 0, v - 1), axis=0) * valid[..., None]
    out = rows.sum(1)
    if combiner == "mean":
      out = out / jnp.maximum(valid.sum(1), 1)[:, None]
    return jnp.vdot(out, cot)

  g_pallas = jax.grad(pallas_loss)(params)
  g_xla = jax.grad(xla_loss)(params)
  np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_xla),
                             rtol=1e-4, atol=1e-5)


def test_grad_clip_mode_matches_embedding_lookup():
  from distributed_embeddings_tpu.ops import embedding_lookup

  rng = np.random.default_rng(5)
  v, d, b, h = 19, 8, 10, 4
  params = jnp.asarray(rng.standard_normal((v, d)), jnp.float32)
  ids = jnp.asarray(rng.integers(0, v, (b, h)).astype(np.int32))

  def pallas_loss(p):
    return multihot_lookup(p, ids, "sum", mode="clip", tile_b=8,
                           interpret=True).sum()

  def xla_loss(p):
    return embedding_lookup(p, ids, combiner="sum").sum()

  np.testing.assert_allclose(np.asarray(jax.grad(pallas_loss)(params)),
                             np.asarray(jax.grad(xla_loss)(params)),
                             rtol=1e-4, atol=1e-5)


def test_choose_tile_b_bounds():
  assert choose_tile_b(1024, 1, 128, jnp.float32) % 8 == 0
  assert 8 <= choose_tile_b(7, 1, 8, jnp.float32) <= 512
  # huge hotness*width shrinks the tile to respect the VMEM budget
  big = choose_tile_b(65536, 200, 256, jnp.float32)
  assert big * 200 * 256 * 4 <= 4 * 1024 * 1024


def test_bad_args_raise():
  params = jnp.zeros((4, 8))
  ids = jnp.zeros((4, 1), jnp.int32)
  with pytest.raises(ValueError):
    multihot_lookup(params, ids, "max", interpret=True)
  with pytest.raises(ValueError):
    multihot_lookup(params, ids, "sum", mode="wrap", interpret=True)
