"""Sim/TPU kernel lockstep: the shared golden vectors, run on CPU.

One parametrized case list (`tests/pallas_goldens.py`) drives both twin
pairs — the SAME streams the real-TPU smoke replays against the
hardware builds (`tools/smoke_pallas_apply.py`), replacing the ad-hoc
per-file vectors each test used to invent:

- apply pair: `ops/pallas_apply_sim.apply_rows_cached_sim` vs
  ``np.add.at`` at the documented f32 tolerance (the cache combines a
  row's duplicate deltas in VMEM before the single add — an
  associativity reordering, so bitwise equality is not the claim);
- exchange pair: `ops/pallas_exchange_sim` (the REAL kernel body under
  Pallas interpret mode) vs ``packed_table.gather_fused`` BIT-for-bit —
  a gather is pure data movement, nothing to forgive.
"""

import numpy as np
import pytest

from distributed_embeddings_tpu.ops.packed_table import (
    PackedLayout,
    gather_fused,
)
from distributed_embeddings_tpu.ops.pallas_apply_sim import (
    apply_rows_cached_sim,
)
from distributed_embeddings_tpu.ops.pallas_exchange_sim import (
    gather_rows_sim,
    gather_send_rows_sim,
)

from pallas_goldens import (
    CASE_NAMES,
    apply_vectors,
    exchange_vectors,
)

import jax.numpy as jnp


@pytest.mark.parametrize("name", CASE_NAMES)
def test_apply_pair_golden(name):
  buf, ids, delta, slots, _ = apply_vectors(name)
  got = apply_rows_cached_sim(buf, ids.astype(np.int64), delta,
                              slots=slots)
  want = np.array(buf, np.float32)
  ok = (ids >= 0) & (ids < buf.shape[0])
  np.add.at(want, ids[ok], delta[ok])
  np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5,
                             err_msg=name)


@pytest.mark.parametrize("name", CASE_NAMES)
def test_exchange_pair_golden_bitexact(name):
  buf, ids, chunk = exchange_vectors(name)
  layout = PackedLayout(rows=buf.shape[0], width=buf.shape[1])
  assert layout.rows_per_phys == 1 and layout.stride == buf.shape[1]
  jbuf, jids = jnp.asarray(buf), jnp.asarray(ids)
  want = np.asarray(gather_fused(layout, jbuf, jids))
  got = np.asarray(gather_rows_sim(layout, jbuf, jids, chunk=chunk))
  np.testing.assert_array_equal(got, want, err_msg=name)


@pytest.mark.parametrize("name", CASE_NAMES[:4])
def test_exchange_send_golden_loopback(name):
  """The full gather->send body (loopback transport): the received
  buffer equals the gathered rows bit-for-bit."""
  buf, ids, chunk = exchange_vectors(name)
  layout = PackedLayout(rows=buf.shape[0], width=buf.shape[1])
  jbuf, jids = jnp.asarray(buf), jnp.asarray(ids)
  want = np.asarray(gather_fused(layout, jbuf, jids))
  got = np.asarray(gather_send_rows_sim(jbuf, jids, chunk=chunk))
  np.testing.assert_array_equal(got, want, err_msg=name)


def test_exchange_kernel_rejects_unserved_layouts():
  """The kernel's validation mirrors its TPU limits: narrow (rpp > 1)
  layouts, non-f32 buffers and non-128-lane rows go to the XLA path."""
  from distributed_embeddings_tpu.ops import pallas_exchange as pe
  buf = jnp.zeros((8, 128), jnp.float32)
  ids = jnp.zeros((4,), jnp.int32)
  narrow = PackedLayout(rows=8, width=16)
  with pytest.raises(ValueError, match="rows_per_phys"):
    pe.gather_rows(narrow, jnp.zeros(narrow.shape, jnp.float32), ids)
  wide = PackedLayout(rows=8, width=128)
  with pytest.raises(ValueError, match="float32"):
    pe.gather_rows(wide, buf.astype(jnp.bfloat16), ids)
  with pytest.raises(ValueError, match="128"):
    pe.gather_rows(wide, jnp.zeros((8, 256), jnp.float32), ids)


def test_exchange_gate_off_on_cpu(monkeypatch):
  """Both gate directions on the CPU proxy: unset -> off; forced on ->
  still off (no TPU backend), so tier-1 never lowers the kernel."""
  from distributed_embeddings_tpu.ops import pallas_exchange as pe
  monkeypatch.delenv("DE_TPU_PALLAS_EXCHANGE", raising=False)
  assert pe._use_pallas_exchange() is False
  monkeypatch.setenv("DE_TPU_PALLAS_EXCHANGE", "1")
  assert pe._use_pallas_exchange() is False  # CPU backend
  monkeypatch.setenv("DE_TPU_PALLAS_EXCHANGE", "0")
  assert pe._use_pallas_exchange() is False
