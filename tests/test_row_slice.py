"""Row slicing (vocab-dim sharding) tests.

The reference stubs row slicing (`/root/reference/distributed_embeddings/
python/layers/dist_model_parallel.py:364-365` raises NotImplementedError);
this build implements it. Parity model: same-weights naive gather (the
pattern of `tests/dist_model_parallel_test.py:157-192`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from distributed_embeddings_tpu.compat import shard_map

from distributed_embeddings_tpu.layers import TableConfig
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    get_weights,
    set_weights,
)
from distributed_embeddings_tpu.layers.planner import (
    DistEmbeddingStrategy,
    slice_rows,
)
from distributed_embeddings_tpu.parallel.lookup_engine import (
    PAD_ID,
    DistributedLookup,
)

WORLD = 8


def make_mesh():
  return Mesh(np.asarray(jax.devices()[:WORLD]), ("mp",))


def rs_plan(configs, threshold, world=WORLD, strategy="basic"):
  return DistEmbeddingStrategy(configs, world, strategy,
                               row_slice_threshold=threshold)


def naive(weights, table_of, inputs, combiners):
  outs = []
  for i, t in enumerate(table_of):
    w, ids = weights[t], np.asarray(inputs[i])
    if ids.ndim == 1:
      outs.append(w[np.clip(ids, 0, w.shape[0] - 1)]
                  * (ids >= 0)[:, None])
      continue
    valid = ids >= 0
    rows = np.where(valid[..., None], w[np.clip(ids, 0, w.shape[0] - 1)], 0.0)
    s = rows.sum(axis=1)
    if combiners[t] == "mean":
      s = s / np.maximum(valid.sum(axis=1), 1)[:, None]
    outs.append(s)
  return outs


# ---- planner ---------------------------------------------------------------


def test_slice_rows_pow2_split_with_remainder():
  cfg = TableConfig(input_dim=103, output_dim=8)
  ranges = slice_rows(cfg, 30 * 8, 8)
  assert len(ranges) == 4  # smallest pow2 with 103*8/N <= 240
  rows = [e - s for s, e in ranges]
  assert sum(rows) == 103 and max(rows) - min(rows) <= 1
  assert ranges[0][0] == 0 and ranges[-1][1] == 103


def test_row_sliced_shards_cover_vocab_once():
  configs = [TableConfig(input_dim=100 if i % 2 == 0 else 40, output_dim=8)
             for i in range(8)]
  plan = rs_plan(configs, threshold=25 * 8)
  for t, cfg in enumerate(configs):
    covered = []
    for _, sh in plan.table_shard_map(t):
      assert sh.row_sliced
      covered.append((sh.row_start, sh.row_start + sh.input_dim))
    covered.sort()
    assert covered[0][0] == 0 and covered[-1][1] == cfg.input_dim
    for (a, b), (c, d) in zip(covered, covered[1:]):
      assert b == c  # contiguous, non-overlapping


def test_column_slicing_wins_over_row_slicing():
  configs = [TableConfig(input_dim=64, output_dim=8) for _ in range(2)]
  plan = DistEmbeddingStrategy(configs, 4, "basic",
                               column_slice_threshold=16 * 8,
                               row_slice_threshold=16 * 8)
  assert not any(sh.row_sliced for shards in plan.rank_shards
                 for sh in shards)
  assert all(len(r) > 1 for r in plan.table_col_ranges)


# ---- forward parity on the mesh -------------------------------------------


@pytest.mark.parametrize("combiner,hot", [(None, 1), ("sum", 3), ("mean", 3)])
def test_row_sliced_forward_parity(combiner, hot):
  rng = np.random.default_rng(3)
  sizes = [96, 64, 48, 40, 88, 56, 72, 104]
  configs = [TableConfig(input_dim=s, output_dim=8, combiner=combiner)
             for s in sizes]
  plan = rs_plan(configs, threshold=16 * 8)
  assert any(sh.row_sliced for shards in plan.rank_shards for sh in shards)
  weights = [rng.standard_normal((s, 8)).astype(np.float32) for s in sizes]
  params = {k: jnp.asarray(v) for k, v in set_weights(plan, weights).items()}

  b = 2 * WORLD
  if hot == 1:
    inputs = [jnp.asarray(rng.integers(0, s, b).astype(np.int32))
              for s in sizes]
  else:
    ids = [rng.integers(0, s, (b, hot)).astype(np.int32) for s in sizes]
    for x in ids:  # sprinkle PADs to exercise valid-count handling
      x[rng.random(x.shape) < 0.25] = PAD_ID
    inputs = [jnp.asarray(x) for x in ids]

  engine = DistributedLookup(plan)
  mesh = make_mesh()
  pspecs = {n: P("mp", None) for n in params}

  def fwd(params, *xs):
    return tuple(engine.forward(params, list(xs)))

  out = jax.jit(shard_map(
      fwd, mesh=mesh,
      in_specs=(pspecs,) + tuple(P("mp") for _ in inputs),
      out_specs=tuple(P("mp") for _ in inputs)))(params, *inputs)
  want = naive(weights, list(range(len(sizes))),
               [np.asarray(x) for x in inputs],
               [combiner] * len(sizes))
  for o, w in zip(out, want):
    np.testing.assert_allclose(np.asarray(o), w, rtol=1e-5, atol=1e-5)


def test_row_sliced_weights_roundtrip():
  rng = np.random.default_rng(5)
  sizes = [128, 96, 64, 80, 112, 144, 72, 56]
  configs = [TableConfig(input_dim=s, output_dim=4) for s in sizes]
  plan = rs_plan(configs, threshold=20 * 4, strategy="memory_balanced")
  weights = [rng.standard_normal((s, 4)).astype(np.float32) for s in sizes]
  params = set_weights(plan, weights)
  back = get_weights(plan, params)
  for a, b in zip(weights, back):
    np.testing.assert_array_equal(a, b)


def test_row_sliced_out_of_vocab_clamps_like_unsliced():
  """Ids >= vocab clamp to the last table row, exactly as without row
  slicing — a sharding knob must not change numerics."""
  rng = np.random.default_rng(6)
  sizes = [64] * 8
  configs = [TableConfig(input_dim=s, output_dim=8, combiner="sum")
             for s in sizes]
  plan = rs_plan(configs, threshold=16 * 8)
  assert any(sh.row_sliced for shards in plan.rank_shards for sh in shards)
  weights = [rng.standard_normal((s, 8)).astype(np.float32) for s in sizes]
  params = {k: jnp.asarray(v) for k, v in set_weights(plan, weights).items()}
  engine = DistributedLookup(plan)
  mesh = make_mesh()
  b = WORLD
  oov = [jnp.full((b, 2), 1000, jnp.int32) for _ in sizes]
  pspecs = {n: P("mp", None) for n in params}

  def fwd(params, *xs):
    return tuple(engine.forward(params, list(xs)))

  out = jax.jit(shard_map(
      fwd, mesh=mesh, in_specs=(pspecs,) + tuple(P("mp") for _ in oov),
      out_specs=tuple(P("mp") for _ in oov)))(params, *oov)
  for t, o in enumerate(out):
    want = np.broadcast_to(2 * weights[t][-1], (b, 8))  # 2-hot of last row
    np.testing.assert_allclose(np.asarray(o), want, rtol=1e-5)


def test_negative_slice_threshold_raises():
  with pytest.raises(ValueError, match="positive"):
    rs_plan([TableConfig(input_dim=64, output_dim=8)] * 8, threshold=-1)


# ---- sparse training path --------------------------------------------------


@pytest.mark.parametrize("combiner,hot,rule_name",
                         [("sum", 3, "adagrad"), ("mean", 3, "sgd"),
                          (None, 1, "sgd")])
def test_row_sliced_sparse_training_matches_unsliced(combiner, hot,
                                                     rule_name):
  """One fused train step over row-sliced tables must move the global
  weights exactly like the unsliced plan (same model, same batch)."""
  from distributed_embeddings_tpu.ops.packed_table import sparse_rule
  from distributed_embeddings_tpu.training import (
      init_sparse_state,
      make_sparse_train_step,
      shard_batch,
      shard_params,
      unpack_sparse_state,
  )
  import flax.linen as nn

  from distributed_embeddings_tpu.layers.dist_model_parallel import (
      DistributedEmbedding,
  )

  rng = np.random.default_rng(9)
  sizes = [96, 64, 48, 40, 32, 24, 16, 8]
  b = 2 * WORLD

  def run(threshold):
    configs = tuple(TableConfig(input_dim=s, output_dim=8, combiner=combiner)
                    for s in sizes)

    class Tiny(nn.Module):
      @nn.compact
      def __call__(self, numerical, cats, emb_acts=None):
        outs = emb_acts if emb_acts is not None else DistributedEmbedding(
            embeddings=configs, world_size=WORLD, row_slice=threshold,
            name="embeddings")(cats)
        x = jnp.concatenate(list(outs) + [numerical], axis=1)
        return jnp.squeeze(nn.Dense(1)(x), -1)

    plan = DistEmbeddingStrategy(list(configs), WORLD,
                                 row_slice_threshold=threshold)
    model = Tiny()
    rng2 = np.random.default_rng(11)  # same draws for both runs
    numerical = jnp.asarray(rng2.standard_normal((b, 4)), jnp.float32)
    if hot == 1:
      cats = [jnp.asarray(rng2.integers(0, s, b).astype(np.int32))
              for s in sizes]
    else:
      raw = [rng2.integers(0, s, (b, hot)).astype(np.int32) for s in sizes]
      for x in raw:
        x[rng2.random(x.shape) < 0.25] = PAD_ID
      cats = [jnp.asarray(x) for x in raw]
    labels = jnp.asarray(rng2.integers(0, 2, b), jnp.float32)

    weights = [rng.standard_normal((s, 8)).astype(np.float32) for s in sizes]
    emb_params = {k: jnp.asarray(v)
                  for k, v in set_weights(plan, weights).items()}
    dummy_acts = [jnp.zeros((b, 8), jnp.float32) for _ in sizes]
    dense = model.init(jax.random.PRNGKey(0), numerical, cats,
                       emb_acts=dummy_acts)["params"]
    params = {**dense, "embeddings": emb_params}

    rule = sparse_rule(rule_name, 0.1)
    opt = optax.sgd(0.1)
    mesh = make_mesh()
    state = init_sparse_state(plan, params, rule, opt)
    state = shard_params(state, mesh)

    def loss_fn(logits, lbl):
      return optax.sigmoid_binary_cross_entropy(logits, lbl).mean()

    step = make_sparse_train_step(model, plan, loss_fn, opt, rule, mesh,
                                  state, (numerical, cats, labels))
    sb = shard_batch((numerical, cats, labels), mesh)
    state, loss = step(state, *sb)
    new_params, _ = unpack_sparse_state(plan, rule, state)
    return float(loss), get_weights(plan, new_params["embeddings"])

  # rng reused across runs -> reseed before each
  rng = np.random.default_rng(9)
  loss_rs, w_rs = run(threshold=16 * 8)  # forces row slicing
  rng = np.random.default_rng(9)
  loss_ref, w_ref = run(threshold=None)  # unsliced
  assert np.isclose(loss_rs, loss_ref, rtol=1e-5)
  for a, b_ in zip(w_rs, w_ref):
    np.testing.assert_allclose(a, b_, rtol=1e-4, atol=1e-5)
