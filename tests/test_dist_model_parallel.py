"""Distributed integration tests on a virtual 8-device mesh.

Core pattern mirrors the reference
(`/root/reference/tests/dist_model_parallel_test.py:157-192`): build a
non-distributed reference model and the distributed model, load identical
global weights, run forward + one SGD step on both, and assert forward
outputs equal and post-update weights allclose. The mesh is 8 virtual CPU
devices (conftest) — the fake-backend capability the reference lacks.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from distributed_embeddings_tpu.compat import shard_map

from distributed_embeddings_tpu.layers import TableConfig
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    get_weights,
    set_weights,
)
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.parallel import (
    DistributedLookup,
    class_param_name,
    pack_mp_inputs,
    ragged_to_padded,
)
from distributed_embeddings_tpu.ops import RaggedIds


WORLD = 8


def make_mesh(world=WORLD):
  return Mesh(np.asarray(jax.devices()[:world]), ("mp",))


def param_specs(plan):
  return {class_param_name(*k): P("mp", None) for k in plan.class_keys}


def gen_weights(rng, configs):
  return [rng.standard_normal((c.input_dim, c.output_dim)).astype(np.float32)
          for c in configs]


def reference_forward(weights, input_table_map, inputs_np, combiners):
  """Naive single-process model: plain gather + combine per input."""
  outs = []
  for i, t in enumerate(input_table_map):
    w, ids = weights[t], inputs_np[i]
    if ids.ndim == 1:
      outs.append(w[ids])
      continue
    rows = np.where(ids[..., None] >= 0, w[np.clip(ids, 0, w.shape[0] - 1)], 0.0)
    if combiners[t] == "sum" or combiners[t] is None:
      out = rows.sum(1) if combiners[t] == "sum" else rows[:, 0]
    else:
      counts = np.maximum((ids >= 0).sum(1), 1)
      out = rows.sum(1) / counts[:, None]
    outs.append(out.astype(np.float32))
  return outs


def dist_forward_fn(plan, dp_input=True):
  engine = DistributedLookup(plan, dp_input=dp_input, axis_name="mp")

  def fn(class_params, *inputs):
    if dp_input:
      return tuple(engine.forward(class_params, list(inputs)))
    return tuple(engine.forward_mp(class_params, inputs[0]))

  return fn


def run_parity(table_sizes, width=8, world=WORLD, strategy="basic",
               input_table_map=None, column_slice_threshold=None,
               combiner=None, hotness=None, seed=0):
  """Forward + train-step parity: distributed vs naive reference."""
  rng = np.random.default_rng(seed)
  configs = [TableConfig(input_dim=s, output_dim=width, combiner=combiner)
             for s in table_sizes]
  plan = DistEmbeddingStrategy(configs, world, strategy,
                               input_table_map=input_table_map,
                               column_slice_threshold=column_slice_threshold)
  table_map = plan.input_table_map
  weights = gen_weights(rng, configs)
  class_params = {k: jnp.asarray(v)
                  for k, v in set_weights(plan, weights).items()}

  batch = 2 * world
  inputs_np = []
  for t in table_map:
    if hotness is None:
      inputs_np.append(
          rng.integers(0, table_sizes[t], size=batch).astype(np.int32))
    else:
      ids = rng.integers(0, table_sizes[t], size=(batch, hotness)).astype(np.int32)
      # make hotness ragged via PAD_ID in a few slots
      mask = rng.random((batch, hotness)) < 0.25
      mask[:, 0] = False  # at least one valid id
      ids[mask] = -1
      inputs_np.append(ids)
  inputs = [jnp.asarray(x) for x in inputs_np]

  mesh = make_mesh(world)
  fn = dist_forward_fn(plan)
  specs_in = (param_specs(plan),) + tuple(P("mp") for _ in inputs)
  n_out = len(table_map)
  fwd = jax.jit(shard_map(fn, mesh=mesh, in_specs=specs_in,
                          out_specs=tuple(P("mp") for _ in range(n_out))))
  got = fwd(class_params, *inputs)

  combiners = [combiner] * len(configs)
  want = reference_forward(weights, table_map, inputs_np, combiners)
  for i, (g, w) in enumerate(zip(got, want)):
    np.testing.assert_allclose(np.asarray(g), w, rtol=1e-5, atol=1e-5,
                               err_msg=f"forward mismatch on input {i}")

  # ---- one SGD step parity ----
  def local_loss(class_params, *inputs):
    outs = fn(class_params, *inputs)
    return sum(jnp.sum(o ** 2) for o in outs)

  grad_fn = jax.jit(
      shard_map(jax.grad(local_loss), mesh=mesh, in_specs=specs_in,
                out_specs=param_specs(plan)))
  grads = grad_fn(class_params, *inputs)
  lr = 0.1
  new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g, class_params,
                                      grads)
  got_weights = get_weights(plan, new_params)

  def ref_loss(weights_list):
    outs = []
    for i, t in enumerate(table_map):
      w, ids = weights_list[t], jnp.asarray(inputs_np[i])
      if ids.ndim == 1:
        outs.append(jnp.take(w, ids, axis=0, mode="clip"))
      else:
        rows = jnp.where((ids >= 0)[..., None],
                         jnp.take(w, jnp.clip(ids, 0, w.shape[0] - 1),
                                  axis=0, mode="clip"), 0.0)
        if combiner == "mean":
          counts = jnp.maximum((ids >= 0).sum(1), 1).astype(jnp.float32)
          outs.append(rows.sum(1) / counts[:, None])
        else:
          outs.append(rows.sum(1))
    return sum(jnp.sum(o ** 2) for o in outs)

  ref_grads = jax.grad(ref_loss)([jnp.asarray(w) for w in weights])
  want_weights = [np.asarray(w) - lr * np.asarray(g)
                  for w, g in zip(weights, ref_grads)]
  for t, (g, w) in enumerate(zip(got_weights, want_weights)):
    np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5,
                               err_msg=f"post-update weight mismatch table {t}")
  return plan


@pytest.mark.parametrize("strategy",
                         ["basic", "memory_balanced", "memory_optimized"])
def test_parity_each_strategy(strategy):
  rng = np.random.default_rng(1)
  sizes = rng.integers(16, 200, size=13).tolist()
  run_parity(sizes, strategy=strategy, seed=2)


def test_parity_single_table_many_workers_auto_slice():
  # fewer tables than workers -> auto column slicing must cover all 8 ranks
  plan = run_parity([300, 50], width=16, seed=3)
  assert all(plan.rank_shards)


def test_parity_explicit_column_slice():
  plan = run_parity([512, 30, 40], width=16, seed=4,
                    column_slice_threshold=1024)
  assert len(plan.output_pieces[0]) > 1  # table 0 actually sliced


def test_parity_shared_tables():
  # 3 inputs share 2 tables (reference `tests/dist_model_parallel_test.py:238-285`)
  run_parity([64, 96], input_table_map=[0, 0, 1], seed=5)


def test_parity_multi_hot_sum():
  run_parity([64, 80, 96], combiner="sum", hotness=5, seed=6)


def test_parity_multi_hot_mean():
  run_parity([64, 80, 96], combiner="mean", hotness=4, seed=7)


def test_parity_mixed_widths():
  rng = np.random.default_rng(8)
  configs = [TableConfig(input_dim=int(s), output_dim=w)
             for s, w in [(50, 4), (60, 8), (70, 4), (80, 8), (90, 16),
                          (100, 4), (110, 8), (120, 16), (130, 4)]]
  plan = DistEmbeddingStrategy(configs, WORLD, "memory_balanced")
  assert len(plan.class_keys) >= 2
  weights = gen_weights(rng, configs)
  class_params = {k: jnp.asarray(v)
                  for k, v in set_weights(plan, weights).items()}
  batch = 16
  inputs_np = [rng.integers(0, c.input_dim, batch).astype(np.int32)
               for c in configs]
  mesh = make_mesh()
  fn = dist_forward_fn(plan)
  fwd = jax.jit(shard_map(
      fn, mesh=mesh,
      in_specs=(param_specs(plan),) + tuple(P("mp") for _ in inputs_np),
      out_specs=tuple(P("mp") for _ in inputs_np)))
  got = fwd(class_params, *[jnp.asarray(x) for x in inputs_np])
  want = reference_forward(weights, plan.input_table_map, inputs_np,
                           [None] * len(configs))
  for g, w in zip(got, want):
    np.testing.assert_allclose(np.asarray(g), w, rtol=1e-5, atol=1e-5)


def test_world_one_no_collectives():
  rng = np.random.default_rng(9)
  configs = [TableConfig(input_dim=40, output_dim=8),
             TableConfig(input_dim=50, output_dim=8)]
  plan = DistEmbeddingStrategy(configs, 1)
  weights = gen_weights(rng, configs)
  class_params = {k: jnp.asarray(v)
                  for k, v in set_weights(plan, weights).items()}
  engine = DistributedLookup(plan)
  inputs_np = [rng.integers(0, 40, 6).astype(np.int32),
               rng.integers(0, 50, 6).astype(np.int32)]
  outs = engine.forward(class_params, [jnp.asarray(x) for x in inputs_np])
  want = reference_forward(weights, [0, 1], inputs_np, [None, None])
  for g, w in zip(outs, want):
    np.testing.assert_allclose(np.asarray(g), w, rtol=1e-6)


def test_mp_input_mode_matches_dp():
  rng = np.random.default_rng(10)
  sizes = [48, 64, 80, 96, 112]
  configs = [TableConfig(input_dim=s, output_dim=8) for s in sizes]
  plan = DistEmbeddingStrategy(configs, WORLD, "basic")
  weights = gen_weights(rng, configs)
  class_params = {k: jnp.asarray(v)
                  for k, v in set_weights(plan, weights).items()}
  batch = 2 * WORLD
  inputs_np = [rng.integers(0, s, batch).astype(np.int32) for s in sizes]
  mesh = make_mesh()

  # dp path
  fn_dp = dist_forward_fn(plan)
  fwd_dp = jax.jit(shard_map(
      fn_dp, mesh=mesh,
      in_specs=(param_specs(plan),) + tuple(P("mp") for _ in sizes),
      out_specs=tuple(P("mp") for _ in sizes)))
  dp_out = fwd_dp(class_params, *[jnp.asarray(x) for x in inputs_np])

  # mp-input path: each rank gets its local inputs over the GLOBAL batch
  per_rank_inputs = [
      [jnp.asarray(inputs_np[i]) for i in plan.input_ids_list[r]]
      for r in range(WORLD)
  ]
  packed = pack_mp_inputs(plan, per_rank_inputs)
  packed_specs = {k: P("mp", None, None, None) for k in packed}
  fn_mp = dist_forward_fn(plan, dp_input=False)
  fwd_mp = jax.jit(shard_map(
      fn_mp, mesh=mesh, in_specs=(param_specs(plan), packed_specs),
      out_specs=tuple(P("mp") for _ in sizes)))
  mp_out = fwd_mp(class_params, packed)
  for a, b in zip(dp_out, mp_out):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_ragged_to_padded_roundtrip():
  ids = RaggedIds(jnp.asarray([3, 4, 5, 9], jnp.int32),
                  jnp.asarray([0, 1, 1, 4], jnp.int32))
  padded = ragged_to_padded(ids, 3)
  np.testing.assert_array_equal(
      np.asarray(padded), [[3, -1, -1], [-1, -1, -1], [4, 5, 9]])


def test_get_set_weights_roundtrip():
  rng = np.random.default_rng(11)
  configs = [TableConfig(input_dim=int(s), output_dim=int(w))
             for s, w in [(40, 8), (600, 16), (70, 8), (80, 16)]]
  plan = DistEmbeddingStrategy(configs, WORLD, "memory_balanced",
                               column_slice_threshold=2000)
  weights = gen_weights(rng, configs)
  back = get_weights(plan, set_weights(plan, weights))
  for t, (a, b) in enumerate(zip(weights, back)):
    np.testing.assert_array_equal(a, b, err_msg=f"table {t}")


def test_get_weights_streams_bounded_fetches(monkeypatch):
  """get_weights must never stage a whole class buffer on host: every
  device_get fetch stays under max_fetch_elements (VERDICT item 4 — the
  reference chunks its allgather for the same reason,
  `dist_model_parallel.py:596-617`)."""
  rng = np.random.default_rng(13)
  configs = [TableConfig(input_dim=int(s), output_dim=16)
             for s in (500, 300, 900, 200, 150, 100, 120, 80)]
  plan = DistEmbeddingStrategy(configs, WORLD)
  weights = gen_weights(rng, configs)
  params = set_weights(plan, weights)

  import distributed_embeddings_tpu.layers.dist_model_parallel as dmp
  fetched = []
  real = jax.device_get
  monkeypatch.setattr(dmp.jax, "device_get",
                      lambda x: fetched.append(int(np.prod(np.shape(x))))
                      or real(x))
  cap = 64 * 16  # 64 rows per fetch
  back = get_weights(plan, params, max_fetch_elements=cap)
  for t, (a, b) in enumerate(zip(weights, back)):
    np.testing.assert_array_equal(a, b, err_msg=f"table {t}")
  assert fetched, "device_get was never used"
  assert max(fetched) <= cap, f"fetch of {max(fetched)} elements exceeds cap"


def test_set_weights_sharded_via_callback():
  rng = np.random.default_rng(12)
  configs = [TableConfig(input_dim=32, output_dim=8) for _ in range(8)]
  plan = DistEmbeddingStrategy(configs, WORLD)
  weights = gen_weights(rng, configs)
  mesh = make_mesh()
  params = set_weights(plan, weights, mesh=mesh)
  for k, v in params.items():
    assert v.sharding.spec == P("mp", None)
  back = get_weights(plan, params)
  for a, b in zip(weights, back):
    np.testing.assert_array_equal(a, np.asarray(b))


def test_set_weights_shape_mismatch_raises():
  plan = DistEmbeddingStrategy([TableConfig(input_dim=4, output_dim=2)], 1)
  with pytest.raises(ValueError):
    set_weights(plan, [np.zeros((5, 2), np.float32)])
  with pytest.raises(ValueError):
    set_weights(plan, [np.zeros((4, 2), np.float32), np.zeros((1, 1))])


def test_parity_mixed_hotness_one_class():
  """1-hot and multi-hot inputs sharing one width class: the hotness-bucket
  bookkeeping (routing build + output re-assembly) must stay aligned."""
  rng = np.random.default_rng(21)
  sizes = [60, 70, 80, 90, 100, 110, 120, 130, 140]
  configs = [TableConfig(input_dim=s, output_dim=8, combiner="sum")
             for s in sizes]
  plan = DistEmbeddingStrategy(configs, WORLD, "memory_balanced")
  assert len(plan.class_keys) == 1  # all in one width class
  weights = gen_weights(rng, configs)
  class_params = {k: jnp.asarray(v)
                  for k, v in set_weights(plan, weights).items()}
  batch = 2 * WORLD
  hots = [1, 5, 1, 3, 5, 1, 3, 1, 5]  # mixed hotness across the class
  inputs_np = []
  for t, h in enumerate(hots):
    ids = rng.integers(0, sizes[t], size=(batch, h)).astype(np.int32)
    if h > 1:  # ragged padding in some slots
      mask = rng.random((batch, h)) < 0.3
      mask[:, 0] = False
      ids[mask] = -1
    inputs_np.append(ids)
  mesh = make_mesh()
  fn = dist_forward_fn(plan)
  fwd = jax.jit(shard_map(
      fn, mesh=mesh,
      in_specs=(param_specs(plan),) + tuple(P("mp") for _ in inputs_np),
      out_specs=tuple(P("mp") for _ in inputs_np)))
  got = fwd(class_params, *[jnp.asarray(x) for x in inputs_np])
  want = reference_forward(weights, plan.input_table_map, inputs_np,
                           ["sum"] * len(configs))
  for i, (g, w) in enumerate(zip(got, want)):
    np.testing.assert_allclose(np.asarray(g), w, rtol=1e-5, atol=1e-5,
                               err_msg=f"input {i} (hotness {hots[i]})")


def test_mp_input_mode_multi_hot_mixed():
  """dp_input=False with mixed hotness: pack_mp_inputs + forward_mp must
  agree on bucket layout via the explicit `hotness` argument."""
  rng = np.random.default_rng(22)
  sizes = [48, 64, 80, 96, 112, 128, 144, 160]
  configs = [TableConfig(input_dim=s, output_dim=8, combiner="sum")
             for s in sizes]
  plan = DistEmbeddingStrategy(configs, WORLD, "basic")
  weights = gen_weights(rng, configs)
  class_params = {k: jnp.asarray(v)
                  for k, v in set_weights(plan, weights).items()}
  batch = 2 * WORLD
  hots = [1, 4, 1, 4, 1, 4, 1, 4]
  inputs_np = [rng.integers(0, s, size=(batch, h)).astype(np.int32)
               for s, h in zip(sizes, hots)]
  mesh = make_mesh()

  # dp path as the oracle
  fn_dp = dist_forward_fn(plan)
  fwd_dp = jax.jit(shard_map(
      fn_dp, mesh=mesh,
      in_specs=(param_specs(plan),) + tuple(P("mp") for _ in sizes),
      out_specs=tuple(P("mp") for _ in sizes)))
  dp_out = fwd_dp(class_params, *[jnp.asarray(x) for x in inputs_np])

  per_rank_inputs = [
      [jnp.asarray(inputs_np[i]) for i in plan.input_ids_list[r]]
      for r in range(WORLD)
  ]
  packed = pack_mp_inputs(plan, per_rank_inputs, hotness=hots)
  assert any(k.endswith("_h4") for k in packed), list(packed)
  packed_specs = {k: P("mp", None, None, None) for k in packed}
  engine = DistributedLookup(plan, dp_input=False, axis_name="mp")

  def fn_mp(class_params, packed):
    return tuple(engine.forward_mp(class_params, packed, hotness=hots))

  fwd_mp = jax.jit(shard_map(
      fn_mp, mesh=mesh, in_specs=(param_specs(plan), packed_specs),
      out_specs=tuple(P("mp") for _ in sizes)))
  mp_out = fwd_mp(class_params, packed)
  for i, (a, b) in enumerate(zip(dp_out, mp_out)):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6,
                               err_msg=f"input {i}")


def test_forward_mp_stale_packed_shape_raises():
  plan = DistEmbeddingStrategy(
      [TableConfig(input_dim=16, output_dim=8) for _ in range(8)], WORLD)
  engine = DistributedLookup(plan, dp_input=False)
  name = class_param_name(8, None) + "_h1"
  bad = {name: jnp.zeros((1, 3, 8, 2), jnp.int32)}  # wrong n_b and h
  params = {class_param_name(8, None): jnp.zeros((16, 8))}
  with pytest.raises(ValueError, match="packed input"):
    engine.forward_mp(params, bad)
