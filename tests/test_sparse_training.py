"""Fused sparse training path tests.

The reference's hybrid backward emits deduplicated sparse grads and TF
optimizers apply them row-wise (`/root/reference/distributed_embeddings/python/ops/embedding_lookup_ops.py:105-122`,
`tests/dist_model_parallel_test.py:157-192`). Here we assert the TPU-native
fused path (lane-packed tables with interleaved optimizer state,
``make_sparse_train_step``) is numerically identical to the dense autodiff +
optax path it replaces:

- ``exact=True`` (sort-dedup, the reference's fused-backward semantics) must
  match dense optax bit-for-bit-ish even with duplicate ids;
- ``exact=False`` (per-occurrence scatter-add, stock-TF-sparse-apply
  semantics) must match whenever ids don't collide, and for SGD always.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_embeddings_tpu.models import DLRM, SyntheticModel, bce_loss
from distributed_embeddings_tpu.models.dlrm import dlrm_embedding_plan
from distributed_embeddings_tpu.models.synthetic import (
    SYNTHETIC_MODELS,
    expand_tables,
    generate_batch,
)
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    get_weights,
    set_weights,
)
from distributed_embeddings_tpu.layers.embedding import TableConfig
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.ops.packed_table import (
    PackedLayout,
    adagrad_rule,
    gather_fused,
    scatter_add_fused,
    sgd_rule,
    sparse_rule,
)
from distributed_embeddings_tpu.ops.sparse_grad import dedup_rows
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.training import (
    init_sparse_state,
    init_sparse_state_direct,
    make_sparse_train_step,
    make_train_step,
    shard_batch,
    shard_params,
    unpack_sparse_state,
)


# ---------------------------------------------------------------------------
# packed_table unit tests
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rows,width,n_aux", [
    (20, 4, 0), (20, 4, 1), (37, 16, 1), (5, 128, 1), (9, 100, 2),
])
def test_packed_layout_roundtrip(rows, width, n_aux):
  rng = np.random.default_rng(0)
  layout = PackedLayout(rows=rows, width=width, n_aux=n_aux)
  table = rng.standard_normal((rows, width)).astype(np.float32)
  aux = [rng.standard_normal((rows, width)).astype(np.float32)
         for _ in range(n_aux)]
  buf = layout.pack(table, aux)
  assert buf.shape == layout.shape
  assert buf.shape[1] % 128 == 0
  t2, a2 = layout.unpack(buf)
  np.testing.assert_array_equal(t2, table)
  for a, b in zip(aux, a2):
    np.testing.assert_array_equal(a, b)


def test_gather_scatter_fused():
  rng = np.random.default_rng(1)
  layout = PackedLayout(rows=33, width=8, n_aux=1)
  table = rng.standard_normal((33, 8)).astype(np.float32)
  acc = rng.uniform(0.1, 1.0, (33, 8)).astype(np.float32)
  buf = jnp.asarray(layout.pack(table, [acc]))
  ids = jnp.asarray([0, 5, 32, 5, -1, 40], jnp.int32)  # dups + OOB sentinels
  fused = gather_fused(layout, buf, ids)
  assert fused.shape == (6, 16)
  for k, i in enumerate([0, 5, 32, 5]):
    np.testing.assert_allclose(np.asarray(fused[k, :8]), table[i], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(fused[k, 8:]), acc[i], rtol=1e-6)
  np.testing.assert_array_equal(np.asarray(fused[4:]), np.zeros((2, 16)))

  delta = jnp.asarray(rng.standard_normal((6, 16)), jnp.float32)
  buf2 = scatter_add_fused(layout, buf, ids, delta)
  t2, (acc2,) = layout.unpack(buf2)
  want_t, want_a = table.copy(), acc.copy()
  for k, i in enumerate([0, 5, 32, 5]):
    want_t[i] += np.asarray(delta[k, :8])
    want_a[i] += np.asarray(delta[k, 8:])
  np.testing.assert_allclose(np.asarray(t2), want_t, rtol=1e-5, atol=1e-6)
  np.testing.assert_allclose(np.asarray(acc2), want_a, rtol=1e-5, atol=1e-6)


def _optax_of(name, lr):
  return {
      "sgd": lambda: optax.sgd(lr),
      "adagrad": lambda: optax.adagrad(lr),
      "momentum": lambda: optax.sgd(lr, momentum=0.9),
      "adam": lambda: optax.adam(lr),
  }[name]()


@pytest.mark.parametrize("name", ["sgd", "adagrad", "momentum", "adam"])
def test_rule_matches_optax_dense(name):
  """dedup'd rule application == dense optax update on the same grads,
  over TWO sequential steps (the second exercises nonzero momentum/moment
  state and Adam's step-dependent bias correction). Both steps touch the
  same rows, where lazy sparse semantics and dense optax agree."""
  rng = np.random.default_rng(0)
  table = jnp.asarray(rng.standard_normal((20, 4)), jnp.float32)
  ids = jnp.asarray([2, 5, 5, 11, 2, 19], jnp.int32)

  rule = sparse_rule(name, 0.1)
  layout = PackedLayout(rows=20, width=4, n_aux=rule.n_aux)
  aux0 = [jnp.full_like(table, v) for v in rule.aux_init]
  buf = jnp.asarray(layout.pack(table, aux0))

  opt = _optax_of(name, 0.1)
  state = opt.init(table)
  want = table
  for step in range(2):
    rows = jnp.asarray(rng.standard_normal((6, 4)), jnp.float32)
    dense_grad = jnp.zeros_like(want).at[ids].add(rows)
    updates, state = opt.update(dense_grad, state, want)
    want = optax.apply_updates(want, updates)

    sr = dedup_rows(ids, rows, 20)
    fused_rows = gather_fused(layout, buf, sr.ids)
    aux = fused_rows[:, 4:].reshape(sr.ids.shape + (rule.n_aux, 4)) \
        if rule.n_aux else None
    delta = rule.delta(sr.rows, aux, jnp.asarray(step, jnp.int32))
    buf = scatter_add_fused(layout, buf, sr.ids, delta)
  got, _ = layout.unpack(buf)
  np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                             rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# step-level parity
# ---------------------------------------------------------------------------


def _dlrm_models(world, vocab, strategy="memory_balanced", threshold=None,
                 dense_row_threshold=0):
  kwargs = dict(vocab_sizes=vocab, embedding_dim=16, bottom_mlp=(32, 16),
                top_mlp=(32, 1), strategy=strategy,
                column_slice_threshold=threshold,
                dense_row_threshold=dense_row_threshold)
  dist = DLRM(world_size=world, **kwargs)
  ref = DLRM(world_size=1, **kwargs)
  plan_d = dlrm_embedding_plan(vocab, 16, world, strategy,
                               column_slice_threshold=threshold,
                               dense_row_threshold=dense_row_threshold)
  plan_r = dlrm_embedding_plan(vocab, 16, 1, strategy,
                               column_slice_threshold=threshold,
                               dense_row_threshold=dense_row_threshold)
  return dist, ref, plan_d, plan_r


def _make_batch(rng, vocab, batch):
  numerical = jnp.asarray(rng.standard_normal((batch, 13)), jnp.float32)
  cats = [jnp.asarray(rng.integers(0, v, batch), jnp.int32) for v in vocab]
  labels = jnp.asarray(rng.integers(0, 2, batch), jnp.float32)
  return numerical, cats, labels


@pytest.mark.parametrize("opt_name,dense_thresh", [
    ("sgd", 0), ("adagrad", 0), ("adagrad", 32),
    ("momentum", 0), ("adam", 32),
])
def test_sparse_step_matches_dense_step_single_device(opt_name, dense_thresh):
  vocab = [64, 32, 16, 8]
  rng = np.random.default_rng(1)
  model = DLRM(vocab_sizes=vocab, embedding_dim=16, bottom_mlp=(32, 16),
               top_mlp=(32, 1), dense_row_threshold=dense_thresh)
  plan = dlrm_embedding_plan(vocab, 16, 1, dense_row_threshold=dense_thresh)
  batch = _make_batch(rng, vocab, 32)
  params = model.init(jax.random.PRNGKey(0), batch[0], batch[1])["params"]

  dense_opt = _optax_of(opt_name, 0.1)

  def loss_fn(p, numerical, cats, labels):
    return bce_loss(model.apply({"params": p}, numerical, cats), labels)

  dstate = dense_opt.init(params)
  dense_step = make_train_step(loss_fn, dense_opt, None, params, dstate,
                               batch, donate=False)
  p_dense, _, loss_dense = dense_step(params, dstate, *batch)

  rule = sparse_rule(opt_name, 0.1)
  state = init_sparse_state(plan, params, rule, dense_opt)
  sparse_step = make_sparse_train_step(
      model, plan, bce_loss, dense_opt, rule, None, state, batch,
      exact=True, donate=False)
  state2, loss_sparse = sparse_step(state, *batch)

  np.testing.assert_allclose(float(loss_dense), float(loss_sparse),
                             rtol=1e-5, atol=1e-6)
  p_sparse, _ = unpack_sparse_state(plan, rule, state2)
  flat_d = jax.tree_util.tree_leaves_with_path(p_dense)
  flat_s = {jax.tree_util.keystr(k): v
            for k, v in jax.tree_util.tree_leaves_with_path(p_sparse)}
  for k, v in flat_d:
    ks = jax.tree_util.keystr(k)
    np.testing.assert_allclose(np.asarray(v), np.asarray(flat_s[ks]),
                               rtol=1e-4, atol=1e-5, err_msg=ks)


def test_fast_mode_matches_exact_without_collisions():
  """Per-occurrence (fast) == dedup (exact) when ids are unique per table."""
  vocab = [128, 96]
  model = DLRM(vocab_sizes=vocab, embedding_dim=16, bottom_mlp=(16, 16),
               top_mlp=(16, 1), dense_row_threshold=0)
  plan = dlrm_embedding_plan(vocab, 16, 1, dense_row_threshold=0)
  rng = np.random.default_rng(5)
  b = 16
  numerical = jnp.asarray(rng.standard_normal((b, 13)), jnp.float32)
  cats = [jnp.asarray(rng.permutation(v)[:b], jnp.int32) for v in vocab]
  labels = jnp.asarray(rng.integers(0, 2, b), jnp.float32)
  batch = (numerical, cats, labels)
  params = model.init(jax.random.PRNGKey(0), numerical, cats)["params"]
  rule = adagrad_rule(0.1)
  opt = optax.adagrad(0.1)

  outs = {}
  for exact in (False, True):
    state = init_sparse_state(plan, params, rule, opt)
    step = make_sparse_train_step(model, plan, bce_loss, opt, rule, None,
                                  state, batch, exact=exact, donate=False)
    s2, loss = step(state, *batch)
    outs[exact], _ = unpack_sparse_state(plan, rule, s2)
  for name in outs[True]["embeddings"]:
    np.testing.assert_allclose(
        np.asarray(outs[False]["embeddings"][name]),
        np.asarray(outs[True]["embeddings"][name]), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("opt_name", ["sgd", "adagrad", "momentum", "adam"])
def test_sparse_step_distributed_matches_single_reference(opt_name):
  """8-device fused hybrid step == single-device dense step (ref pattern,
  `tests/dist_model_parallel_test.py:157-192`).

  All four rules run the world>1 shard_map path: momentum (n_aux=1) and
  adam (n_aux=2) interleave aux state into the packed physical rows, which
  changes routing-buffer widths vs sgd — previously only covered
  single-device (VERDICT r4 weak item 5)."""
  world = 8
  vocab = [977, 355, 131, 64, 32, 16, 9, 5, 130, 70]
  rng = np.random.default_rng(2)
  # dense_row_threshold=64 exercises mixed dense+sparse classes under mesh
  dist, ref, plan_d, plan_r = _dlrm_models(world, vocab,
                                           dense_row_threshold=64)
  batch = _make_batch(rng, vocab, 8 * world)
  mesh = create_mesh(world)

  ref_params = ref.init(jax.random.PRNGKey(0), batch[0], batch[1])["params"]

  global_w = get_weights(plan_r, ref_params["embeddings"])
  dist_tables = set_weights(plan_d, global_w)
  dist_params = dict(ref_params)
  dist_params["embeddings"] = {k: jnp.asarray(v)
                               for k, v in dist_tables.items()}

  dense_opt = _optax_of(opt_name, 0.05)
  rule = sparse_rule(opt_name, 0.05)

  def ref_loss(p, numerical, cats, labels):
    return bce_loss(ref.apply({"params": p}, numerical, cats), labels)

  rstate = dense_opt.init(ref_params)
  ref_step = make_train_step(ref_loss, dense_opt, None, ref_params, rstate,
                             batch, donate=False)
  ref_after, _, ref_loss_v = ref_step(ref_params, rstate, *batch)

  state = init_sparse_state(plan_d, dist_params, rule, dense_opt)
  state = shard_params(state, mesh)
  step = make_sparse_train_step(dist, plan_d, bce_loss, dense_opt, rule,
                                mesh, state, batch, exact=True, donate=False)
  state2, loss_v = step(state, *shard_batch(batch, mesh))

  np.testing.assert_allclose(float(ref_loss_v), float(loss_v),
                             rtol=1e-5, atol=1e-6)
  p2, _ = unpack_sparse_state(plan_d, rule, jax.device_get(state2))
  got_w = get_weights(plan_d, p2["embeddings"])
  want_w = get_weights(plan_r, ref_after["embeddings"])
  for t, (g, w) in enumerate(zip(got_w, want_w)):
    np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5,
                               err_msg=f"table {t}")
  # dense layers updated identically too (every leaf of both MLPs)
  for key in ("bottom_mlp", "top_mlp"):
    got = {jax.tree_util.keystr(k): v
           for k, v in jax.tree_util.tree_leaves_with_path(p2[key])}
    for k, v in jax.tree_util.tree_leaves_with_path(ref_after[key]):
      ks = jax.tree_util.keystr(k)
      np.testing.assert_allclose(np.asarray(got[ks]), np.asarray(v),
                                 rtol=1e-4, atol=1e-5,
                                 err_msg=f"{key}{ks}")


def test_sparse_step_synthetic_multihot():
  """Multi-hot shared tables (hotness buckets) through the fused path."""
  from distributed_embeddings_tpu.models.synthetic import (
      EmbeddingGroup,
      SyntheticModelConfig,
  )
  small = SyntheticModelConfig(
      name="t", embedding_groups=(
          EmbeddingGroup(1, (1, 5), 97, 8, True),
          EmbeddingGroup(3, (1,), 53, 8, False),
          EmbeddingGroup(2, (1,), 31, 16, False),
      ),
      mlp_sizes=(32, 16), num_numerical_features=4, interact_stride=None)
  world = 8
  tables, tmap, hotness = expand_tables(small)
  rng = np.random.default_rng(3)
  batch = 2 * world
  numerical, cats, labels = generate_batch(small, batch, alpha=1.05, seed=4)
  cats = [np.minimum(c, tables[t].input_dim - 1).astype(np.int32)
          for c, t in zip(cats, tmap)]
  cats = [jnp.asarray(c if h > 1 else c[:, 0])
          for c, h in zip(cats, hotness)]
  batch_tree = (jnp.asarray(numerical), cats, jnp.asarray(labels))

  # dense_row_threshold=40 puts the width-16 tables on the MXU path while
  # the shared multi-hot 97-row table stays sparse
  dist = SyntheticModel(config=small, world_size=world, strategy="basic",
                        dense_row_threshold=40)
  ref = SyntheticModel(config=small, world_size=1, strategy="basic",
                       dense_row_threshold=40)
  plan_d = DistEmbeddingStrategy(tables, world, "basic", input_table_map=tmap,
                                 dense_row_threshold=40)
  plan_r = DistEmbeddingStrategy(tables, 1, "basic", input_table_map=tmap,
                                 dense_row_threshold=40)

  ref_params = ref.init(jax.random.PRNGKey(0), batch_tree[0],
                        batch_tree[1])["params"]
  global_w = get_weights(plan_r, ref_params["embeddings"])
  dist_params = dict(ref_params)
  dist_params["embeddings"] = {
      k: jnp.asarray(v) for k, v in set_weights(plan_d, global_w).items()}

  dense_opt = optax.adagrad(0.05)
  rule = adagrad_rule(0.05)
  mesh = create_mesh(world)

  def ref_loss(p, numerical, cats, labels):
    return bce_loss(ref.apply({"params": p}, numerical, cats), labels)

  rstate = dense_opt.init(ref_params)
  ref_step = make_train_step(ref_loss, dense_opt, None, ref_params, rstate,
                             batch_tree, donate=False)
  ref_after, _, ref_loss_v = ref_step(ref_params, rstate, *batch_tree)

  state = shard_params(init_sparse_state(plan_d, dist_params, rule,
                                         dense_opt), mesh)
  step = make_sparse_train_step(dist, plan_d, bce_loss, dense_opt, rule,
                                mesh, state, batch_tree, exact=True,
                                donate=False)
  state2, loss_v = step(state, *shard_batch(batch_tree, mesh))
  np.testing.assert_allclose(float(ref_loss_v), float(loss_v),
                             rtol=1e-5, atol=1e-6)
  p2, _ = unpack_sparse_state(plan_d, rule, jax.device_get(state2))
  got_w = get_weights(plan_d, p2["embeddings"])
  want_w = get_weights(plan_r, ref_after["embeddings"])
  for t, (g, w) in enumerate(zip(got_w, want_w)):
    np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5,
                               err_msg=f"table {t}")


# ---------------------------------------------------------------------------
# chunked gather + direct packed init
# ---------------------------------------------------------------------------


def test_gather_fused_chunked_matches_one_shot():
  from distributed_embeddings_tpu.ops.packed_table import gather_fused_chunked
  rng = np.random.default_rng(3)
  layout = PackedLayout(rows=1000, width=16, n_aux=1)
  table = rng.standard_normal((1000, 16)).astype(np.float32)
  acc = rng.uniform(0.1, 1.0, (1000, 16)).astype(np.float32)
  buf = jnp.asarray(layout.pack(table, [acc]))
  ids = jnp.asarray(rng.integers(-1, 1000, (7, 130)).astype(np.int32))
  want = gather_fused(layout, buf, ids)
  got = jax.jit(lambda b, i: gather_fused_chunked(layout, b, i, chunk=128))(
      buf, ids)
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_init_sparse_state_direct_matches_generic():
  """Direct packed init: same pytree/shapes as the generic path, correct
  per-table scale and aux init, and usable by the train step."""
  from distributed_embeddings_tpu.training import init_sparse_state_direct

  vocab = [3000, 2500, 64, 32]
  model = DLRM(vocab_sizes=vocab, embedding_dim=16, bottom_mlp=(16, 16),
               top_mlp=(16, 1), dense_row_threshold=128)
  plan = dlrm_embedding_plan(vocab, 16, 1, dense_row_threshold=128)
  rng = np.random.default_rng(0)
  B = 32
  numerical = jnp.asarray(rng.standard_normal((B, 13)), jnp.float32)
  cats = [jnp.asarray(rng.integers(0, v, B), jnp.int32) for v in vocab]
  labels = jnp.asarray(rng.integers(0, 2, B), jnp.float32)

  dense_opt = optax.adagrad(0.05)
  rule = adagrad_rule(0.05, initial_accumulator_value=0.3)

  params = model.init(jax.random.PRNGKey(0), numerical, cats)["params"]
  state_generic = init_sparse_state(plan, params, rule, dense_opt)

  dummy_acts = [jnp.zeros((2, 16), jnp.float32) for _ in vocab]
  dense_params = model.init(jax.random.PRNGKey(0), numerical[:2],
                            [c[:2] for c in cats],
                            emb_acts=dummy_acts)["params"]
  state_direct = init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                          jax.random.PRNGKey(1))

  # identical pytree structure + shapes (AOT avals interchangeable)
  gs = jax.tree_util.tree_map(lambda x: (jnp.shape(x), jnp.result_type(x)),
                              state_generic)
  ds = jax.tree_util.tree_map(lambda x: (jnp.shape(x), jnp.result_type(x)),
                              state_direct)
  assert jax.tree_util.tree_structure(gs) == jax.tree_util.tree_structure(ds)
  assert jax.tree_util.tree_all(
      jax.tree_util.tree_map(lambda a, b: a == b, gs, ds))

  params_d, aux = unpack_sparse_state(plan, rule, state_direct,
                                      include_aux=True)
  for name, t in params_d["embeddings"].items():
    t = np.asarray(t)
    live = np.abs(t).sum(axis=-1) > 0  # padding rows are zero
    vals = t[live]
    # DLRM init is uniform(-1/sqrt(rows), 1/sqrt(rows)); rows differ per
    # table, so just bound by the largest scale and check non-degenerate
    assert np.abs(vals).max() <= 1.0 / np.sqrt(min(vocab)) + 1e-6
    assert vals.std() > 0
  for name, a in aux.items():
    acc = np.asarray(a[0])
    live = np.abs(acc).sum(axis=-1) > 0
    np.testing.assert_allclose(acc[live], 0.3, rtol=1e-6)

  step = make_sparse_train_step(model, plan, bce_loss, dense_opt, rule,
                                None, state_direct,
                                (numerical, cats, labels))
  l0 = None
  state = state_direct
  for _ in range(5):
    state, loss = step(state, numerical, cats, labels)
    if l0 is None:
      l0 = float(loss)
  assert float(loss) < l0


def test_apply_sparse_chunked_matches_single_shot():
  """Multi-chunk scatter scan (with a padded tail chunk) must equal the
  single-shot apply; regression for the last-chunk gradient misalignment."""
  from distributed_embeddings_tpu.parallel.lookup_engine import (
      DistributedLookup,
  )

  tables = [dict(input_dim=50, output_dim=8, combiner="sum")]
  plan = DistEmbeddingStrategy(tables, 1, "basic")
  rng = np.random.default_rng(7)
  B, h = 30, 3  # n = 90 occurrences; chunk 12 -> 8 chunks with pad 6
  ids_in = jnp.asarray(rng.integers(0, 50, (B, h)).astype(np.int32))

  rule = adagrad_rule(0.1)
  results = {}
  for chunk in (12, 1 << 20):
    rng = np.random.default_rng(8)  # identical table/grads for both runs
    engine = DistributedLookup(plan, apply_chunk=chunk)
    layouts = engine.fused_layouts(rule)
    name = next(iter(layouts))
    layout = layouts[name]
    table = rng.standard_normal((50, 8)).astype(np.float32)
    acc = np.full((50, 8), 0.1, np.float32)
    buf = jnp.asarray(layout.pack(
        np.pad(table, ((0, layout.rows - 50), (0, 0))),
        [np.pad(acc, ((0, layout.rows - 50), (0, 0)))]))
    fused = {name: buf}
    ids_all = engine.route_ids([ids_in])
    _, residuals = engine.lookup_sparse_fused(fused, layouts, ids_all)
    bk = next(iter(ids_all))
    d_z = {bk: jnp.asarray(
        rng.standard_normal(ids_all[bk].shape[:2] + (8,)), jnp.float32)}
    new = engine.apply_sparse(fused, layouts, d_z, residuals, rule,
                              jnp.zeros((), jnp.int32))
    results[chunk] = np.asarray(new[name])
  np.testing.assert_allclose(results[12], results[1 << 20],
                             rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("name", ["sgd", "adagrad", "momentum", "adam"])
def test_sparse_optimizer_apply_matches_optax(name):
  """Standalone SparseOptimizer (IndexedSlices-equivalent apply path,
  reference `embedding_lookup_ops.py:105-122` + TF sparse applies) matches
  dense optax on deduplicated gradients, over two steps touching the same
  rows (where lazy sparse state and dense optax state agree)."""
  from distributed_embeddings_tpu.ops.sparse_grad import sparse_optimizer

  rng = np.random.default_rng(4)
  table = jnp.asarray(rng.standard_normal((30, 8)), jnp.float32)
  ids = jnp.asarray([1, 7, 7, 29, 1, 3], jnp.int32)

  opt = _optax_of(name, 0.2)
  sopt = sparse_optimizer(name, 0.2)
  dstate = opt.init(table)
  sstate = sopt.init(table)
  want = got = table
  for _ in range(2):
    rows = jnp.asarray(rng.standard_normal((6, 8)), jnp.float32)
    dense_grad = jnp.zeros_like(want).at[ids].add(rows)
    updates, dstate = opt.update(dense_grad, dstate, want)
    want = optax.apply_updates(want, updates)
    sr = dedup_rows(ids, rows, 30)
    got, sstate = jax.jit(sopt.apply)(got, sstate, sr)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_shard_batch_rejects_indivisible_global_batch():
  """Reference parity: an indivisible model-parallel batch errors clearly
  (`dist_model_parallel.py:352-365`)."""
  import pytest

  from distributed_embeddings_tpu.parallel import create_mesh
  from distributed_embeddings_tpu.training import shard_batch
  mesh = create_mesh(8)
  with pytest.raises(ValueError, match="not divisible"):
    shard_batch((jnp.zeros((10, 4)),), mesh)


@pytest.mark.parametrize("combiner,reg", [("sum", None), ("sum", "l2")])
def test_multihot_masked_path_matches_onehot_decomposition(combiner, reg):
  """The multi-hot narrow fast path (window-masked phys-width residuals,
  round 3) must produce EXACTLY the updates of the mathematically
  equivalent decomposition into h shared-table 1-hot inputs (which takes
  the stride-width residual path): same forward sum, same per-occurrence
  Adagrad deltas from forward-time state. With ``reg='l2'``, the
  touched-rows weight decay's forward-time-row extraction must also agree
  between the masked-phys and stride residual layouts."""
  import flax.linen as nn
  from distributed_embeddings_tpu.layers.dist_model_parallel import (
      get_weights,
  )
  from distributed_embeddings_tpu.models import bce_loss
  from distributed_embeddings_tpu.training import unpack_sparse_state

  h, b, vocab, w = 5, 16, 300, 16  # w16+acc: stride 32, rpp 4 -> masked path
  rng = np.random.default_rng(11)
  ids = rng.integers(0, vocab, (b, h)).astype(np.int32)
  # force duplicates inside bags to exercise the per-occurrence semantics
  ids[:, 1] = ids[:, 0]
  numerical = rng.standard_normal((b, 4)).astype(np.float32)
  labels = rng.integers(0, 2, b).astype(np.float32)

  class HeadMulti(nn.Module):
    @nn.compact
    def __call__(self, numerical, cats, emb_acts=None):
      x = jnp.concatenate([numerical, emb_acts[0]], axis=1)
      return jnp.squeeze(nn.Dense(1, name="d")(x), -1)

  class HeadSplit(nn.Module):
    @nn.compact
    def __call__(self, numerical, cats, emb_acts=None):
      summed = sum(emb_acts[1:], emb_acts[0])
      x = jnp.concatenate([numerical, summed], axis=1)
      return jnp.squeeze(nn.Dense(1, name="d")(x), -1)

  def train(variant):
    if variant == "multi":
      tables = [TableConfig(vocab, w, combiner=combiner,
                            initializer="uniform", regularizer=reg)]
      tmap, cats = [0], [jnp.asarray(ids)]
      model = HeadMulti()
    else:
      tables = [TableConfig(vocab, w, combiner=combiner,
                            initializer="uniform", regularizer=reg)]
      tmap = [0] * h
      cats = [jnp.asarray(ids[:, j]) for j in range(h)]
      model = HeadSplit()
    plan = DistEmbeddingStrategy(tables, 1, "basic", input_table_map=tmap,
                                 dense_row_threshold=0)
    rule = adagrad_rule(0.5)
    opt = optax.adagrad(0.5)
    dummy = [jnp.zeros((2, w), jnp.float32) for _ in tmap]
    dp = model.init(jax.random.PRNGKey(0), jnp.asarray(numerical[:2]), None,
                    emb_acts=dummy)["params"]
    state = init_sparse_state_direct(plan, rule, dp, opt,
                                     jax.random.PRNGKey(1))
    step = make_sparse_train_step(model, plan, bce_loss, opt, rule, None,
                                  state, (jnp.asarray(numerical), cats,
                                          jnp.asarray(labels)),
                                  donate=False)
    for _ in range(2):
      state, loss = step(state, jnp.asarray(numerical), cats,
                         jnp.asarray(labels))
    params, aux = unpack_sparse_state(plan, rule, state, include_aux=True)
    (table,) = get_weights(plan, params["embeddings"])
    return table, aux, float(loss)

  t_multi, aux_m, loss_m = train("multi")
  t_split, aux_s, loss_s = train("split")
  assert abs(loss_m - loss_s) < 1e-6
  np.testing.assert_allclose(t_multi, t_split, rtol=1e-5, atol=1e-6)
  # the Adagrad accumulators (extracted through BOTH residual layouts by
  # the two variants' applies) must agree too
  for a_m, a_s in zip(aux_m.values(), aux_s.values()):
    for x, y in zip(a_m, a_s):
      np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                 rtol=1e-5, atol=1e-6)
