"""Sparse (IndexedSlices-equivalent) training path tests.

The reference's hybrid backward emits deduplicated sparse grads and TF
optimizers apply them row-wise (`/root/reference/distributed_embeddings/python/ops/embedding_lookup_ops.py:105-122`,
`tests/dist_model_parallel_test.py:157-192`). Here we assert the TPU-native
sparse path (``make_sparse_train_step`` + ``sparse_sgd``/``sparse_adagrad``)
is numerically identical to the dense autodiff + optax path it replaces.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_embeddings_tpu.models import DLRM, SyntheticModel, bce_loss
from distributed_embeddings_tpu.models.dlrm import dlrm_embedding_plan
from distributed_embeddings_tpu.models.synthetic import (
    SYNTHETIC_MODELS,
    expand_tables,
    generate_batch,
)
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.ops.sparse_grad import (
    SparseRows,
    dedup_rows,
    sparse_adagrad,
    sparse_optimizer,
    sparse_sgd,
)
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.training import (
    init_sparse_state,
    make_sparse_train_step,
    make_train_step,
    shard_batch,
    shard_params,
)


def test_dedup_rows_sums_duplicates():
  ids = jnp.asarray([3, 1, 3, 7, 1, 99, -2], jnp.int32)
  rows = jnp.asarray(np.arange(14, dtype=np.float32).reshape(7, 2))
  out = dedup_rows(ids, rows, sentinel=10)
  dense = np.zeros((10, 2), np.float32)
  np_ids, np_rows = np.asarray(out.ids), np.asarray(out.rows)
  for i, r in zip(np_ids, np_rows):
    if i < 10:
      dense[i] += r
  expect = np.zeros((10, 2), np.float32)
  for i, r in zip([3, 1, 3, 7, 1], np.asarray(rows)[:5]):
    expect[i] += r
  np.testing.assert_allclose(dense, expect)
  # live ids unique
  live = np_ids[np_ids < 10]
  assert len(live) == len(set(live.tolist())) == 3


@pytest.mark.parametrize("name", ["sgd", "adagrad"])
def test_sparse_apply_matches_optax_dense(name):
  rng = np.random.default_rng(0)
  table = jnp.asarray(rng.standard_normal((20, 4)), jnp.float32)
  ids = jnp.asarray([2, 5, 5, 11, 2, 19], jnp.int32)
  rows = jnp.asarray(rng.standard_normal((6, 4)), jnp.float32)

  dense_grad = jnp.zeros_like(table).at[ids].add(rows)
  opt = optax.sgd(0.1) if name == "sgd" else optax.adagrad(0.1)
  state = opt.init(table)
  updates, _ = opt.update(dense_grad, state, table)
  want = optax.apply_updates(table, updates)

  sopt = sparse_optimizer(name, 0.1)
  sstate = sopt.init(table)
  got, sstate2 = sopt.apply(table, sstate, dedup_rows(ids, rows, 20))
  np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                             rtol=1e-5, atol=1e-6)
  if name == "adagrad":
    acc_want = jnp.full_like(table, 0.1).at[
        jnp.asarray([2, 5, 11, 19])].add(0)  # shape check only
    assert sstate2.sum_of_squares.shape == acc_want.shape


def test_sparse_apply_requires_dedup_semantics():
  """Duplicate live ids in .at[].add still sum for SGD (sanity)."""
  table = jnp.zeros((4, 2), jnp.float32)
  sr = SparseRows(jnp.asarray([1, 1], jnp.int32), jnp.ones((2, 2)))
  got, _ = sparse_sgd(1.0).apply(table, sparse_sgd(1.0).init(table), sr)
  np.testing.assert_allclose(np.asarray(got)[1], [-2.0, -2.0])


def _dlrm_models(world, vocab, strategy="memory_balanced", threshold=None):
  kwargs = dict(vocab_sizes=vocab, embedding_dim=16, bottom_mlp=(32, 16),
                top_mlp=(32, 1), strategy=strategy,
                column_slice_threshold=threshold)
  dist = DLRM(world_size=world, **kwargs)
  ref = DLRM(world_size=1, **kwargs)
  plan_d = dlrm_embedding_plan(vocab, 16, world, strategy,
                               column_slice_threshold=threshold)
  plan_r = dlrm_embedding_plan(vocab, 16, 1, strategy,
                               column_slice_threshold=threshold)
  return dist, ref, plan_d, plan_r


def _make_batch(rng, vocab, batch):
  numerical = jnp.asarray(rng.standard_normal((batch, 13)), jnp.float32)
  cats = [jnp.asarray(rng.integers(0, v, batch), jnp.int32) for v in vocab]
  labels = jnp.asarray(rng.integers(0, 2, batch), jnp.float32)
  return numerical, cats, labels


@pytest.mark.parametrize("opt_name", ["sgd", "adagrad"])
def test_sparse_step_matches_dense_step_single_device(opt_name):
  vocab = [64, 32, 16, 8]
  rng = np.random.default_rng(1)
  model = DLRM(vocab_sizes=vocab, embedding_dim=16, bottom_mlp=(32, 16),
               top_mlp=(32, 1))
  plan = dlrm_embedding_plan(vocab, 16, 1)
  batch = _make_batch(rng, vocab, 32)
  params = model.init(jax.random.PRNGKey(0), batch[0], batch[1])["params"]

  dense_opt = optax.sgd(0.1) if opt_name == "sgd" else optax.adagrad(0.1)

  def loss_fn(p, numerical, cats, labels):
    return bce_loss(model.apply({"params": p}, numerical, cats), labels)

  dstate = dense_opt.init(params)
  dense_step = make_train_step(loss_fn, dense_opt, None, params, dstate,
                               batch, donate=False)
  p_dense, _, loss_dense = dense_step(params, dstate, *batch)

  sopt = sparse_optimizer(opt_name, 0.1)
  ds, ts = init_sparse_state(params, dense_opt, sopt)
  sparse_step = make_sparse_train_step(
      model, plan, bce_loss, dense_opt, sopt, None, params, ds, ts,
      batch, donate=False)
  p_sparse, _, _, loss_sparse = sparse_step(params, ds, ts, *batch)

  np.testing.assert_allclose(float(loss_dense), float(loss_sparse),
                             rtol=1e-5, atol=1e-6)
  flat_d = jax.tree_util.tree_leaves_with_path(p_dense)
  flat_s = {jax.tree_util.keystr(k): v
            for k, v in jax.tree_util.tree_leaves_with_path(p_sparse)}
  for k, v in flat_d:
    ks = jax.tree_util.keystr(k)
    np.testing.assert_allclose(np.asarray(v), np.asarray(flat_s[ks]),
                               rtol=1e-4, atol=1e-5, err_msg=ks)


@pytest.mark.parametrize("opt_name", ["sgd", "adagrad"])
def test_sparse_step_distributed_matches_single_reference(opt_name):
  """8-device sparse hybrid step == single-device dense step (ref pattern,
  `tests/dist_model_parallel_test.py:157-192`)."""
  world = 8
  vocab = [977, 355, 131, 64, 32, 16, 9, 5, 130, 70]
  rng = np.random.default_rng(2)
  dist, ref, plan_d, plan_r = _dlrm_models(world, vocab)
  batch = _make_batch(rng, vocab, 8 * world)
  mesh = create_mesh(world)

  ref_params = ref.init(jax.random.PRNGKey(0), batch[0], batch[1])["params"]

  # copy global weights into the distributed layout
  from distributed_embeddings_tpu.layers.dist_model_parallel import (
      get_weights,
      set_weights,
  )
  global_w = get_weights(plan_r, ref_params["embeddings"])
  dist_tables = set_weights(plan_d, global_w)
  dist_params = dict(ref_params)
  dist_params["embeddings"] = {k: jnp.asarray(v)
                               for k, v in dist_tables.items()}

  dense_opt = optax.sgd(0.05) if opt_name == "sgd" else optax.adagrad(0.05)
  sopt = sparse_optimizer(opt_name, 0.05)

  # reference: dense single-device step
  def ref_loss(p, numerical, cats, labels):
    return bce_loss(ref.apply({"params": p}, numerical, cats), labels)

  rstate = dense_opt.init(ref_params)
  ref_step = make_train_step(ref_loss, dense_opt, None, ref_params, rstate,
                             batch, donate=False)
  ref_after, _, ref_loss_v = ref_step(ref_params, rstate, *batch)

  ds, ts = init_sparse_state(dist_params, dense_opt, sopt)
  dist_params_s = shard_params(dist_params, mesh)
  ds_s = shard_params(ds, mesh)
  ts_s = shard_params(ts, mesh)
  step = make_sparse_train_step(
      dist, plan_d, bce_loss, dense_opt, sopt, mesh, dist_params, ds, ts,
      batch, donate=False)
  sharded = shard_batch(batch, mesh)
  p2, _, _, loss_v = step(dist_params_s, ds_s, ts_s, *sharded)

  np.testing.assert_allclose(float(ref_loss_v), float(loss_v),
                             rtol=1e-5, atol=1e-6)
  got_w = get_weights(plan_d, p2["embeddings"])
  want_w = get_weights(plan_r, ref_after["embeddings"])
  for t, (g, w) in enumerate(zip(got_w, want_w)):
    np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5,
                               err_msg=f"table {t}")
  # dense layers updated identically too (every leaf of both MLPs)
  for key in ("bottom_mlp", "top_mlp"):
    got = {jax.tree_util.keystr(k): v
           for k, v in jax.tree_util.tree_leaves_with_path(p2[key])}
    for k, v in jax.tree_util.tree_leaves_with_path(ref_after[key]):
      ks = jax.tree_util.keystr(k)
      np.testing.assert_allclose(np.asarray(got[ks]), np.asarray(v),
                                 rtol=1e-4, atol=1e-5,
                                 err_msg=f"{key}{ks}")


def test_sparse_step_synthetic_multihot():
  """Multi-hot shared tables (hotness buckets) through the sparse path."""
  cfg = SYNTHETIC_MODELS["tiny"]
  # shrink: take the structure but tiny rows
  from distributed_embeddings_tpu.models.synthetic import (
      EmbeddingGroup,
      SyntheticModelConfig,
  )
  small = SyntheticModelConfig(
      name="t", embedding_groups=(
          EmbeddingGroup(1, (1, 5), 97, 8, True),
          EmbeddingGroup(3, (1,), 53, 8, False),
          EmbeddingGroup(2, (1,), 31, 16, False),
      ),
      mlp_sizes=(32, 16), num_numerical_features=4, interact_stride=None)
  world = 8
  tables, tmap, hotness = expand_tables(small)
  rng = np.random.default_rng(3)
  batch = 2 * world
  numerical, cats, labels = generate_batch(small, batch, alpha=1.05, seed=4)
  cats = [np.minimum(c, tables[t].input_dim - 1).astype(np.int32)
          for c, t in zip(cats, tmap)]
  cats = [jnp.asarray(c if h > 1 else c[:, 0])
          for c, h in zip(cats, hotness)]
  batch_tree = (jnp.asarray(numerical), cats, jnp.asarray(labels))

  dist = SyntheticModel(config=small, world_size=world, strategy="basic")
  ref = SyntheticModel(config=small, world_size=1, strategy="basic")
  plan_d = DistEmbeddingStrategy(tables, world, "basic", input_table_map=tmap)
  plan_r = DistEmbeddingStrategy(tables, 1, "basic", input_table_map=tmap)

  ref_params = ref.init(jax.random.PRNGKey(0), batch_tree[0],
                        batch_tree[1])["params"]
  from distributed_embeddings_tpu.layers.dist_model_parallel import (
      get_weights,
      set_weights,
  )
  global_w = get_weights(plan_r, ref_params["embeddings"])
  dist_params = dict(ref_params)
  dist_params["embeddings"] = {
      k: jnp.asarray(v) for k, v in set_weights(plan_d, global_w).items()}

  dense_opt = optax.adagrad(0.05)
  sopt = sparse_adagrad(0.05)
  mesh = create_mesh(world)

  def ref_loss(p, numerical, cats, labels):
    return bce_loss(ref.apply({"params": p}, numerical, cats), labels)

  rstate = dense_opt.init(ref_params)
  ref_step = make_train_step(ref_loss, dense_opt, None, ref_params, rstate,
                             batch_tree, donate=False)
  ref_after, _, ref_loss_v = ref_step(ref_params, rstate, *batch_tree)

  ds, ts = init_sparse_state(dist_params, dense_opt, sopt)
  step = make_sparse_train_step(
      dist, plan_d, bce_loss, dense_opt, sopt, mesh, dist_params, ds, ts,
      batch_tree, donate=False)
  p2, _, _, loss_v = step(shard_params(dist_params, mesh),
                          shard_params(ds, mesh), shard_params(ts, mesh),
                          *shard_batch(batch_tree, mesh))
  np.testing.assert_allclose(float(ref_loss_v), float(loss_v),
                             rtol=1e-5, atol=1e-6)
  got_w = get_weights(plan_d, p2["embeddings"])
  want_w = get_weights(plan_r, ref_after["embeddings"])
  for t, (g, w) in enumerate(zip(got_w, want_w)):
    np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5,
                               err_msg=f"table {t}")
