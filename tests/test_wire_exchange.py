"""Exchange parity suite for the round-6 and round-7 wire knobs.

``DistEmbeddingStrategy(wire_dtype=..., dedup_exchange=...)`` compresses
the dp<->mp exchanges; this file pins what each knob may and may not
change:

- ``wire_dtype='f32', dedup_exchange=True`` is BIT-EXACT against the
  seed exchange on the forward/eval path — the unique-then-gather
  rerouting ships different tensors but must reproduce the raw path's
  activations to the bit (expansion re-gathers identical rows; the
  h-sum and mean divisor run over the same values in the same order).
  Covered across 1-hot, multi-hot sum/mean with PAD_ID holes, shared
  tables, row-sliced shards, ragged inputs (which ride the raw value
  stream even under dedup), micro-batch and guarded steps.
- Training under dedup is IDENTICAL IN VALUE but not in summation
  order: duplicate ids' cotangents are segment-summed per unique id
  before the scatter instead of inside it, an fp-associativity
  reordering — trajectories are pinned to a 1e-6 absolute bound (the
  observed drift is last-ulp, ~1e-8 after 3 steps; nonlinear rules add
  the documented per-unique delta semantics, the exact=True semantics
  restricted to one exchange block).
- ``wire_dtype='bf16'`` is tolerance-bounded: one exchange round-trip
  rounds each activation row once to bf16 (8 mantissa bits, half-ulp
  2^-9), so per output element ``|err| <= h * 2^-9 * max|row|`` before
  fp-sum slack; the tests assert a 2x margin (``h * 2^-8 * max|row|``).
- ``exact=True`` demands the f32 wire at build time (sparse AND tiered
  builders), and knob validation/reporting behaves.

Round-7 additions (``overlap='pipelined'``, ``exchange_chunks``,
``wire_dtype='fp8'``, ``dedup_capacity``):

- the pipelined f32 exchange is BIT-EXACT against the monolithic wire
  across the whole parity matrix — raw and dedup'd routing, ragged and
  row-sliced shards, micro-batch and guarded steps, world 1/2/4,
  including chunk counts that do not divide the payload (the rounds are
  pure data movement: a roll, (world-1) x chunks ppermutes, a gather);
- the fp8 wire's error bound is the bf16 bound's analog with the e4m3
  half-ulp (2^-4 relative for normals, per-block amax scaling):
  ``|err| <= h * 2^-4 * max|row|`` per output element, asserted at a 2x
  margin (``h * 2^-3``);
- ``dedup_capacity`` (a cap below the safe unique bound) is refused by
  every builder without a counter path, and the guarded/with-metrics
  paths report the psum'd per-class distinct-overflow count.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from distributed_embeddings_tpu.compat import shard_map
from distributed_embeddings_tpu.layers import (
    DistEmbeddingStrategy,
    TableConfig,
)
from distributed_embeddings_tpu.layers.dist_model_parallel import set_weights
from distributed_embeddings_tpu.models import bce_loss
from distributed_embeddings_tpu.models.synthetic import (
    EmbeddingGroup,
    SyntheticModel,
    SyntheticModelConfig,
    expand_tables,
    generate_batch,
)
from distributed_embeddings_tpu.ops.packed_table import sparse_rule
from distributed_embeddings_tpu.ops.ragged import RaggedIds
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.parallel.lookup_engine import (
    PAD_ID,
    DedupRouted,
    DistributedLookup,
)
from distributed_embeddings_tpu.training import (
    init_sparse_state_direct,
    make_sparse_eval_step,
    make_sparse_train_step,
    shard_batch,
    shard_params,
    unpack_sparse_state,
)

WORLD = 4

CFG = SyntheticModelConfig(
    name="wiretest", embedding_groups=(
        EmbeddingGroup(2, (1, 5), 131, 8, True),   # shared multi-hot
        EmbeddingGroup(3, (1,), 97, 8, False),
        EmbeddingGroup(2, (3,), 53, 16, False),    # multi-hot narrow
    ),
    mlp_sizes=(32, 16), num_numerical_features=4, interact_stride=None)


# ---------------------------------------------------------------------------
# simple-path forward parity (engine.forward under shard_map)
# ---------------------------------------------------------------------------


def _forward_outs(plan, params, inputs, in_specs=None, world=WORLD):
  engine = DistributedLookup(plan)
  if world == 1:
    outs = jax.jit(lambda p, *xs: tuple(engine.forward(p, list(xs))))(
        params, *inputs)
    return [np.asarray(o) for o in outs]
  mesh = create_mesh(world)
  pspecs = {n: P("mp", None) for n in params}

  def fwd(params, *xs):
    return tuple(engine.forward(params, list(xs)))

  if in_specs is None:
    in_specs = tuple(P("mp") for _ in inputs)
  outs = jax.jit(shard_map(
      fwd, mesh=mesh, in_specs=(pspecs,) + in_specs,
      out_specs=tuple(P("mp") for _ in inputs)))(params, *inputs)
  return [np.asarray(o) for o in outs]


def _mixed_fixture(combiner, rng, world=WORLD, **plan_kw):
  sizes = [50, 80, 23, 31, 47, 19, 27, 35, 41]
  tables = [TableConfig(s, 16, combiner=combiner) for s in sizes]
  plan = DistEmbeddingStrategy(tables, world, "memory_balanced",
                               dense_row_threshold=0, **plan_kw)
  weights = [rng.standard_normal((s, 16)).astype(np.float32) for s in sizes]
  params = {k: jnp.asarray(v)
            for k, v in set_weights(plan, weights).items()}
  b = 4 * WORLD
  ids = [rng.integers(0, s, (b, 3)).astype(np.int32) for s in sizes]
  for x in ids:  # PAD holes exercise the sentinel/valid-count handling
    x[rng.random(x.shape) < 0.25] = PAD_ID
  inputs = [jnp.asarray(x) for x in ids]
  return plan, params, inputs


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_forward_bitexact_f32_dedup(combiner):
  rng = np.random.default_rng(0)
  plan_a, params, inputs = _mixed_fixture(combiner, rng)
  rng = np.random.default_rng(0)
  plan_b, params_b, inputs_b = _mixed_fixture(combiner, rng,
                                              dedup_exchange=True)
  # the dedup'd plan really routes DedupRouted buckets
  assert all(c["dedup"] for c in plan_b.exchange_report()["classes"].values())
  out_a = _forward_outs(plan_a, params, inputs)
  out_b = _forward_outs(plan_b, params_b, inputs_b)
  for t, (a, b) in enumerate(zip(out_a, out_b)):
    np.testing.assert_array_equal(a, b, err_msg=f"table {t}")


@pytest.mark.parametrize("pipe_kw", [
    {}, {"overlap": "pipelined", "exchange_chunks": 3},
    {"overlap": "fused", "exchange_chunks": 3}])
def test_forward_bitexact_f32_dedup_row_sliced(pipe_kw):
  rng = np.random.default_rng(1)
  sizes = [96, 64, 48, 40, 88, 56, 72, 104]
  tables = [TableConfig(s, 8, combiner="mean") for s in sizes]

  def build(**kw):
    plan = DistEmbeddingStrategy(tables, WORLD, "basic",
                                 row_slice_threshold=16 * 8, **kw)
    assert any(sh.row_sliced for shards in plan.rank_shards
               for sh in shards)
    params = {k: jnp.asarray(v) for k, v in set_weights(
        plan, [rng.standard_normal((s, 8)).astype(np.float32)
               for s in sizes]).items()}
    return plan, params

  rng = np.random.default_rng(1)
  plan_a, params_a = build()
  rng = np.random.default_rng(1)
  plan_b, params_b = build(dedup_exchange=True, **pipe_kw)
  b = 2 * WORLD
  ids = [rng.integers(0, s, (b, 3)).astype(np.int32) for s in sizes]
  for x in ids:
    x[rng.random(x.shape) < 0.2] = PAD_ID
  inputs = [jnp.asarray(x) for x in ids]
  out_a = _forward_outs(plan_a, params_a, inputs)
  out_b = _forward_outs(plan_b, params_b, inputs)
  for t, (a, b_) in enumerate(zip(out_a, out_b)):
    np.testing.assert_array_equal(a, b_, err_msg=f"table {t}")


@pytest.mark.parametrize("pipe_kw", [
    {}, {"overlap": "pipelined", "exchange_chunks": 5},
    {"overlap": "fused", "exchange_chunks": 5}])
def test_forward_bitexact_f32_dedup_ragged(pipe_kw):
  """A ragged input rides the raw value-stream exchange even under
  ``dedup_exchange=True`` (there is nothing padded to dedup), while the
  plan's other (padded) buckets dedup — the mix must be bit-exact. The
  pipelined variant chunks the value-stream AND unique-block wires with
  a count that divides neither."""
  rng = np.random.default_rng(2)
  tables = [TableConfig(60, 8, combiner="sum"),
            TableConfig(40, 8, combiner="sum")]

  def build(**kw):
    plan = DistEmbeddingStrategy(tables, WORLD, "basic",
                                 input_hotness=[-8, 2],
                                 dense_row_threshold=0, **kw)
    params = {k: jnp.asarray(v) for k, v in set_weights(
        plan, [rng.standard_normal((c.input_dim, 8)).astype(np.float32)
               for c in tables]).items()}
    return plan, params

  rng = np.random.default_rng(2)
  plan_a, params_a = build()
  rng = np.random.default_rng(2)
  plan_b, params_b = build(dedup_exchange=True, **pipe_kw)

  b_local, cap = 4, 16
  values = rng.integers(0, 60, WORLD * cap).astype(np.int32)
  lengths = rng.integers(0, 5, (WORLD, b_local))
  lengths = np.minimum(lengths, cap // b_local)  # fit each block's cap
  splits = np.concatenate([np.concatenate([[0], np.cumsum(l)]) + 0
                           for l in lengths])
  dense = jnp.asarray(
      rng.integers(0, 40, (WORLD * b_local, 2)).astype(np.int32))

  def run(plan, params):
    engine = DistributedLookup(plan)
    mesh = create_mesh(WORLD)
    pspec = {n: P("mp", None) for n in params}

    def fwd(params, v, s, d):
      return tuple(engine.forward(params, [RaggedIds(v, s), d]))

    return [np.asarray(o) for o in jax.jit(shard_map(
        fwd, mesh=mesh, in_specs=(pspec, P("mp"), P("mp"), P("mp")),
        out_specs=(P("mp"), P("mp"))))(
            params, jnp.asarray(values),
            jnp.asarray(splits.astype(np.int32)), dense)]

  out_a = run(plan_a, params_a)
  out_b = run(plan_b, params_b)
  for t, (a, b_) in enumerate(zip(out_a, out_b)):
    np.testing.assert_array_equal(a, b_, err_msg=f"table {t}")


def test_forward_bf16_wire_tolerance_bound():
  """The documented bf16 bound: one exchange round-trip rounds each row
  once to bf16 (half-ulp 2^-9), so ``|err| <= h * 2^-9 * max|row|`` per
  output element; asserted here with a 2x margin."""
  rng = np.random.default_rng(3)
  plan_a, params, inputs = _mixed_fixture("sum", rng)
  rng = np.random.default_rng(3)
  plan_b, params_b, inputs_b = _mixed_fixture("sum", rng,
                                              wire_dtype="bf16")
  out_a = _forward_outs(plan_a, params, inputs)
  out_b = _forward_outs(plan_b, params_b, inputs_b)
  h = 3
  for t, (a, b) in enumerate(zip(out_a, out_b)):
    bound = h * 2.0 ** -8 * np.abs(a).max() + 1e-6
    assert np.abs(a - b).max() <= bound, (t, np.abs(a - b).max(), bound)
    assert np.abs(a - b).max() > 0  # the wire really narrowed something


# ---------------------------------------------------------------------------
# fused path: eval bit-exactness, training trajectories, guard, micro-batch
# ---------------------------------------------------------------------------


def _fused_setup(rule_name, batch=32, **plan_kw):
  tables, tmap, hotness = expand_tables(CFG)
  model = SyntheticModel(CFG)
  numerical, cats, labels = generate_batch(CFG, batch, alpha=1.1, seed=8)
  cats = [np.minimum(c, tables[t].input_dim - 1).astype(np.int32)
          for c, t in zip(cats, tmap)]
  cats = [jnp.asarray(c if h > 1 else c[:, 0])
          for c, h in zip(cats, hotness)]
  batch_tree = (jnp.asarray(numerical), cats, jnp.asarray(labels))
  plan = DistEmbeddingStrategy(
      tables, WORLD, "memory_balanced", input_table_map=tmap,
      input_hotness=hotness, dense_row_threshold=60, batch_hint=batch,
      **plan_kw)
  rule = sparse_rule(rule_name, 0.005)
  opt = optax.adagrad(0.005)
  dummy = [jnp.zeros((2, t.output_dim), jnp.float32)
           for t in (tables[i] for i in tmap)]
  dense_params = model.init(jax.random.PRNGKey(0), batch_tree[0][:2],
                            [c[:2] for c in cats], emb_acts=dummy)["params"]
  state = init_sparse_state_direct(plan, rule, dense_params, opt,
                                   jax.random.PRNGKey(1))
  mesh = create_mesh(WORLD)
  state = shard_params(state, mesh)
  batch_tree = shard_batch(batch_tree, mesh)
  return model, plan, rule, opt, state, batch_tree, mesh


_RUN_STEPS_CACHE = {}


def _run_steps(rule_name, steps=3, step_kw=None, **plan_kw):
  # Memoized: every parity test re-runs the same seeded baseline arm
  # (e.g. plain f32) against its own variant, and each arm pays a fresh
  # train+eval compile. The run is pure (fresh PRNG-seeded state per
  # call, callers only compare the results), so identical configs can
  # share one run.
  key = (rule_name, steps,
         tuple(sorted((step_kw or {}).items())),
         tuple(sorted(plan_kw.items())))
  if key not in _RUN_STEPS_CACHE:
    _RUN_STEPS_CACHE[key] = _run_steps_uncached(rule_name, steps,
                                                step_kw, **plan_kw)
  return _RUN_STEPS_CACHE[key]


def _run_steps_uncached(rule_name, steps=3, step_kw=None, **plan_kw):
  model, plan, rule, opt, state, bt, mesh = _fused_setup(rule_name,
                                                         **plan_kw)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, bt, donate=False, **(step_kw or {}))
  losses = []
  for _ in range(steps):
    out = step(state, *bt)
    state, loss = out[0], out[1]
    losses.append(float(loss))
  ev = make_sparse_eval_step(model, plan, rule, mesh, state, bt)
  preds = np.asarray(ev(state, *bt[:2]))
  params, _ = unpack_sparse_state(plan, rule, jax.device_get(state))
  return losses, preds, params


def test_eval_bitexact_f32_dedup():
  """Same state, same batch: the dedup'd exchange must reproduce the raw
  exchange's predictions to the bit."""
  model, plan_a, rule, opt, state, bt, mesh = _fused_setup("adagrad")
  _, plan_b, *_ = _fused_setup("adagrad", dedup_exchange=True)
  ev_a = make_sparse_eval_step(model, plan_a, rule, mesh, state, bt)
  ev_b = make_sparse_eval_step(model, plan_b, rule, mesh, state, bt)
  np.testing.assert_array_equal(np.asarray(ev_a(state, *bt[:2])),
                                np.asarray(ev_b(state, *bt[:2])))


def test_train_f32_dedup_trajectory():
  """sgd (a linear rule) under dedup applies the mathematically identical
  update — only duplicate-summation associativity differs (segment-sum
  before the scatter vs inside it), so the trajectory is pinned at 1e-6
  absolute; the first step's loss (pure forward) is bit-exact."""
  la, pa, para = _run_steps("sgd")
  lb, pb, parb = _run_steps("sgd", dedup_exchange=True)
  assert la[0] == lb[0]
  np.testing.assert_allclose(la, lb, rtol=0, atol=1e-6)
  for k in para["embeddings"]:
    np.testing.assert_allclose(np.asarray(para["embeddings"][k]),
                               np.asarray(parb["embeddings"][k]),
                               rtol=0, atol=1e-6, err_msg=k)


def test_train_adagrad_dedup_semantics_close():
  """Nonlinear rules under dedup get per-UNIQUE delta semantics within
  each exchange block (the exact=True semantics restricted to one
  block): same gradient mass, second-order (lr * g^2-scale) deviation
  from the per-occurrence seed path."""
  la, pa, _ = _run_steps("adagrad")
  lb, pb, _ = _run_steps("adagrad", dedup_exchange=True)
  assert la[0] == lb[0]
  np.testing.assert_allclose(la, lb, rtol=0, atol=1e-5)
  np.testing.assert_allclose(pa, pb, rtol=0, atol=1e-4)


def test_train_bf16_dedup_converges_close():
  la, _, _ = _run_steps("sgd")
  lb, _, _ = _run_steps("sgd", dedup_exchange=True, wire_dtype="bf16")
  np.testing.assert_allclose(la, lb, rtol=0, atol=5e-3)


def test_micro_batch_with_dedup():
  la, pa, para = _run_steps("adagrad", step_kw={"micro_batches": 2})
  lb, pb, parb = _run_steps("adagrad", step_kw={"micro_batches": 2},
                            dedup_exchange=True)
  assert la[0] == lb[0]  # forward (and the scanned loss sum) is exact
  np.testing.assert_allclose(la, lb, rtol=0, atol=1e-5)
  np.testing.assert_allclose(pa, pb, rtol=0, atol=1e-4)


def test_guarded_step_with_dedup_skips_poison_batch():
  model, plan, rule, opt, state, bt, mesh = _fused_setup(
      "adagrad", dedup_exchange=True, wire_dtype="bf16")
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, bt, donate=False, guard=True)
  state1, loss, metrics = step(state, *bt)
  assert int(metrics["bad_step"]) == 0
  # poison labels -> NaN loss: the guarded step must commit NOTHING
  bad_labels = jnp.full_like(bt[2], jnp.nan)
  state2, loss2, metrics2 = step(state1, bt[0], bt[1], bad_labels)
  assert int(metrics2["bad_step"]) == 1
  before = jax.device_get(state1)
  after = jax.device_get(state2)
  for name in before["fused"]:
    np.testing.assert_array_equal(np.asarray(before["fused"][name]),
                                  np.asarray(after["fused"][name]))
  assert int(after["step"]) == int(before["step"])


def test_eval_metrics_oov_counts():
  """The eval path surfaces the per-class OOV counters (with_metrics) —
  the serving-side observability the ROADMAP resilience follow-on asked
  for; counters are global (psum'd) occurrence counts."""
  model, plan, rule, opt, state, bt, mesh = _fused_setup(
      "adagrad", dedup_exchange=True)
  ev = make_sparse_eval_step(model, plan, rule, mesh, state, bt,
                             with_metrics=True)
  preds, metrics = ev(state, *bt[:2])
  assert all(int(v) == 0 for v in metrics["oov"].values())
  # drive input 0 (97-row table, sparse class) out of vocabulary
  cats = list(bt[1])
  oov_ids = jnp.full_like(cats[2], 10_000)
  cats[2] = oov_ids
  preds2, metrics2 = ev(state, bt[0], cats)
  total = sum(int(v) for v in metrics2["oov"].values())
  assert total == int(np.prod(np.asarray(oov_ids).shape))


# ---------------------------------------------------------------------------
# knob validation / build-time contracts
# ---------------------------------------------------------------------------


def test_wire_dtype_validation():
  with pytest.raises(ValueError, match="wire_dtype"):
    DistEmbeddingStrategy([TableConfig(8, 4)], 1, wire_dtype="f16")


def test_exact_rejects_bf16_wire_sparse_and_tiered():
  model, plan, rule, opt, state, bt, mesh = _fused_setup(
      "adagrad", wire_dtype="bf16")
  with pytest.raises(ValueError, match="wire_dtype='f32'"):
    make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh, state,
                           bt, donate=False, exact=True)
  from distributed_embeddings_tpu.models.dlrm import _dlrm_initializer
  from distributed_embeddings_tpu.tiering import TieringConfig, TieringPlan
  from distributed_embeddings_tpu.training import make_tiered_train_step
  plan_t = DistEmbeddingStrategy(
      [TableConfig(5000, 16, initializer=_dlrm_initializer(5000)),
       TableConfig(300, 16, initializer=_dlrm_initializer(300))],
      WORLD, "memory_balanced", host_row_threshold=1000,
      wire_dtype="bf16")
  tplan = TieringPlan(plan_t, rule, TieringConfig(cache_fraction=0.3,
                                                  staging_grps=64))
  with pytest.raises(ValueError, match="wire_dtype='f32'"):
    make_tiered_train_step(model, tplan, bce_loss, opt, rule, mesh,
                           state, bt, donate=False, exact=True)


def test_exact_composes_with_dedup_f32():
  model, plan, rule, opt, state, bt, mesh = _fused_setup(
      "adagrad", dedup_exchange=True)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, bt, donate=False, exact=True)
  _, loss = step(state, *bt)
  assert np.isfinite(float(loss))


def test_exchange_report():
  tables, tmap, hotness = expand_tables(CFG)
  plan = DistEmbeddingStrategy(
      tables, WORLD, "memory_balanced", input_table_map=tmap,
      input_hotness=hotness, dense_row_threshold=60,
      wire_dtype="bf16", dedup_exchange=True)
  rep = plan.exchange_report()
  assert rep["wire_dtype"] == "bf16"
  assert rep["float_wire_bytes_per_value"] == 2
  assert rep["dedup_exchange"] is True
  kinds = {c["kind"] for c in rep["classes"].values()}
  assert kinds == {"sparse", "dense"}
  for c in rep["classes"].values():
    assert c["dedup"] == (c["kind"] == "sparse")
  # world 1: no wire, nothing to dedup
  rep1 = DistEmbeddingStrategy([TableConfig(100, 8)], 1,
                               dedup_exchange=True).exchange_report()
  assert not any(c["dedup"] for c in rep1["classes"].values())


# ---------------------------------------------------------------------------
# round 7: pipelined (chunked ppermute) exchange — bit-exact parity matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("world,chunks", [
    (1, 2),   # no wire: knobs must be inert, not crash
    (2, 2),
    (4, 1),   # pure ppermute rewrite, no chunking
    (4, 2),
    (4, 3),   # does not divide the per-destination payload
    (4, 5),   # exceeds some buckets' column counts (all-padding chunks)
])
@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_pipelined_f32_forward_bitexact(combiner, world, chunks):
  """The pipelined f32 exchange is pure data movement: forward outputs
  must equal the monolithic wire's TO THE BIT, at every world size and
  for chunk counts that do not divide the payload."""
  rng = np.random.default_rng(10)
  plan_a, params_a, inputs_a = _mixed_fixture(combiner, rng, world=world)
  rng = np.random.default_rng(10)
  plan_b, params_b, inputs_b = _mixed_fixture(
      combiner, rng, world=world, overlap="pipelined",
      exchange_chunks=chunks)
  out_a = _forward_outs(plan_a, params_a, inputs_a, world=world)
  out_b = _forward_outs(plan_b, params_b, inputs_b, world=world)
  for t, (a, b) in enumerate(zip(out_a, out_b)):
    np.testing.assert_array_equal(a, b, err_msg=f"table {t}")


def test_pipelined_f32_dedup_forward_bitexact():
  """Pipelined x dedup'd routing: the unique blocks and their return
  rows ride the ppermute pipeline — still bit-exact vs the raw
  monolithic exchange."""
  rng = np.random.default_rng(11)
  plan_a, params_a, inputs_a = _mixed_fixture("mean", rng)
  rng = np.random.default_rng(11)
  plan_b, params_b, inputs_b = _mixed_fixture(
      "mean", rng, dedup_exchange=True, overlap="pipelined",
      exchange_chunks=3)
  out_a = _forward_outs(plan_a, params_a, inputs_a)
  out_b = _forward_outs(plan_b, params_b, inputs_b)
  for t, (a, b) in enumerate(zip(out_a, out_b)):
    np.testing.assert_array_equal(a, b, err_msg=f"table {t}")


def test_pipelined_train_eval_bitexact():
  """Full fused train steps under the pipelined f32 wire: losses, eval
  predictions AND final packed tables are bit-identical to the
  monolithic wire's — the reverse cotangent pipeline (custom_vjp) must
  deliver exactly the same bits too."""
  la, pa, para = _run_steps("adagrad")
  lb, pb, parb = _run_steps("adagrad", overlap="pipelined",
                            exchange_chunks=2)
  assert la == lb
  np.testing.assert_array_equal(pa, pb)
  for k in para["embeddings"]:
    np.testing.assert_array_equal(np.asarray(para["embeddings"][k]),
                                  np.asarray(parb["embeddings"][k]),
                                  err_msg=k)


def test_pipelined_dedup_train_bitexact():
  la, pa, _ = _run_steps("adagrad", dedup_exchange=True)
  lb, pb, _ = _run_steps("adagrad", dedup_exchange=True,
                         overlap="pipelined", exchange_chunks=3)
  assert la == lb
  np.testing.assert_array_equal(pa, pb)


def test_pipelined_micro_batch_bitexact():
  la, pa, para = _run_steps("adagrad", step_kw={"micro_batches": 2})
  lb, pb, parb = _run_steps("adagrad", step_kw={"micro_batches": 2},
                            overlap="pipelined", exchange_chunks=2)
  assert la == lb
  np.testing.assert_array_equal(pa, pb)
  for k in para["embeddings"]:
    np.testing.assert_array_equal(np.asarray(para["embeddings"][k]),
                                  np.asarray(parb["embeddings"][k]),
                                  err_msg=k)


def test_pipelined_guarded_step_skips_poison_batch():
  """The guard composes with the pipelined wire: a poison batch commits
  nothing (state bit-identical), and good steps equal the monolithic
  guarded step's."""
  model, plan, rule, opt, state, bt, mesh = _fused_setup(
      "adagrad", dedup_exchange=True, overlap="pipelined",
      exchange_chunks=2)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, bt, donate=False, guard=True)
  state1, loss, metrics = step(state, *bt)
  assert int(metrics["bad_step"]) == 0
  bad_labels = jnp.full_like(bt[2], jnp.nan)
  state2, loss2, metrics2 = step(state1, bt[0], bt[1], bad_labels)
  assert int(metrics2["bad_step"]) == 1
  before = jax.device_get(state1)
  after = jax.device_get(state2)
  for name in before["fused"]:
    np.testing.assert_array_equal(np.asarray(before["fused"][name]),
                                  np.asarray(after["fused"][name]))
  assert int(after["step"]) == int(before["step"])


def test_pipelined_exact_composes_f32():
  """exact=True + pipelined f32: the bit-for-bit dedup'd backward claim
  survives a pure-data-movement rewrite, so the builder accepts it."""
  model, plan, rule, opt, state, bt, mesh = _fused_setup(
      "adagrad", overlap="pipelined", exchange_chunks=2)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, bt, donate=False, exact=True)
  _, loss = step(state, *bt)
  assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# round 7: fp8 wire — error bound, scale shipping, composition
# ---------------------------------------------------------------------------


def test_forward_fp8_wire_tolerance_bound():
  """The fp8 analog of the documented bf16 bound: float8_e4m3 carries 3
  mantissa bits (half-ulp 2^-4 relative for normals) and the per-block
  amax scaling keeps every element in the normal range relative to the
  block's max, so one exchange round-trip bounds each output element by
  ``h * 2^-4 * max|row|``; asserted with a 2x margin (h * 2^-3)."""
  rng = np.random.default_rng(12)
  plan_a, params, inputs = _mixed_fixture("sum", rng)
  rng = np.random.default_rng(12)
  plan_b, params_b, inputs_b = _mixed_fixture("sum", rng,
                                              wire_dtype="fp8")
  out_a = _forward_outs(plan_a, params, inputs)
  out_b = _forward_outs(plan_b, params_b, inputs_b)
  h = 3
  for t, (a, b) in enumerate(zip(out_a, out_b)):
    bound = h * 2.0 ** -3 * np.abs(a).max() + 1e-6
    assert np.abs(a - b).max() <= bound, (t, np.abs(a - b).max(), bound)
    assert np.abs(a - b).max() > 0  # the wire really narrowed something


def test_fp8_pipelined_matches_monolithic_one_chunk():
  """With one chunk the pipelined fp8 wire quantizes over exactly the
  blocks the monolithic wire does (same per-destination amax), so the
  two schedules must agree to the bit."""
  rng = np.random.default_rng(13)
  plan_a, params_a, inputs_a = _mixed_fixture("sum", rng,
                                              wire_dtype="fp8")
  rng = np.random.default_rng(13)
  plan_b, params_b, inputs_b = _mixed_fixture(
      "sum", rng, wire_dtype="fp8", overlap="pipelined",
      exchange_chunks=1)
  out_a = _forward_outs(plan_a, params_a, inputs_a)
  out_b = _forward_outs(plan_b, params_b, inputs_b)
  for t, (a, b) in enumerate(zip(out_a, out_b)):
    np.testing.assert_array_equal(a, b, err_msg=f"table {t}")


def test_train_fp8_pipelined_dedup_converges_close():
  """The full composition — fp8 wire x dedup'd routing x chunked
  pipeline — trains within a loose tolerance of the f32 seed path (the
  fp8 wire is a serving/throughput knob, not a precision claim)."""
  la, _, _ = _run_steps("sgd")
  lb, _, _ = _run_steps("sgd", wire_dtype="fp8", dedup_exchange=True,
                        overlap="pipelined", exchange_chunks=2)
  assert all(np.isfinite(lb))
  np.testing.assert_allclose(la, lb, rtol=0, atol=5e-2)


def test_exact_rejects_fp8_wire():
  model, plan, rule, opt, state, bt, mesh = _fused_setup(
      "adagrad", wire_dtype="fp8")
  with pytest.raises(ValueError, match="wire_dtype='f32'"):
    make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh, state,
                           bt, donate=False, exact=True)


# ---------------------------------------------------------------------------
# round 7: knob validation + reporting
# ---------------------------------------------------------------------------


def test_overlap_knob_validation():
  with pytest.raises(ValueError, match="overlap"):
    DistEmbeddingStrategy([TableConfig(8, 4)], 1, overlap="async")
  with pytest.raises(ValueError, match="exchange_chunks"):
    DistEmbeddingStrategy([TableConfig(8, 4)], 1, exchange_chunks=0)
  # chunks without the pipeline would be silently ignored -> refused
  with pytest.raises(ValueError, match="overlap='pipelined'"):
    DistEmbeddingStrategy([TableConfig(8, 4)], 1, exchange_chunks=2)
  # round 20: fused is a registered overlap and carries the chunk axis
  DistEmbeddingStrategy([TableConfig(8, 4)], 1, overlap="fused",
                        exchange_chunks=2)
  # fp8 is a registered wire dtype now; junk still isn't
  DistEmbeddingStrategy([TableConfig(8, 4)], 1, wire_dtype="fp8")
  with pytest.raises(ValueError, match="wire_dtype"):
    DistEmbeddingStrategy([TableConfig(8, 4)], 1, wire_dtype="f8")


def test_exchange_report_rounds_geometry():
  tables, tmap, hotness = expand_tables(CFG)
  plan = DistEmbeddingStrategy(
      tables, WORLD, "memory_balanced", input_table_map=tmap,
      input_hotness=hotness, dense_row_threshold=60,
      wire_dtype="fp8", dedup_exchange=True, overlap="pipelined",
      exchange_chunks=3)
  rep = plan.exchange_report()
  assert rep["overlap"] == "pipelined"
  assert rep["exchange_chunks"] == 3
  assert rep["rounds_per_exchange"] == (WORLD - 1) * 3
  assert rep["float_wire_bytes_per_value"] == 1
  assert rep["jit_gather"] is False  # fused-only flag
  # monolithic: one all_to_all per exchange; world 1: no wire at all
  rep_m = DistEmbeddingStrategy([TableConfig(100, 8)], WORLD).exchange_report()
  assert rep_m["overlap"] == "none" and rep_m["rounds_per_exchange"] == 1
  rep_1 = DistEmbeddingStrategy([TableConfig(100, 8)], 1,
                                overlap="pipelined").exchange_report()
  assert rep_1["rounds_per_exchange"] == 0


# ---------------------------------------------------------------------------
# round 7: dedup_capacity override + overflow counter
# ---------------------------------------------------------------------------


def test_dedup_capacity_validation():
  with pytest.raises(ValueError, match="dedup_exchange"):
    DistEmbeddingStrategy([TableConfig(8, 4)], 1, dedup_capacity=16)
  with pytest.raises(ValueError, match="dedup_capacity"):
    DistEmbeddingStrategy([TableConfig(8, 4)], 1, dedup_exchange=True,
                          dedup_capacity=0)


def test_dedup_capacity_refused_without_counter_path():
  """A silent smaller cap would alias ids: every builder without the
  overflow-counter path must refuse a capped plan at build time."""
  model, plan, rule, opt, state, bt, mesh = _fused_setup(
      "adagrad", dedup_exchange=True, dedup_capacity=3)
  with pytest.raises(ValueError, match="dedup_capacity"):
    make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh, state,
                           bt, donate=False)  # unguarded
  with pytest.raises(ValueError, match="dedup_capacity"):
    make_sparse_eval_step(model, plan, rule, mesh, state, bt)  # no metrics
  from distributed_embeddings_tpu.training import make_train_step
  with pytest.raises(NotImplementedError, match="dedup_capacity"):
    make_train_step(lambda p, *b: 0.0, opt, mesh, {}, {}, bt, plan=plan)


def test_dedup_capacity_overflow_counter():
  """A cap below the per-block distinct count must show up in the
  guarded step's psum'd ``dedup_overflow`` metric (and the with-metrics
  eval's); a generous cap reports zero."""
  model, plan, rule, opt, state, bt, mesh = _fused_setup(
      "adagrad", dedup_exchange=True, dedup_capacity=3)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, bt, donate=False, guard=True)
  _, _, metrics = step(state, *bt)
  assert sum(int(v) for v in metrics["dedup_overflow"].values()) > 0
  # only sparse-kind classes dedup, so only they can overflow
  for name, v in metrics["dedup_overflow"].items():
    if "dense" in name:
      assert int(v) == 0
  ev = make_sparse_eval_step(model, plan, rule, mesh, state, bt,
                             with_metrics=True)
  _, em = ev(state, *bt[:2])
  assert sum(int(v) for v in em["dedup_overflow"].values()) > 0
  # micro-batch composition: per-micro-batch counts ride the scan
  # outputs and sum into the same metric
  step_mb = make_sparse_train_step(model, plan, bce_loss, opt, rule,
                                   mesh, state, bt, donate=False,
                                   guard=True, micro_batches=2)
  _, _, m_mb = step_mb(state, *bt)
  assert sum(int(v) for v in m_mb["dedup_overflow"].values()) > 0

  model, plan2, rule, opt, state2, bt2, mesh = _fused_setup(
      "adagrad", dedup_exchange=True, dedup_capacity=1 << 20)
  step2 = make_sparse_train_step(model, plan2, bce_loss, opt, rule, mesh,
                                 state2, bt2, donate=False, guard=True)
  _, _, m2 = step2(state2, *bt2)
  assert sum(int(v) for v in m2["dedup_overflow"].values()) == 0


def test_dedup_capacity_safe_cap_is_exact():
  """A capacity at (or above) the safe bound changes nothing: outputs
  stay bit-exact vs the uncapped dedup'd exchange and the counter stays
  zero — the knob only bites when it actually caps."""
  rng = np.random.default_rng(14)
  plan_a, params_a, inputs_a = _mixed_fixture("sum", rng,
                                              dedup_exchange=True)
  rng = np.random.default_rng(14)
  plan_b, params_b, inputs_b = _mixed_fixture(
      "sum", rng, dedup_exchange=True, dedup_capacity=1 << 20)
  out_a = _forward_outs(plan_a, params_a, inputs_a)
  out_b = _forward_outs(plan_b, params_b, inputs_b)
  for t, (a, b) in enumerate(zip(out_a, out_b)):
    np.testing.assert_array_equal(a, b, err_msg=f"table {t}")


def test_route_ids_emits_dedup_routed():
  tables, tmap, hotness = expand_tables(CFG)
  plan = DistEmbeddingStrategy(
      tables, WORLD, "memory_balanced", input_table_map=tmap,
      input_hotness=hotness, dense_row_threshold=60,
      dedup_exchange=True)
  engine = DistributedLookup(plan)
  mesh = create_mesh(WORLD)
  _, cats, _ = generate_batch(CFG, 4 * WORLD, alpha=1.1, seed=9)
  cats = [jnp.asarray(np.minimum(c, tables[t].input_dim - 1)
                      .astype(np.int32))
          for c, t in zip(cats, tmap)]

  kinds = {}

  def probe(*xs):
    ids_all = engine.route_ids(list(xs))
    for bk, ids in ids_all.items():
      kinds[bk] = type(ids).__name__
      if isinstance(ids, DedupRouted):
        # capacity bound: K = min(block occurrences, sentinel + 1)
        assert ids.uniq.shape == ids.uniq_local.shape
        assert ids.uniq.shape[0] == WORLD
    return xs[0]

  jax.jit(shard_map(probe, mesh=mesh,
                    in_specs=tuple(P("mp") for _ in cats),
                    out_specs=P("mp")))(*cats)
  by_kind = {plan.classes[bk.class_key].kind for bk in kinds}
  assert by_kind == {"sparse", "dense"}
  for bk, tname in kinds.items():
    want = ("DedupRouted"
            if plan.classes[bk.class_key].kind == "sparse" else "ndarray")
    got = tname if tname == "DedupRouted" else "ndarray"
    assert got == want, (bk, tname)


# ---------------------------------------------------------------------------
# round 20: fused (just-in-time gather) exchange — bit-exact parity matrix
# ---------------------------------------------------------------------------
#
# ``overlap='fused'`` restructures WHEN each wire round's payload is
# gathered (immediately before its send, per (round, chunk)) but not WHAT
# is gathered: every per-chunk gather + combine is elementwise over the
# same (slot, sample, h) values the monolithic pre-pass reads, and all
# placement is dynamic_slice / stack / take / reshape — pure data
# movement. f32 must therefore be BIT-exact vs the monolithic wire AND vs
# the pipelined schedule, forward and reverse (the backward rounds fall
# out of native autodiff of the per-round sends). bf16 narrows
# elementwise (same bits as pipelined); fp8 chunks split the gathered
# ROWS rather than the flat payload, so its amax windows differ from the
# pipelined wire's — fp8 fused is tolerance-compared against f32 and
# bit-compared against the monolithic wire only at chunks=1 (one window =
# the whole destination block, same as the monolithic amax).


@pytest.mark.parametrize("world,chunks", [
    (1, 2),   # no wire: fused must be inert, not crash
    (2, 2),
    (4, 1),   # one chunk per round: pure schedule rewrite
    (4, 2),
    (4, 3),   # does not divide some blocks' row counts
    (4, 5),   # exceeds some blocks' row counts (chunk count caps at rows)
])
@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_fused_f32_forward_bitexact(combiner, world, chunks):
  rng = np.random.default_rng(20)
  plan_a, params_a, inputs_a = _mixed_fixture(combiner, rng, world=world)
  rng = np.random.default_rng(20)
  plan_b, params_b, inputs_b = _mixed_fixture(
      combiner, rng, world=world, overlap="fused", exchange_chunks=chunks)
  out_a = _forward_outs(plan_a, params_a, inputs_a, world=world)
  out_b = _forward_outs(plan_b, params_b, inputs_b, world=world)
  for t, (a, b) in enumerate(zip(out_a, out_b)):
    np.testing.assert_array_equal(a, b, err_msg=f"table {t}")


def test_fused_f32_dedup_forward_bitexact():
  """Fused x dedup'd routing: each round's unique-block rows are gathered
  just-in-time and the return rows expand per round — still bit-exact vs
  the raw monolithic exchange."""
  rng = np.random.default_rng(21)
  plan_a, params_a, inputs_a = _mixed_fixture("mean", rng)
  rng = np.random.default_rng(21)
  plan_b, params_b, inputs_b = _mixed_fixture(
      "mean", rng, dedup_exchange=True, overlap="fused",
      exchange_chunks=3)
  out_a = _forward_outs(plan_a, params_a, inputs_a)
  out_b = _forward_outs(plan_b, params_b, inputs_b)
  for t, (a, b) in enumerate(zip(out_a, out_b)):
    np.testing.assert_array_equal(a, b, err_msg=f"table {t}")


def test_fused_train_eval_bitexact_vs_monolithic_and_pipelined():
  """Full train steps under the fused f32 wire: losses, eval predictions
  AND final packed tables bit-identical to BOTH the monolithic and the
  pipelined schedules — the per-round reverse cotangent sends (native
  autodiff of the round body) deliver exactly the same bits."""
  la, pa, para = _run_steps("adagrad")
  lp, pp, parp = _run_steps("adagrad", overlap="pipelined",
                            exchange_chunks=2)
  lb, pb, parb = _run_steps("adagrad", overlap="fused",
                            exchange_chunks=2)
  assert la == lb == lp
  np.testing.assert_array_equal(pa, pb)
  np.testing.assert_array_equal(pp, pb)
  for k in para["embeddings"]:
    np.testing.assert_array_equal(np.asarray(para["embeddings"][k]),
                                  np.asarray(parb["embeddings"][k]),
                                  err_msg=k)
    np.testing.assert_array_equal(np.asarray(parp["embeddings"][k]),
                                  np.asarray(parb["embeddings"][k]),
                                  err_msg=k)


def test_fused_dedup_train_bitexact():
  """The dedup'd backward's per-round form: cotangent chunks ship per
  round and segment-sum between sends — same bits as the monolithic
  dedup'd exchange."""
  la, pa, _ = _run_steps("adagrad", dedup_exchange=True)
  lb, pb, _ = _run_steps("adagrad", dedup_exchange=True,
                         overlap="fused", exchange_chunks=3)
  assert la == lb
  np.testing.assert_array_equal(pa, pb)


def test_fused_micro_batch_bitexact():
  la, pa, para = _run_steps("adagrad", step_kw={"micro_batches": 2})
  lb, pb, parb = _run_steps("adagrad", step_kw={"micro_batches": 2},
                            overlap="fused", exchange_chunks=2)
  assert la == lb
  np.testing.assert_array_equal(pa, pb)
  for k in para["embeddings"]:
    np.testing.assert_array_equal(np.asarray(para["embeddings"][k]),
                                  np.asarray(parb["embeddings"][k]),
                                  err_msg=k)


def test_fused_guarded_step_skips_poison_batch():
  """The guard composes with the fused wire: a poison batch commits
  nothing (state bit-identical), good steps commit."""
  model, plan, rule, opt, state, bt, mesh = _fused_setup(
      "adagrad", dedup_exchange=True, overlap="fused",
      exchange_chunks=2)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, bt, donate=False, guard=True)
  state1, loss, metrics = step(state, *bt)
  assert int(metrics["bad_step"]) == 0
  bad_labels = jnp.full_like(bt[2], jnp.nan)
  state2, loss2, metrics2 = step(state1, bt[0], bt[1], bad_labels)
  assert int(metrics2["bad_step"]) == 1
  before = jax.device_get(state1)
  after = jax.device_get(state2)
  for name in before["fused"]:
    np.testing.assert_array_equal(np.asarray(before["fused"][name]),
                                  np.asarray(after["fused"][name]))
  assert int(after["step"]) == int(before["step"])


def test_fused_exact_composes_f32():
  """exact=True + fused f32: a pure-data-movement rewrite keeps the
  bit-for-bit dedup'd backward claim, so the builder accepts it."""
  model, plan, rule, opt, state, bt, mesh = _fused_setup(
      "adagrad", overlap="fused", exchange_chunks=2)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, bt, donate=False, exact=True)
  _, loss = step(state, *bt)
  assert np.isfinite(float(loss))


def test_fused_bf16_matches_pipelined_bitexact():
  """bf16 narrows each payload element independently of the chunk
  geometry (no per-block scale), so the fused schedule's bf16 bits equal
  the pipelined schedule's exactly."""
  rng = np.random.default_rng(22)
  plan_a, params_a, inputs_a = _mixed_fixture(
      "sum", rng, wire_dtype="bf16", overlap="pipelined",
      exchange_chunks=2)
  rng = np.random.default_rng(22)
  plan_b, params_b, inputs_b = _mixed_fixture(
      "sum", rng, wire_dtype="bf16", overlap="fused", exchange_chunks=2)
  out_a = _forward_outs(plan_a, params_a, inputs_a)
  out_b = _forward_outs(plan_b, params_b, inputs_b)
  for t, (a, b) in enumerate(zip(out_a, out_b)):
    np.testing.assert_array_equal(a, b, err_msg=f"table {t}")


def test_fp8_fused_matches_monolithic_one_chunk():
  """With one chunk the fused fp8 wire's amax window is the whole
  per-destination block — the same window the monolithic wire uses, so
  the two schedules agree to the bit."""
  rng = np.random.default_rng(23)
  plan_a, params_a, inputs_a = _mixed_fixture("sum", rng,
                                              wire_dtype="fp8")
  rng = np.random.default_rng(23)
  plan_b, params_b, inputs_b = _mixed_fixture(
      "sum", rng, wire_dtype="fp8", overlap="fused", exchange_chunks=1)
  out_a = _forward_outs(plan_a, params_a, inputs_a)
  out_b = _forward_outs(plan_b, params_b, inputs_b)
  for t, (a, b) in enumerate(zip(out_a, out_b)):
    np.testing.assert_array_equal(a, b, err_msg=f"table {t}")


def test_train_fp8_fused_dedup_converges_close():
  """fp8 x dedup x fused: the row-chunked amax windows differ from the
  pipelined wire's flat-payload windows, so the claim is the f32-relative
  tolerance, not bitwise agreement with another fp8 schedule."""
  la, _, _ = _run_steps("sgd")
  lb, _, _ = _run_steps("sgd", wire_dtype="fp8", dedup_exchange=True,
                        overlap="fused", exchange_chunks=2)
  assert all(np.isfinite(lb))
  np.testing.assert_allclose(la, lb, rtol=0, atol=5e-2)


def test_fused_report_and_gate():
  """exchange_report announces the just-in-time gather schedule, and the
  DE_TPU_PALLAS_EXCHANGE gate stays off on the CPU proxy even when
  forced (no tier-1 behavior change with the env flag set)."""
  tables, tmap, hotness = expand_tables(CFG)
  plan = DistEmbeddingStrategy(
      tables, WORLD, "memory_balanced", input_table_map=tmap,
      input_hotness=hotness, dense_row_threshold=60,
      overlap="fused", exchange_chunks=2)
  rep = plan.exchange_report()
  assert rep["overlap"] == "fused"
  assert rep["jit_gather"] is True
  assert rep["rounds_per_exchange"] == (WORLD - 1) * 2
  # world 1: fused is inert, no jit-gather schedule to run
  rep1 = DistEmbeddingStrategy([TableConfig(100, 8)], 1,
                               overlap="fused").exchange_report()
  assert rep1["jit_gather"] is False

  from distributed_embeddings_tpu.ops import pallas_exchange
  import os
  old = os.environ.get("DE_TPU_PALLAS_EXCHANGE")
  os.environ["DE_TPU_PALLAS_EXCHANGE"] = "1"
  try:
    assert not pallas_exchange._use_pallas_exchange()
    rng = np.random.default_rng(24)
    plan_a, params_a, inputs_a = _mixed_fixture("sum", rng)
    rng = np.random.default_rng(24)
    plan_b, params_b, inputs_b = _mixed_fixture(
        "sum", rng, overlap="fused", exchange_chunks=2)
    out_a = _forward_outs(plan_a, params_a, inputs_a)
    out_b = _forward_outs(plan_b, params_b, inputs_b)
    for t, (a, b) in enumerate(zip(out_a, out_b)):
      np.testing.assert_array_equal(a, b, err_msg=f"table {t}")
  finally:
    if old is None:
      del os.environ["DE_TPU_PALLAS_EXCHANGE"]
    else:
      os.environ["DE_TPU_PALLAS_EXCHANGE"] = old


@pytest.mark.slow
def test_profile_exchange_occupancy_full_sweep():
  """The full fused-exchange pricing (`tools/profile_exchange.py
  --overlap-occupancy`: pipelined f32 vs fused f32/fp8 at production
  scale, per-round wall + gather-hidden accounting + the fused <=
  pipelined step bar) passes its acceptance; the smoke tier rides
  `make verify` as exchange-smoke instead."""
  import subprocess
  import sys
  repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
  env = dict(os.environ)
  env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
  r = subprocess.run(
      [sys.executable, os.path.join(repo, "tools", "profile_exchange.py"),
       "--overlap-occupancy"],
      env=env, capture_output=True, text=True, timeout=1200)
  assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-4000:]
