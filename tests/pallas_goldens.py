"""Shared golden vectors for the Pallas twin pairs.

One case list drives every consumer, so the sim and TPU builds of a
kernel are checked against the SAME streams:

- `tests/test_pallas_goldens.py` (tier-1, CPU): the apply simulator
  (`ops/pallas_apply_sim.py`) against ``np.add.at`` at the documented
  f32-associativity tolerance, and the exchange interpret twin
  (`ops/pallas_exchange_sim.py`) against ``packed_table.gather_fused``
  BIT-for-bit (a gather is pure data movement — no summation order to
  forgive).
- `tools/smoke_pallas_apply.py` (real TPU): the hardware apply kernel
  replays the same cases against XLA's scatter AND against the
  simulator, so a hardware/sim divergence fails with the exact case
  name tier-1 already knows.

The directed names pin the state-machine corners (duplicate hits, slot
collision chains, eviction round-trips, OOB drops, cross-chunk
persistence); the seeded names add power-law and uniform fuzz at fixed
seeds so every consumer sees identical streams.
"""

import numpy as np

APPLY_WIDTH = 8        # apply-pair row width (the sim is width-agnostic)
EXCHANGE_LANES = 128   # exchange kernel serves 128-lane physical rows

# name -> (rows, slots, chunk, builder). ``slots`` parameterizes the
# apply pair's cache; ``chunk`` the exchange pair's double buffer.
_CASES = {}


def _case(name, rows, slots, chunk):
  def deco(fn):
    _CASES[name] = (rows, slots, chunk, fn)
    return fn
  return deco


@_case("unique", rows=16, slots=4, chunk=4)
def _(rng, rows):
  return np.array([0, 1, 2, 3], np.int32)


@_case("duplicate_hits", rows=16, slots=4, chunk=4)
def _(rng, rows):
  return np.array([5, 5, 5], np.int32)


@_case("evict_and_return", rows=16, slots=4, chunk=2)
def _(rng, rows):
  return np.array([1, 5, 1], np.int32)


@_case("slot_collision_chain", rows=16, slots=4, chunk=4)
def _(rng, rows):
  return np.array([1, 5, 9, 13, 1, 5], np.int32)


@_case("alternating_evict", rows=32, slots=16, chunk=8)
def _(rng, rows):
  # two rows sharing one slot, alternating: every access evicts
  # (list repeat, values <= 19 — no overflow)
  return np.array([3, 19] * 30, np.int32)  # graftlint: disable=GL106


@_case("full_sweep_twice", rows=64, slots=16, chunk=32)
def _(rng, rows):
  # the second sweep must observe the first sweep's values
  return np.concatenate([np.arange(rows), np.arange(rows)]).astype(np.int32)


@_case("oob_mixed", rows=32, slots=4, chunk=4)
def _(rng, rows):
  return np.array([-1, 0, 31, 32, 1000, -2**31, 5, 5, 5], np.int32)


@_case("cross_chunk_duplicates", rows=128, slots=16, chunk=128)
def _(rng, rows):
  # duplicates recurring across chunk/grid boundaries: cache tags and
  # pending writes must persist between steps
  return np.asarray((list(range(100)) * 6)[:555], np.int32)


@_case("uniform_fuzz", rows=200, slots=32, chunk=64)
def _(rng, rows):
  return rng.integers(-3, 2 * rows, 400).astype(np.int32)


@_case("power_law", rows=256, slots=8, chunk=128)
def _(rng, rows):
  r = rng.random(2000)
  gamma = -0.05
  ids = ((r * (float(rows + 1) ** gamma - 1.0) + 1.0) ** (1.0 / gamma))
  return (np.clip(ids.astype(np.int64) - 1, 0, rows - 1)).astype(np.int32)


CASE_NAMES = tuple(_CASES)


def golden_ids(name):
  """(ids[int32], rows, slots, chunk) for one named case; the stream is
  a pure function of the name (seeded rng), identical in every
  consumer."""
  rows, slots, chunk, fn = _CASES[name]
  rng = np.random.default_rng(_seed(name))
  return fn(rng, rows), rows, slots, chunk


def _seed(name):
  # stable across processes (hash() is salted): fold the name's bytes
  return int.from_bytes(name.encode()[:8].ljust(8, b"\0"), "little") % (2**31)


def apply_vectors(name, width=APPLY_WIDTH):
  """(buf, ids, delta, slots, chunk) for the apply pair: the kernel /
  simulator compute ``buf[ids] += delta`` on these. The id stream and
  cache geometry are width-independent; tier-1 runs the simulator at
  ``APPLY_WIDTH`` while the TPU smoke replays the same streams at the
  hardware kernel's 128-lane row width."""
  ids, rows, slots, chunk = golden_ids(name)
  rng = np.random.default_rng(_seed(name) ^ 0xA11E)
  buf = rng.standard_normal((rows, width)).astype(np.float32)
  delta = rng.standard_normal((len(ids), width)).astype(np.float32)
  return buf, ids, delta, slots, chunk


def exchange_vectors(name):
  """(buf, ids, chunk) for the exchange pair: the kernel / interpret
  twin gather ``buf[ids]`` (OOB -> zero rows) through the
  double-buffered send staging."""
  ids, rows, _, chunk = golden_ids(name)
  rng = np.random.default_rng(_seed(name) ^ 0xE8C4)
  buf = rng.standard_normal((rows, EXCHANGE_LANES)).astype(np.float32)
  return buf, ids, chunk
