"""Multi-controller checkpoint/get_weights: 2 processes x 4 CPU devices.

Spawns two real JAX processes (jax.distributed over a localhost
coordinator, 4 virtual CPU devices each -> an 8-device global mesh),
builds a world-8 plan with GLOBAL sharded fused buffers, and verifies:

- checkpoint.save writes only locally-addressable rank blocks per process
  (never touching a global buffer), process 0 writes manifest/dense parts,
  and the barriers order the tmp-dir lifecycle;
- checkpoint.restore reassembles mesh-sharded buffers whose local shards
  match what each process saved;
- get_weights serves windows owned by local shards and raises the
  documented error for remote ones.

The reference solves the same problem with chunked hvd.allgather
(`dist_model_parallel.py:574-664`); here per-process files + a shared
filesystem replace the collectives.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from multiproc import spawn_world2  # noqa: E402

_WORKER = r"""
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_embeddings_tpu import checkpoint
from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.layers.embedding import TableConfig
from distributed_embeddings_tpu.layers.dist_model_parallel import get_weights
from distributed_embeddings_tpu.ops.packed_table import adagrad_rule
from distributed_embeddings_tpu.parallel.lookup_engine import (
    DistributedLookup, class_param_name)

WORLD = 8
tables = [TableConfig(input_dim=64 + 8 * t, output_dim=16, combiner="sum")
          for t in range(WORLD)]
plan = DistEmbeddingStrategy(tables, WORLD, "basic")
rule = adagrad_rule(0.01)
engine = DistributedLookup(plan)
layouts = engine.fused_layouts(rule)
mesh = Mesh(np.array(jax.devices()), ("mp",))

fused = {}
for key in plan.class_keys:
    name = class_param_name(*key)
    layout = layouts[name]
    shape = (WORLD * layout.phys_rows, layout.phys_width)
    sharding = NamedSharding(mesh, P("mp", None))
    def cb(index, layout=layout):
        r = (index[0].start or 0) // layout.phys_rows
        rng = np.random.default_rng(1234 + r)
        return rng.standard_normal(
            (layout.phys_rows, layout.phys_width)).astype(np.float32)
    fused[name] = jax.make_array_from_callback(shape, sharding, cb)
    assert not fused[name].is_fully_addressable

rep = NamedSharding(mesh, P())
dense = {"w": jax.device_put(jnp.arange(12, dtype=jnp.float32), rep)}
state = {"fused": fused, "dense": dense, "dense_opt": {},
         "emb_dense": {}, "emb_dense_opt": {},
         "step": jax.device_put(jnp.asarray(7, jnp.int32), rep)}

ckpt = os.path.join(tmpdir, "ckpt")
checkpoint.save(ckpt, plan, rule, state)

# every rank file must exist exactly once, written by the owning process
name0 = sorted(fused)[0]
for r in range(WORLD):
    assert os.path.exists(os.path.join(ckpt, f"fused_{name0}_r{r}.npy")), r
man = json.load(open(os.path.join(ckpt, "manifest.json")))
assert man["step"] == 7

restored = checkpoint.restore(ckpt, plan, rule, state, mesh=mesh)
for name, arr in restored["fused"].items():
    for shard in arr.addressable_shards:
        if shard.replica_id:
            continue
        r = (shard.index[0].start or 0) // layouts[name].phys_rows
        rng = np.random.default_rng(1234 + r)
        want = rng.standard_normal(np.asarray(shard.data).shape
                                   ).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(shard.data), want)

# get_weights must raise the documented error for remote windows
try:
    ws = get_weights(plan, fused)
    print("PROC", proc_id, "get_weights unexpectedly succeeded")
    sys.exit(2)
except RuntimeError as e:
    assert "not owned by this process" in str(e), e
print("PROC", proc_id, "OK")
"""


@pytest.mark.slow
def test_two_process_checkpoint(tmp_path):
  spawn_world2(tmp_path, _WORKER)
