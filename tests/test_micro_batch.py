"""Bounded-memory micro-batch accumulation (round 5, VERDICT item 4).

``make_sparse_train_step(..., micro_batches=n)`` must reproduce the
one-shot step's numerics for every rule: deltas are computed from each
micro-batch's own forward-gathered optimizer-state rows while the fused
buffers stay untouched until the final per-class scatter, so the only
difference from one-shot is fp addition order inside the scatter.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu.layers import (
    DistEmbeddingStrategy,
    TableConfig,
)
from distributed_embeddings_tpu.models import bce_loss
from distributed_embeddings_tpu.models.synthetic import (
    EmbeddingGroup,
    SyntheticModel,
    SyntheticModelConfig,
    expand_tables,
    generate_batch,
)
from distributed_embeddings_tpu.ops.packed_table import sparse_rule
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.training import (
    init_sparse_state_direct,
    make_sparse_train_step,
    shard_batch,
    shard_params,
    unpack_sparse_state,
)

CFG = SyntheticModelConfig(
    name="mbtest", embedding_groups=(
        EmbeddingGroup(2, (1, 5), 131, 8, True),   # shared multi-hot
        EmbeddingGroup(3, (1,), 97, 8, False),
        EmbeddingGroup(2, (3,), 53, 16, False),    # multi-hot narrow
    ),
    mlp_sizes=(32, 16), num_numerical_features=4, interact_stride=None)


def _setup(world, rule_name, mesh=None, batch=32):
  tables, tmap, hotness = expand_tables(CFG)
  model = SyntheticModel(CFG)
  rng = np.random.default_rng(7)
  numerical, cats, labels = generate_batch(CFG, batch, alpha=1.1, seed=8)
  cats = [np.minimum(c, tables[t].input_dim - 1).astype(np.int32)
          for c, t in zip(cats, tmap)]
  cats = [jnp.asarray(c if h > 1 else c[:, 0])
          for c, h in zip(cats, hotness)]
  batch_tree = (jnp.asarray(numerical), cats, jnp.asarray(labels))

  plan = DistEmbeddingStrategy(
      tables, world, "memory_balanced", input_table_map=tmap,
      input_hotness=hotness, dense_row_threshold=60, batch_hint=batch)
  rule = sparse_rule(rule_name, 0.005)
  opt = optax.adagrad(0.005)
  dummy = [jnp.zeros((2, t.output_dim), jnp.float32)
           for t in (tables[i] for i in tmap)]
  dense_params = model.init(jax.random.PRNGKey(0), batch_tree[0][:2],
                            [c[:2] for c in cats],
                            emb_acts=dummy)["params"]
  state = init_sparse_state_direct(plan, rule, dense_params, opt,
                                   jax.random.PRNGKey(1))
  if mesh is not None:
    state = shard_params(state, mesh)
    batch_tree = shard_batch(batch_tree, mesh)
  return model, plan, rule, opt, state, batch_tree


def _run(model, plan, rule, opt, state, batch_tree, mesh, n_mb, steps=2):
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, batch_tree, donate=False,
                                micro_batches=n_mb)
  losses = []
  for _ in range(steps):
    state, loss = step(state, *batch_tree)
    losses.append(float(loss))
  return state, losses


@pytest.mark.parametrize("rule_name", ["sgd", "adagrad", "momentum", "adam"])
def test_micro_batch_matches_one_shot_single_device(rule_name):
  model, plan, rule, opt, state, batch_tree = _setup(1, rule_name)
  s1, l1 = _run(model, plan, rule, opt, state, batch_tree, None, 1)
  s4, l4 = _run(model, plan, rule, opt, state, batch_tree, None, 4)
  np.testing.assert_allclose(l1, l4, rtol=1e-5, atol=1e-6)
  p1, _ = unpack_sparse_state(plan, rule, jax.device_get(s1))
  p4, _ = unpack_sparse_state(plan, rule, jax.device_get(s4))
  for name in p1["embeddings"]:
    np.testing.assert_allclose(
        np.asarray(p4["embeddings"][name]), np.asarray(p1["embeddings"][name]),
        rtol=1e-4, atol=1e-5, err_msg=name)
  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                              rtol=1e-4, atol=1e-5),
      p1["mlp"], p4["mlp"])


def test_micro_batch_matches_one_shot_mesh():
  world = 8
  mesh = create_mesh(world)
  model, plan, rule, opt, state, batch_tree = _setup(
      world, "adagrad", mesh=mesh, batch=8 * world)
  s1, l1 = _run(model, plan, rule, opt, state, batch_tree, mesh, 1)
  s2, l2 = _run(model, plan, rule, opt, state, batch_tree, mesh, 2)
  # accumulate-then-psum vs psum-per-micro-batch is an fp reordering of
  # the dense-grad sum; step 2 amplifies it through the updated weights
  np.testing.assert_allclose(l1, l2, rtol=1e-4, atol=1e-6)
  p1, _ = unpack_sparse_state(plan, rule, jax.device_get(s1))
  p2, _ = unpack_sparse_state(plan, rule, jax.device_get(s2))
  for name in p1["embeddings"]:
    np.testing.assert_allclose(
        np.asarray(p2["embeddings"][name]), np.asarray(p1["embeddings"][name]),
        rtol=1e-4, atol=1e-5, err_msg=name)


def test_micro_batch_guards():
  model, plan, rule, opt, state, batch_tree = _setup(1, "sgd")
  with pytest.raises(NotImplementedError, match="exact"):
    make_sparse_train_step(model, plan, bce_loss, opt, rule, None,
                           state, batch_tree, donate=False,
                           micro_batches=2, exact=True)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, None,
                                state, batch_tree, donate=False,
                                micro_batches=5)  # 32 % 5 != 0
  with pytest.raises(ValueError, match="not divisible"):
    step(state, *batch_tree)
