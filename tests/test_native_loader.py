"""Native (C++) Criteo loader: build, parity with the numpy path, dp slicing.

The reference ships its native code as a CUDA/C++ op library; here the native
surface is the data loader (``cc/data_loader.cc``) and these tests mirror the
reference's approach of validating the native path against a pure-Python
oracle (`/root/reference/distributed_embeddings/python/ops/embedding_lookup_ops_test.py`
validates custom ops against stock TF the same way).
"""

import numpy as np
import pytest

from distributed_embeddings_tpu.cc import build, load_data_loader
from distributed_embeddings_tpu.utils.data import (
    RawBinaryCriteoDataset,
    write_dummy_criteo_split,
)

VOCAB = [50, 40_000, 3_000_000]


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
  d = tmp_path_factory.mktemp("criteo")
  write_dummy_criteo_split(str(d), 1000, VOCAB, seed=3)
  return str(d)


def _kw(**over):
  kw = dict(batch_size=128, numerical_features=13,
            categorical_features=[0, 1, 2], categorical_feature_sizes=VOCAB)
  kw.update(over)
  return kw


def test_native_builds():
  assert build(), "native loader failed to build"
  assert load_data_loader() is not None


def _assert_batches_equal(a, b):
  assert len(a) == len(b)
  for (n1, c1, l1), (n2, c2, l2) in zip(a, b):
    np.testing.assert_array_equal(n1, n2)
    np.testing.assert_array_equal(l1, l2)
    assert len(c1) == len(c2)
    for x, y in zip(c1, c2):
      np.testing.assert_array_equal(x, y)


def test_native_matches_numpy(data_dir):
  a = list(RawBinaryCriteoDataset(data_dir, backend="numpy", **_kw()))
  b = list(RawBinaryCriteoDataset(data_dir, backend="native", **_kw()))
  assert len(a) == 1000 // 128
  _assert_batches_equal(a, b)


def test_native_dp_slicing(data_dir):
  for rank in range(4):
    a = list(RawBinaryCriteoDataset(
        data_dir, backend="numpy", rank=rank, world_size=4, **_kw()))
    b = list(RawBinaryCriteoDataset(
        data_dir, backend="native", rank=rank, world_size=4, **_kw()))
    _assert_batches_equal(a, b)


def test_native_feature_subset(data_dir):
  # mp input mode: a rank reads only its own tables' files
  kw = _kw(categorical_features=[2, 0])
  a = list(RawBinaryCriteoDataset(data_dir, backend="numpy", **kw))
  b = list(RawBinaryCriteoDataset(data_dir, backend="native", **kw))
  _assert_batches_equal(a, b)
  assert a[0][1][0].dtype == np.int32


def test_native_no_numerical(data_dir):
  kw = _kw(numerical_features=0)
  a = list(RawBinaryCriteoDataset(data_dir, backend="numpy", **kw))
  b = list(RawBinaryCriteoDataset(data_dir, backend="native", **kw))
  assert a[0][0] is None and b[0][0] is None
  _assert_batches_equal(a, b)


def test_native_valid_split(data_dir):
  a = list(RawBinaryCriteoDataset(data_dir, backend="numpy", valid=True, **_kw()))
  b = list(RawBinaryCriteoDataset(data_dir, backend="native", valid=True, **_kw()))
  _assert_batches_equal(a, b)


def test_native_trailing_partial_batch(data_dir):
  # 1000 % 128 != 0: the short last batch must keep per-feature row strides
  kw = _kw(drop_last_batch=False)
  a = list(RawBinaryCriteoDataset(data_dir, backend="numpy", **kw))
  b = list(RawBinaryCriteoDataset(data_dir, backend="native", **kw))
  assert a[-1][2].shape[0] == 1000 % 128
  _assert_batches_equal(a, b)


def test_native_empty_rank_slice(data_dir):
  # 1000 samples, batch 384, world 2, no drop: global batch 1 leaves rank 1
  # with an empty slice (start 1152 > 1000) — it must still be yielded as a
  # zero-length batch, not end the epoch early (ranks would desync).
  kw = _kw(batch_size=384, drop_last_batch=False)
  for rank in (0, 1):
    a = list(RawBinaryCriteoDataset(
        data_dir, backend="numpy", rank=rank, world_size=2, **kw))
    b = list(RawBinaryCriteoDataset(
        data_dir, backend="native", rank=rank, world_size=2, **kw))
    assert len(a) == len(b) == 2
    _assert_batches_equal(a, b)
  assert b[-1][2].shape[0] == 0


def test_auto_backend_iterates(data_dir):
  ds = RawBinaryCriteoDataset(data_dir, **_kw())
  n = sum(1 for _ in ds)
  assert n == len(ds)
