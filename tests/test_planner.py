"""Planner unit tests: placement strategies, column slicing, merge, fusion.

Zero-device pure-Python tests (reference pattern:
`tests/dist_model_parallel_test.py:220-236,287-334,367-374`)."""

import numpy as np
import pytest

from distributed_embeddings_tpu.layers import TableConfig
from distributed_embeddings_tpu.layers.planner import (
    DistEmbeddingStrategy,
    apply_placement,
    auto_column_slice_threshold,
    slice_columns,
)


def _configs(sizes, width=8, combiner=None):
  return [TableConfig(input_dim=s, output_dim=width, combiner=combiner)
          for s in sizes]


def _all_slices(plan):
  return [sh for shards in plan.rank_shards for sh in shards]


@pytest.mark.parametrize("mode", ["basic", "memory_balanced", "memory_optimized"])
def test_every_table_placed_exactly_once(mode):
  rng = np.random.default_rng(0)
  sizes = rng.integers(10, 1000, size=13).tolist()
  plan = DistEmbeddingStrategy(_configs(sizes), 4, strategy=mode)
  placed = sorted(sh.table_id for sh in _all_slices(plan))
  assert placed == list(range(13))
  for sh in _all_slices(plan):
    assert (sh.col_start, sh.col_end) == (0, 8)  # no slicing needed


def test_basic_round_robin():
  plan = DistEmbeddingStrategy(_configs([10] * 8), 4, strategy="basic")
  assert plan.table_ids == [[0, 4], [1, 5], [2, 6], [3, 7]]


def test_memory_balanced_even_count_and_size():
  sizes = [100, 90, 80, 70, 60, 50, 40, 30]
  plan = DistEmbeddingStrategy(_configs(sizes), 4, strategy="memory_balanced")
  loads = [sum(sizes[t] * 8 // 8 * 8 for t in ids) for ids in plan.table_ids]
  counts = [len(ids) for ids in plan.table_ids]
  assert counts == [2, 2, 2, 2]
  # boustrophedon: each worker gets one big + one small; loads near-equal
  assert max(loads) - min(loads) <= 20 * 8


def test_memory_optimized_balances_loads():
  sizes = [1000, 10, 10, 10, 10, 10, 10, 980]
  plan = DistEmbeddingStrategy(_configs(sizes), 2, strategy="memory_optimized")
  loads = [sum(sh.size() for sh in shards) for shards in plan.rank_shards]
  assert abs(loads[0] - loads[1]) <= 60 * 8


def test_column_slice_power_of_two():
  cfg = TableConfig(input_dim=100, output_dim=16)
  # size=1600; threshold 500 -> need 4 slices of 4 cols
  ranges = slice_columns(cfg, 500, world_size=8)
  assert ranges == [(0, 4), (4, 8), (8, 12), (12, 16)]


def test_column_slice_remainder_spread():
  cfg = TableConfig(input_dim=10, output_dim=10)
  ranges = slice_columns(cfg, 30, world_size=8)  # 100 -> 4 slices of 10 cols
  widths = [e - s for s, e in ranges]
  assert widths == [3, 3, 2, 2]
  assert ranges[-1][1] == 10


def test_column_slice_caps():
  cfg = TableConfig(input_dim=1000, output_dim=3)
  # would want many slices but capped by output_dim=3
  assert len(slice_columns(cfg, 10, world_size=8)) == 3
  # capped by world_size
  cfg2 = TableConfig(input_dim=1000, output_dim=64)
  assert len(slice_columns(cfg2, 10, world_size=2)) == 2


def test_auto_threshold_when_fewer_tables_than_workers():
  sizes = [1000, 10]
  thr = auto_column_slice_threshold(sizes, 4)
  assert thr is not None
  # plan must give every one of 4 workers at least one slice
  plan = DistEmbeddingStrategy(
      _configs([125, 10], width=8), 4, strategy="basic")
  assert all(plan.rank_shards)


def test_not_enough_tables_raises():
  with pytest.raises(ValueError):
    # one table of width 1 cannot be split over 2 workers
    DistEmbeddingStrategy([TableConfig(input_dim=5, output_dim=1)], 2)


def test_slice_merge_on_same_rank():
  # 1 table, 4 slices, 2 workers -> 2 slices per worker merge into one shard
  plan = DistEmbeddingStrategy(
      [TableConfig(input_dim=8, output_dim=16)], 2, strategy="basic",
      column_slice_threshold=40)  # 128 elems -> 4 slices
  assert [len(s) for s in plan.rank_shards] == [1, 1]
  (sh0,), (sh1,) = plan.rank_shards
  assert (sh0.col_start, sh0.col_end) == (0, 8)
  assert (sh1.col_start, sh1.col_end) == (8, 16)


def test_concat_fusion_same_width():
  plan = DistEmbeddingStrategy(_configs([10, 20, 30], width=8), 1)
  # all same width+combiner -> one fused local table
  assert len(plan.local_configs[0]) == 1
  cfg = plan.local_configs[0][0]
  assert cfg["input_dim"] == 60 and cfg["output_dim"] == 8
  assert plan.local_weight_offsets[0][0] == [0, 10, 30, 60]
  assert plan.local_input_offsets[0] == [0, 10, 30]


def test_no_fusion_across_widths_or_combiners():
  configs = [
      TableConfig(input_dim=10, output_dim=8),
      TableConfig(input_dim=10, output_dim=4),
      TableConfig(input_dim=10, output_dim=8, combiner="sum"),
  ]
  plan = DistEmbeddingStrategy(configs, 1)
  assert len(plan.local_configs[0]) == 3


def test_shared_table_input_map():
  plan = DistEmbeddingStrategy(
      _configs([10, 20], width=4), 2, input_table_map=[0, 0, 1])
  all_inputs = sorted(i for ids in plan.input_ids_list for i in ids)
  assert all_inputs == [0, 1, 2]
  # reorder indices restore input order
  assert sorted(plan.rev_global_input_ids) == [0, 1, 2]


def test_shared_table_with_slicing_duplicates_outputs():
  # table 0 sliced in 2, two inputs use it -> 4 worker-order outputs + 1
  plan = DistEmbeddingStrategy(
      _configs([64, 10], width=8), 2, input_table_map=[0, 0, 1],
      column_slice_threshold=256)
  worker_outputs = sum(len(ids) for ids in plan.input_ids_list)
  assert worker_outputs == 5
  assert len(plan.output_pieces[0]) == 2
  assert [p.col_start for p in plan.output_pieces[0]] == [0, 4]


def test_output_pieces_cover_full_width():
  rng = np.random.default_rng(3)
  configs = _configs(rng.integers(50, 500, size=5).tolist(), width=12)
  plan = DistEmbeddingStrategy(configs, 4, strategy="memory_balanced",
                               column_slice_threshold=800)
  for i, t in enumerate(plan.input_table_map):
    pieces = plan.output_pieces[i]
    total = sum(p.width for p in pieces)
    assert total == configs[t].output_dim
    # contiguous column coverage
    pos = 0
    for p in pieces:
      assert p.col_start == pos
      pos += p.width


def test_width_class_uniformity():
  rng = np.random.default_rng(4)
  configs = _configs(rng.integers(10, 100, size=9).tolist(), width=8)
  plan = DistEmbeddingStrategy(configs, 4, strategy="memory_optimized")
  assert len(plan.class_keys) == 1
  plan_c = plan.classes[plan.class_keys[0]]
  assert len(plan_c.rows_per_rank) == 4
  assert plan_c.max_rows == max(plan_c.rows_per_rank)
  # every table's rows appear exactly once across ranks
  total_rows = sum(plan_c.rows_per_rank)
  assert total_rows == sum(c.input_dim for c in configs)


def test_world_one_keeps_fusion_but_skips_comm_strategy():
  plan = DistEmbeddingStrategy(_configs([10, 20], width=8), 1,
                               strategy="memory_balanced")
  assert plan.strategy == "basic"
  assert len(plan.local_configs) == 1


def test_invalid_strategy_raises():
  with pytest.raises(ValueError):
    DistEmbeddingStrategy(_configs([10]), 2, strategy="bogus")


def test_class_generation_split_caps_buffer_bytes():
  """max_class_bytes splits a width class into generations so no per-rank
  fused buffer exceeds the cap (XLA copies any >= 4 GiB buffer on every
  use; see ClassKey docs). Forced here with a tiny cap."""
  sizes = [100, 80, 60, 50, 40, 30]
  cap = 120 * 8 * 4  # rows*width*4 bytes -> 120 rows per generation
  plan = DistEmbeddingStrategy(_configs(sizes), 2, strategy="basic",
                               max_class_bytes=cap)
  assert len(plan.class_keys) > 1  # split happened
  gens = {k[3] for k in plan.class_keys}
  assert gens == set(range(len(gens)))
  for key in plan.class_keys:
    cp = plan.classes[key]
    for rows in cp.rows_per_rank:
      # a generation holding a single over-cap shard may exceed the cap;
      # none of these shards are over-cap, so all gens obey it
      assert rows * cp.width * 4 <= cap
  # every table's rows appear exactly once across (rank, gen)
  total = sum(sum(cp.rows_per_rank) for cp in plan.classes.values())
  assert total == sum(sizes)


def test_planner_int32_id_space_contract():
  """A table whose id space exceeds int32 needs the int64 routing path,
  which localizes global ids through row-slice windows (round 4;
  reference registers an int64 op variant, `embedding_lookup_ops.cc:
  24-88`): without row slicing it must fail loudly at plan time, with it
  the plan must come out row-sliced into int32-sized windows. 2^31 - 1
  rows plans either way (colossal's 2B-row table clears by 7%)."""
  with pytest.raises(ValueError, match="int64 routing path"):
    DistEmbeddingStrategy([TableConfig((1 << 31), 8)], 128, "basic")
  plan = DistEmbeddingStrategy([TableConfig((1 << 31), 8)], 128, "basic",
                               row_slice_threshold=1 << 24)
  for (r0, r1) in plan.table_row_ranges[0]:
    assert r1 - r0 <= 2 ** 31 - 1
  plan = DistEmbeddingStrategy([TableConfig((1 << 31) - 1, 8)], 128,
                               "basic", row_slice_threshold=1 << 24)
  assert plan.world_size == 128


def test_first_fit_generation_assignment_legacy_layout():
  """gen_assignment='first_fit' reproduces the round-2 first-fit layout:
  shards fill generations in shard order against the byte cap (the legacy
  mode exists so pre-round-3 checkpoints stay restorable)."""
  sizes = [100, 80, 60, 50, 40, 30]
  cap = 120 * 8 * 4  # 120 rows per generation at width 8
  plan = DistEmbeddingStrategy(_configs(sizes), 1, strategy="basic",
                               max_class_bytes=cap,
                               gen_assignment="first_fit")
  # first-fit in shard order: 100 -> g0; 80 -> g1 (100+80 > 120);
  # 60 -> g2; 50 -> g2? no (60+50=110 <= 120 -> g2); 40 -> g1 (80+40=120);
  # 30 -> g3 (g0 100+30>120, g1 120+30>120, g2 110+30>120)
  gen_of = {sh.table_id: sh.gen for sh in _all_slices(plan)}
  assert gen_of == {0: 0, 1: 1, 2: 2, 3: 2, 4: 1, 5: 3}
  with pytest.raises(ValueError, match="gen_assignment"):
    DistEmbeddingStrategy(_configs(sizes), 1, gen_assignment="bogus")


def test_class_generation_single_oversized_shard_gets_own_gen():
  sizes = [500, 10]
  cap = 100 * 8 * 4  # smaller than the big table alone
  plan = DistEmbeddingStrategy(_configs(sizes), 1, strategy="basic",
                               max_class_bytes=cap)
  rows_by_gen = {k[3]: plan.classes[k].rows_per_rank[0]
                 for k in plan.class_keys}
  assert sorted(rows_by_gen.values()) == [10, 500]


def test_generation_split_forward_matches_unsplit():
  """Same lookup results with and without a forced generation split."""
  import jax
  import jax.numpy as jnp

  from distributed_embeddings_tpu.layers.dist_model_parallel import (
      get_weights,
      set_weights,
  )
  from distributed_embeddings_tpu.parallel.lookup_engine import (
      DistributedLookup,
      class_param_name,
  )

  rng = np.random.default_rng(7)
  sizes = [40, 30, 20, 10]
  weights = [rng.standard_normal((s, 8)).astype(np.float32) for s in sizes]
  ids = [jnp.asarray(rng.integers(0, s, 16).astype(np.int32)) for s in sizes]

  outs = {}
  for cap in (1 << 30, 24 * 8 * 4):
    plan = DistEmbeddingStrategy(_configs(sizes), 1, strategy="basic",
                                 max_class_bytes=cap)
    params = {name: jnp.asarray(arr)
              for name, arr in set_weights(plan, weights).items()}
    engine = DistributedLookup(plan)
    outs[cap] = engine.forward(params, ids)
    got = get_weights(plan, params)
    for w, g in zip(weights, got):
      np.testing.assert_array_equal(w, g)
  for a, b in zip(outs[1 << 30], outs[24 * 8 * 4]):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_accepts_stock_keras_embedding_configs():
  """The reference accepts stock tf.keras Embedding configs by dropping
  Keras-only fields (`embedding.py:145-152`); dict inputs here do the
  same, mapping embeddings_initializer -> initializer."""
  plan = DistEmbeddingStrategy(
      [{"input_dim": 32, "output_dim": 8, "mask_zero": False,
        "input_length": None, "embeddings_initializer": "uniform",
        "dtype": "float32", "trainable": True},
       {"input_dim": 16, "output_dim": 8}], 2)
  assert [c.input_dim for c in plan.global_configs] == [32, 16]
  assert plan.global_configs[0].initializer == "uniform"


def test_planner_scales_to_colossal_table_counts():
  """Plan construction must stay sub-second at the zoo's largest config
  (2002 tables / 128 workers) — it runs identically on every process.
  Vocab is scaled down: plan-time cost is per-TABLE, and the full-vocab
  colossal config is not legally placeable on 128 workers at all (the
  2B-row giants exceed the 2^31-element buffer limit the planner now
  enforces — see test_plan_scale.py for the full-scale 1024-worker plan
  and the world-64 rejection)."""
  import dataclasses
  import time

  from distributed_embeddings_tpu.models import SYNTHETIC_MODELS, expand_tables
  cfg = SYNTHETIC_MODELS["colossal"]
  tables, tmap, _ = expand_tables(cfg)
  tables = [dataclasses.replace(t, input_dim=max(8, t.input_dim // 1000))
            for t in tables]
  t0 = time.perf_counter()
  # threshold 8 preserves the original dense/sparse split under the
  # scaled vocabs (only the hundred 10-row tables ride the dense path,
  # exactly as threshold 2048 selected at full vocab) so the test still
  # times the sparse placement/fusion loops over ~1900 tables
  plan = DistEmbeddingStrategy(tables, 128, "memory_balanced",
                               input_table_map=tmap,
                               dense_row_threshold=8)
  assert time.perf_counter() - t0 < 5.0
  assert sum(len(s) for s in plan.rank_shards) >= len(tables)
