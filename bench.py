"""Driver benchmark: synthetic Tiny (55 tables, 4.2 GiB) train step on one chip.

Baseline: the reference's published 1xA100 step time for the same model at
global batch 65536 with Adagrad — 24.433 ms
(`/root/reference/examples/benchmarks/synthetic_models/README.md:71`, see
BASELINE.md). ``vs_baseline > 1`` means this TPU chip beats the A100.

Prints ONE JSON line:
  {"metric": ..., "value": <ms>, "unit": "ms", "vs_baseline": <ratio>}
"""

import json
import os
import sys
import time

BASELINE_MS = 24.433  # 1xA100, Tiny, batch 65536, Adagrad
MODEL = os.environ.get("BENCH_MODEL", "tiny")
BATCH = int(os.environ.get("BENCH_BATCH", 65536))
STEPS = int(os.environ.get("BENCH_STEPS", 20))


def run(batch_size: int) -> float:
  import jax
  import jax.numpy as jnp
  import numpy as np
  import optax

  from distributed_embeddings_tpu.models import (
      SYNTHETIC_MODELS,
      SyntheticModel,
      bce_loss,
      expand_tables,
      generate_batch,
  )
  from distributed_embeddings_tpu.training import make_train_step

  cfg = SYNTHETIC_MODELS[MODEL]
  tables, tmap, _ = expand_tables(cfg)
  model = SyntheticModel(config=cfg, world_size=1)

  batches = []
  for i in range(2):
    numerical, cats, labels = generate_batch(cfg, batch_size, alpha=1.05,
                                             seed=i)
    cats = [np.minimum(c, tables[t].input_dim - 1).astype(np.int32)
            for c, t in zip(cats, tmap)]
    batches.append((jnp.asarray(numerical),
                    [jnp.asarray(c) for c in cats], jnp.asarray(labels)))

  params = model.init(jax.random.PRNGKey(0), batches[0][0],
                      batches[0][1])["params"]
  optimizer = optax.adagrad(0.01)
  opt_state = optimizer.init(params)

  def loss_fn(p, numerical, cats, labels):
    return bce_loss(model.apply({"params": p}, numerical, cats), labels)

  step = make_train_step(loss_fn, optimizer, None, params, opt_state,
                         batches[0])
  for i in range(3):
    params, opt_state, loss = step(params, opt_state, *batches[i % 2])
  jax.block_until_ready(loss)
  t0 = time.perf_counter()
  for i in range(STEPS):
    params, opt_state, loss = step(params, opt_state, *batches[i % 2])
  jax.block_until_ready(loss)
  return (time.perf_counter() - t0) / STEPS * 1000


def main():
  batch = BATCH
  while True:
    try:
      ms = run(batch)
      break
    except Exception as e:  # noqa: BLE001 - OOM fallback, report honestly
      if "RESOURCE_EXHAUSTED" in str(e) and batch > 4096:
        print(f"# batch {batch} OOM, retrying at {batch // 2}",
              file=sys.stderr)
        batch //= 2
        continue
      raise
  # normalize to the baseline's global batch if we had to shrink
  equiv_ms = ms * (BATCH / batch)
  print(json.dumps({
      "metric": f"synthetic_{MODEL}_step_time_1chip_batch{BATCH}",
      "value": round(equiv_ms, 3),
      "unit": "ms",
      "vs_baseline": round(BASELINE_MS / equiv_ms, 4),
  }))


if __name__ == "__main__":
  main()
