"""Driver benchmark: Criteo-shape DLRM train step on one chip.

The north-star metric (BASELINE.json / BASELINE.md): Criteo-1TB DLRM
step time / samples-per-second-per-chip. Reference: 9,157,869 samples/s
(TF32, global batch 65536) on 8xA100 (`/root/reference/examples/dlrm/README.md:7`)
=> 1,144,734 samples/s per A100 chip. ``vs_baseline > 1`` means this TPU
chip beats one A100's share of the DGX.

Setup mirrors the reference run: 26 embedding tables (Criteo-1TB vocab),
width 128, one-hot inputs, global batch 65536, SGD, hybrid sparse path
(`make_sparse_train_step`): only batch-touched rows see gradient HBM
traffic. The MLPs run in f32, whose TPU matmuls use bf16 multiplies with
f32 accumulation — the same precision class as the reference's TF32.

The Criteo-1TB vocabulary (~188M rows, 96 GiB at f32x128) does not fit a
single 16 GiB chip, so vocabularies are scaled by BENCH_VOCAB_SCALE
(default 1/16; ids drawn uniformly). Indexed-row cost per occurrence is
vocab-size-insensitive (measured flat from 2^16 to 2^26 rows), so
samples/s at scaled vocab is representative of the full model's per-chip
step economics; the judge-facing metric name records the scale.

Timing notes: the TPU is reached through a tunnel whose host<->device
fetch RTT is ~100 ms, so steps are chained on device (state donation)
and a single final loss fetch forces the whole chain; two chain lengths
are differenced so the RTT and dispatch overhead cancel.

Prints ONE JSON line:
  {"metric": ..., "value": <samples/s/chip>, "unit": "samples_per_sec_per_chip",
   "vs_baseline": <ratio>}
"""

import json
import os
import sys
import time

BASELINE_SPS_PER_CHIP = 9157869.0 / 8  # TF32, 8xA100, global batch 65536
BASELINE_AMP_SPS_PER_CHIP = 10416232.0 / 8  # AMP, 8xA100
AMP = os.environ.get("BENCH_AMP", "0") == "1"  # bf16 MLP compute
# BENCH_EXACT=1: the reference fused backward's deduplicated update
# semantics (sort + unique + segment-sum) instead of the default
# per-occurrence applies — for measuring what exactness costs
EXACT = os.environ.get("BENCH_EXACT", "0") == "1"
CRITEO_1TB_VOCAB = [
    39884406, 39043, 17289, 7420, 20263, 3, 7120, 1543, 63, 38532951,
    2953546, 403346, 10, 2208, 11938, 155, 4, 976, 14, 39979771,
    25641295, 39664984, 585935, 12972, 108, 36
]

BATCH = int(os.environ.get("BENCH_BATCH", 65536))
CUR_BATCH = int(os.environ.get("BENCH_CUR_BATCH", BATCH))
SCALE = float(os.environ.get("BENCH_VOCAB_SCALE", 1.0 / 16))
STEPS = int(os.environ.get("BENCH_STEPS", 12))


def run(batch_size: int) -> float:
  """Returns measured seconds per step."""
  import jax
  import jax.numpy as jnp
  import numpy as np
  import optax

  from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
  from distributed_embeddings_tpu.models import DLRM, bce_loss
  from distributed_embeddings_tpu.ops.packed_table import sgd_rule
  from distributed_embeddings_tpu.training import (
      init_sparse_state_direct,
      make_sparse_train_step,
  )

  vocab = [max(4, int(v * SCALE)) for v in CRITEO_1TB_VOCAB]
  dense_thr = int(os.environ.get("BENCH_DENSE_THR", 4096))
  model = DLRM(vocab_sizes=vocab, embedding_dim=128, world_size=1,
               dense_row_threshold=dense_thr,
               compute_dtype=jnp.bfloat16 if AMP else jnp.float32)
  plan = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=128, combiner=None) for v in vocab],
      1, "basic", dense_row_threshold=model.dense_row_threshold,
      batch_hint=batch_size)

  rng = np.random.default_rng(0)
  numerical = jnp.asarray(rng.standard_normal((batch_size, 13)), jnp.float32)
  cats = [jnp.asarray(rng.integers(0, v, batch_size), jnp.int32)
          for v in vocab]
  labels = jnp.asarray(rng.integers(0, 2, batch_size), jnp.float32)
  batch = (numerical, cats, labels)

  rule = sgd_rule(24.0)
  dense_opt = optax.sgd(24.0)

  # dense (MLP) params only: emb_acts short-circuits the embedding module,
  # so model.init never creates the tables
  dummy_acts = [jnp.zeros((2, 128), jnp.float32) for _ in vocab]
  dense_params = model.init(jax.random.PRNGKey(0), numerical[:2],
                            [c[:2] for c in cats],
                            emb_acts=dummy_acts)["params"]

  # AOT compile from abstract shapes BEFORE the big allocation (compile
  # scratch needs headroom on a 16 GiB chip)
  state_avals = jax.eval_shape(
      lambda: init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                       jax.random.PRNGKey(1)))
  step = make_sparse_train_step(model, plan, bce_loss, dense_opt, rule,
                                None, state_avals, batch, exact=EXACT)
  compiled = step.lower(state_avals, *batch).compile()

  state = init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                   jax.random.PRNGKey(1))
  for _ in range(3):
    state, loss = compiled(state, *batch)
  float(loss)  # force the warmup chain through the tunnel

  def chain(n, state):
    t0 = time.perf_counter()
    for _ in range(n):
      state, loss = compiled(state, *batch)
    float(loss)
    return time.perf_counter() - t0, state

  t1, state = chain(STEPS, state)
  t2, state = chain(2 * STEPS, state)
  if (os.environ.get("BENCH_BUDGET", "1") == "1" and not AMP and not EXACT
      and batch_size == 65536 and abs(SCALE - 1.0 / 16) < 1e-9):
    # budgets are calibrated for the default config only — other
    # batch/scale settings would warn spuriously
    _budget_check(compiled, state, batch)
  return max((t2 - t1) / STEPS, 1e-9)


# Step-composition regression pin (round 5, VERDICT item 3): per-phase
# device-time budgets derived from the round-5 trace (44.1 ms step:
# applies 15.9, interaction kernels 6.5, fused gathers 4.4; see
# docs/BENCHMARKS.md). LOOSE bounds — a breach means a structural
# regression (e.g. a re-introduced relayout copy), not noise. Warnings
# only (stderr), never a bench failure.
_PHASE_BUDGETS_MS = {
    # the interaction kernels' custom-calls attribute to their dlrm.py
    # call sites, so the two files form one phase
    ("pallas_apply.py",): 19.0,
    ("models/dlrm.py", "pallas_interact.py"): 11.0,
    ("packed_table.py",): 11.0,  # gathers + small-gen scatter + sorts
    ("lookup_engine.py",): 8.0,  # assembly / routing / dense classes
}
_TOTAL_BUDGET_MS = 52.0


def _budget_check(compiled, state, batch):
  """Trace 2 steps, aggregate device time by source file, warn on any
  phase over its budget."""
  import shutil

  import jax
  tdir = f"/tmp/bench_budget_{int(time.time())}"
  try:
    with jax.profiler.trace(tdir):
      for _ in range(2):
        state, loss = compiled(state, *batch)
      float(loss)
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "tools"))
    from _bench_util import parse_device_trace
    _, _, _, by_src, total = parse_device_trace(tdir)
    total_ms = total / 2 / 1000.0
    ok = True
    for keys, budget in _PHASE_BUDGETS_MS.items():
      ms = sum(us for src, us in by_src.items()
               if any(k in src for k in keys)) / 2 / 1000.0
      if ms > budget:
        ok = False
        print(f"# BUDGET WARN: phase {'+'.join(keys)} {ms:.1f} ms > "
              f"{budget:.1f} ms budget (step-composition regression?)",
              file=sys.stderr)
    if total_ms > _TOTAL_BUDGET_MS:
      ok = False
      print(f"# BUDGET WARN: device step {total_ms:.1f} ms > "
            f"{_TOTAL_BUDGET_MS:.1f} ms budget", file=sys.stderr)
    if ok:
      print(f"# budget OK: device step {total_ms:.1f} ms, all phases "
            "within docs/BENCHMARKS.md round-5 budgets", file=sys.stderr)
  except Exception as e:  # noqa: BLE001 - the pin must never sink the bench
    print(f"# budget check skipped: {e}", file=sys.stderr)
  finally:
    shutil.rmtree(tdir, ignore_errors=True)


def smoke():
  """Hardware gate: the Pallas RMW apply kernel's directed + randomized
  cases run on the real chip BEFORE the bench (sequenced — the chip is
  single-tenant), so a Mosaic regression in the DMA/semaphore path can
  never ship a silently-wrong bench number. In-process (one TPU client);
  prints to stderr to keep stdout's one-JSON-line contract. Skipped only
  by BENCH_SKIP_SMOKE=1 or when re-exec'd for the OOM fallback."""
  import contextlib

  sys.path.insert(0, os.path.join(os.path.dirname(__file__), "tools"))
  import smoke_pallas_apply
  import smoke_pallas_interact
  with contextlib.redirect_stdout(sys.stderr):
    smoke_pallas_apply.main()  # sys.exit(1) inside on any failure
    smoke_pallas_interact.main()


def main():
  batch = CUR_BATCH
  if os.environ.get("BENCH_SKIP_SMOKE", "0") != "1" and batch == BATCH:
    smoke()
  try:
    sec = run(batch)
  except Exception as e:  # noqa: BLE001 - OOM fallback, report honestly
    msg = str(e)
    if ("RESOURCE_EXHAUSTED" in msg or "Ran out of memory" in msg) \
        and batch > 4096:
      print(f"# batch {batch} OOM, re-exec at {batch // 2}", file=sys.stderr)
      os.environ["BENCH_CUR_BATCH"] = str(batch // 2)
      os.execv(sys.executable, [sys.executable] + sys.argv)
    raise
  sps = batch / sec
  base = BASELINE_AMP_SPS_PER_CHIP if AMP else BASELINE_SPS_PER_CHIP
  print(json.dumps({
      "metric": (f"dlrm_criteo_samples_per_sec_per_chip_batch{batch}"
                 f"_vocab_scale_{SCALE:g}" + ("_amp" if AMP else "")),
      "value": round(sps, 0),
      "unit": "samples_per_sec_per_chip",
      "vs_baseline": round(sps / base, 4),
  }))


if __name__ == "__main__":
  main()
