"""Driver benchmark: synthetic Tiny (55 tables, 4.2 GiB) train step on one chip.

Baseline: the reference's published 1xA100 step time for the same model at
global batch 65536 with Adagrad — 24.433 ms
(`/root/reference/examples/benchmarks/synthetic_models/README.md:71`, see
BASELINE.md). ``vs_baseline > 1`` means this TPU chip beats the A100.

Uses the sparse (IndexedSlices-equivalent) training path
(``make_sparse_train_step`` + fused packed tables): like the reference, only
batch-touched rows see gradient/optimizer HBM traffic — a dense optax step
on 4.2 GiB of tables would spend ~17 GiB of HBM traffic per step on the
adagrad accumulator alone (and OOM a 16 GB chip on the dense grad temps).

Memory discipline (16 GB v5e, state alone is 8.4 GiB):
- the train step is AOT-compiled from abstract shapes BEFORE any big
  allocation (compile scratch needs headroom);
- the packed state is drawn directly in its physical layout
  (``init_sparse_state_direct``) — the [rows, width] tables never exist;
- on OOM the process re-execs itself at half the batch so retries start
  with a genuinely empty device.

Timing notes: the TPU is reached through a tunnel whose host<->device fetch
RTT is ~100 ms, so steps are chained on device (params donation) and a
single final loss fetch forces the whole chain; the separately-measured
fetch RTT is subtracted.

Prints ONE JSON line:
  {"metric": ..., "value": <ms>, "unit": "ms", "vs_baseline": <ratio>}
"""

import json
import os
import sys
import time

BASELINE_MS = 24.433  # 1xA100, Tiny, batch 65536, Adagrad
MODEL = os.environ.get("BENCH_MODEL", "tiny")
BATCH = int(os.environ.get("BENCH_BATCH", 65536))
CUR_BATCH = int(os.environ.get("BENCH_CUR_BATCH", BATCH))
STEPS = int(os.environ.get("BENCH_STEPS", 30))


def run(batch_size: int) -> float:
  import jax
  import jax.numpy as jnp
  import numpy as np
  import optax

  from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
  from distributed_embeddings_tpu.models import (
      SYNTHETIC_MODELS,
      SyntheticModel,
      bce_loss,
      expand_tables,
      generate_batch,
  )
  from distributed_embeddings_tpu.ops.packed_table import adagrad_rule
  from distributed_embeddings_tpu.training import (
      init_sparse_state_direct,
      make_sparse_train_step,
  )

  cfg = SYNTHETIC_MODELS[MODEL]
  tables, tmap, hotness = expand_tables(cfg)
  model = SyntheticModel(config=cfg, world_size=1)
  plan = DistEmbeddingStrategy(tables, 1, "basic", input_table_map=tmap,
                               dense_row_threshold=model.dense_row_threshold)

  batches = []
  for i in range(2):
    numerical, cats, labels = generate_batch(cfg, batch_size, alpha=1.05,
                                             seed=i)
    cats = [np.minimum(c, tables[t].input_dim - 1).astype(np.int32)
            for c, t in zip(cats, tmap)]
    cats = [jnp.asarray(c if h > 1 else c[:, 0])
            for c, h in zip(cats, hotness)]
    batches.append((jnp.asarray(numerical), cats, jnp.asarray(labels)))

  dense_opt = optax.adagrad(0.01)
  rule = adagrad_rule(0.01)

  # dense (MLP) params only: emb_acts short-circuits the embedding module,
  # so model.init never creates the 4.2 GiB tables
  dummy_acts = [jnp.zeros((2, tables[t].output_dim), jnp.float32)
                for t in tmap]
  small_cats = [c[:2] for c in batches[0][1]]
  dense_params = model.init(jax.random.PRNGKey(0), batches[0][0][:2],
                            small_cats, emb_acts=dummy_acts)["params"]

  # ---- AOT compile from abstract shapes, before the big allocations ------
  def abstract_state():
    return init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                    jax.random.PRNGKey(1))
  state_avals = jax.eval_shape(abstract_state)
  step = make_sparse_train_step(model, plan, bce_loss, dense_opt, rule,
                                None, state_avals, batches[0])
  compiled = step.lower(state_avals, *batches[0]).compile()

  # ---- real state, directly in packed layout -----------------------------
  state = init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                   jax.random.PRNGKey(1))

  for i in range(3):
    state, loss = compiled(state, *batches[i % 2])
  warm = float(loss)  # force the warmup chain before timing

  # fetch-RTT estimate (subtracted below): time fetching a ready scalar.
  # block_until_ready first so compile/dispatch are not counted in the RTT.
  probe = jax.block_until_ready(jax.jit(lambda x: x + 1)(jnp.zeros(())))
  t0 = time.perf_counter()
  float(probe)
  rtt = time.perf_counter() - t0

  t0 = time.perf_counter()
  for i in range(STEPS):
    state, loss = compiled(state, *batches[i % 2])
  final = float(loss)  # forces the whole chain through the tunnel
  elapsed = time.perf_counter() - t0 - rtt
  del warm, final
  return max(elapsed, 1e-9) / STEPS * 1000


def main():
  batch = CUR_BATCH
  try:
    ms = run(batch)
  except Exception as e:  # noqa: BLE001 - OOM fallback, report honestly
    msg = str(e)
    if ("RESOURCE_EXHAUSTED" in msg or "Ran out of memory" in msg) \
        and batch > 4096:
      print(f"# batch {batch} OOM, re-exec at {batch // 2}", file=sys.stderr)
      os.environ["BENCH_CUR_BATCH"] = str(batch // 2)
      os.execv(sys.executable, [sys.executable] + sys.argv)
    raise
  # normalize to the baseline's global batch if we had to shrink
  equiv_ms = ms * (BATCH / batch)
  print(json.dumps({
      "metric": f"synthetic_{MODEL}_step_time_1chip_batch{BATCH}",
      "value": round(equiv_ms, 3),
      "unit": "ms",
      "vs_baseline": round(BASELINE_MS / equiv_ms, 4),
  }))


if __name__ == "__main__":
  main()
