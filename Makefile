# Developer entry points (the reference drives its native build + tests from
# make, `/root/reference/Makefile`; here the native loader builds itself on
# first import, so these are conveniences).

PY ?= python
SHELL := /bin/bash  # verify uses pipefail/PIPESTATUS

.PHONY: test test-fast verify lint native bench dryrun chaos chaos-kill \
	chaos-preempt preempt-smoke chaos-multiproc multiproc-smoke \
	chaos-stream stream-smoke serve-bench \
	serve-smoke vocab-bench vocab-smoke obs-bench obs-smoke fresh-bench \
	fresh-smoke fleet-bench fleet-smoke trace-bench trace-smoke \
	control-bench control-smoke overlap-bench overlap-smoke \
	exchange-occupancy exchange-smoke clean

test:
	$(PY) -m pytest tests/ -q

# repo-invariant linter: AST rules (GL1xx, incl. GL124 stale
# suppressions), the concurrency pass (threadlint GL120-GL123 lock
# discipline + GL125 thread-root registry — library package only,
# sharing the one pyproject/repo context parse, so verify cost stays
# flat) + trace-time jaxpr audit of the step builders against committed
# fingerprints (tests/data/).
# Regenerate fingerprints after an INTENTIONAL structural change with
#   $(PY) tools/graftlint.py --update-fingerprints
lint:
	$(PY) tools/graftlint.py

# serving engine load test: step throughput (int8 serve vs f32 eval)
# plus p50/p99/p99.9 latency vs offered QPS through the micro-batcher,
# across {f32,int8} x {all-device,tiered} x batcher deadlines
# (tools/profile_serve.py; budgets recorded in docs/BENCHMARKS.md r8)
serve-bench:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH $(PY) tools/profile_serve.py

# the make-verify tier of the serve bench: tiny world, a few hundred
# requests; asserts finite latency percentiles and exact load-shed
# rejection accounting (timeout-guarded like the pytest tier — a wedged
# compile or thread must fail the gate, not hang it)
serve-smoke:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH timeout -k 10 300 \
	  $(PY) tools/profile_serve.py --smoke

# dynamic-vocabulary churn bench: power-law ids with a drifting tail,
# admission (count-min threshold) vs admit-everything on one stream —
# acceptance: admission <= 50% of the row allocations at equal final
# eval loss (tools/profile_dynvocab.py; budget in docs/BENCHMARKS.md r9)
vocab-bench:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH $(PY) tools/profile_dynvocab.py

# the make-verify tier of the vocab bench: tiny stream, same assertions,
# timeout-guarded like the other smoke tiers
vocab-smoke:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH timeout -k 10 300 \
	  $(PY) tools/profile_dynvocab.py --smoke

# telemetry overhead bench: spans/counters on the tiered + dynvocab
# power-law workloads must cost <= 3% of step time with tracing ENABLED,
# the emitted trace.json must SHOW the prefetch-ahead classify
# overlapping the device window on separate tracks, and the registry
# must round-trip through its manifest section
# (tools/profile_telemetry.py; budget in docs/BENCHMARKS.md r10)
obs-bench:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH $(PY) tools/profile_telemetry.py

# the make-verify tier: same structural assertions (trace produced with
# the overlap visible, counters round-trip), overhead only required
# finite — tiny world, timeout-guarded
obs-smoke:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH timeout -k 10 300 \
	  $(PY) tools/profile_telemetry.py --smoke

# streaming chaos: SIGKILL the trainer mid-publish (torn delta tmp), the
# compactor mid-fold, and the subscriber mid-promote; relaunch each and
# assert the folded serve state is bit-exact vs an unkilled reference at
# the same watermark, the chain fingerprints sha256-continuous across
# the trainer kill (publisher ATTACH, no re-root), and cold start from
# the compacted base+tail converges (tools/chaos_stream.py; the long
# variant is @pytest.mark.slow in tests/test_streaming.py)
chaos-stream:
	$(PY) tools/chaos_stream.py

# the make-verify tier of the streaming chaos: 2 worker subprocesses
# (the mid-publish SIGKILL + attach relaunch), subscriber fold and
# compaction checked in-driver — same bit-exactness assertions,
# timeout-guarded like the other smoke tiers
stream-smoke:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH timeout -k 10 480 \
	  $(PY) tools/chaos_stream.py --smoke

# online-learning freshness bench: trainer publishes row-granular deltas
# while a live subscriber+batcher serve concurrent traffic — measures
# train-step->servable lag (stream/freshness_s), delta bytes vs the
# full export, chain convergence, and delta-vs-reexport bit-exactness
# (tools/profile_freshness.py; budget in docs/BENCHMARKS.md r11)
fresh-bench:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH $(PY) tools/profile_freshness.py

# the make-verify tier of the freshness bench: tiny world, same
# structural assertions, timeout-guarded like the other smoke tiers
fresh-smoke:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH timeout -k 10 300 \
	  $(PY) tools/profile_freshness.py --smoke

# open-loop fleet load generator: exactness vs the single-process
# engine (f32 bit-exact incl. tiered; int8/fp8 byte-exact), p50/p99/
# p99.9 vs offered QPS across fleet sizes {1,2,4 owners} with
# per-process telemetry rolled up through the registry merge, and a
# kill-one-replicated-owner-mid-load run proving zero wrong answers
# with counted failover (tools/profile_fleet.py; budgets in
# docs/BENCHMARKS.md r17)
fleet-bench:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH $(PY) tools/profile_fleet.py

# the make-verify tier of the fleet bench: tiny world, 1-2 owners, a
# few hundred requests — same exactness/failover/roll-up assertions,
# timeout-guarded like the other smoke tiers
fleet-smoke:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH timeout -k 10 300 \
	  $(PY) tools/profile_fleet.py --smoke

# distributed-tracing budget: tracing-enabled fleet serve overhead
# <= 3% vs disabled (the PR 10 budget on the fleet path), ONE merged
# Chrome trace from a world-2 multi-process fleet run (router + 2 owner
# processes + device track; clock-offset handshake, rpc-contains-gather
# nesting after correction), and a chaos-injected failover producing a
# flight-recorder bundle whose slowest request's critical path names
# the rpc stage (tools/profile_trace.py; budgets in docs/BENCHMARKS.md
# r18). DE_TPU_KEEP_TRACE=<dir> keeps the merged trace.json.
trace-bench:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH $(PY) tools/profile_trace.py

# the make-verify tier of the trace bench: tiny world, same structural
# assertions (merged tracks, nesting, flight bundle), overhead only
# required finite — timeout-guarded like the other smoke tiers (the
# longer budget covers the two real owner-process spawns, like
# stream-smoke's worker subprocesses)
trace-smoke:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH timeout -k 10 480 \
	  $(PY) tools/profile_trace.py --smoke

# control-plane budget: hedging off/on p99.9 on a slow-replica fleet
# (zero wrong answers, measurable tightening) and a 3x-QPS-step ramp
# where the autoscaler re-sizes the fleet through apply_fleet mid-load
# with zero wrong/zero dropped requests, every decision in the
# replayable control/decisions stream (tools/profile_control.py;
# budgets in docs/BENCHMARKS.md round 20)
control-bench:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH $(PY) tools/profile_control.py

# the make-verify tier of the control bench: tiny world, same
# assertions, timeout-guarded like the other smoke tiers
control-smoke:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH timeout -k 10 300 \
	  $(PY) tools/profile_control.py --smoke

# host-device overlap budget: the same tiered power-law workload run
# serial (overlap_host=False) vs overlapped (batch k+1's classify/gather
# on the HostWorker while step k runs on device) — acceptance: >= 25%
# step-wall reduction with >= 70% of the host pipeline hidden, the
# overlapped wall within 1.15x of max(host, device), the two loss
# streams BIT-IDENTICAL, and the trace showing worker spans strictly
# inside device windows (tools/profile_overlap.py; budgets in
# docs/BENCHMARKS.md round 22)
overlap-bench:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH $(PY) tools/profile_overlap.py

# the make-verify tier of the overlap bench: tiny world, parity + the
# worker-span structural assertion only (CPU step times at toy scale are
# noise), timeout-guarded like the other smoke tiers
overlap-smoke:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH timeout -k 10 300 \
	  $(PY) tools/profile_overlap.py --smoke

# the round-20 fused-exchange pricing: per-round wall, gather-hidden
# fraction (schedule accounting), wire bytes, fused vs pipelined step
exchange-occupancy:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH $(PY) tools/profile_exchange.py \
	  --overlap-occupancy

# the make-verify tier: tiny workload, machinery + loss parity + the
# schedule accounting only (CPU step times at toy scale are noise),
# timeout-guarded like the other smoke tiers
exchange-smoke:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH timeout -k 10 300 \
	  $(PY) tools/profile_exchange.py --overlap-occupancy --smoke

# the tier-1 gate, exactly as ROADMAP.md specifies it (CPU mesh, no slow
# tests, collection errors surfaced but not fatal to the log); lint runs
# first so invariant violations fail fast, then the smoke tiers
verify: lint serve-smoke vocab-smoke obs-smoke fresh-smoke stream-smoke \
	fleet-smoke trace-smoke preempt-smoke multiproc-smoke control-smoke \
	overlap-smoke exchange-smoke
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

test-fast:
	$(PY) -m pytest tests/ -q -x -k "not training and not checkpoint"

# build a pip wheel (includes the C++ loader sources + any prebuilt .so;
# reference parity: setup.py / build_pip_pkg.sh)
wheel:
	$(PY) -m pip wheel --no-deps --no-build-isolation -w dist .

# force-(re)build the native C++ data loader
native:
	$(PY) -c "from distributed_embeddings_tpu.cc import build; print('built:', build(force=True))"

# the driver-facing benchmark (real TPU; BENCH_AMP=1 for bf16 compute)
bench:
	$(PY) bench.py

# real-TPU smoke test of the Pallas RMW apply kernel (single-tenant chip:
# don't run while a bench/profile process holds the tunnel)
tpu-smoke:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH $(PY) tools/smoke_pallas_apply.py

# resilience chaos run on the virtual CPU mesh: injected NaN batches, a
# transient checkpoint-write fault, and a kill mid-save — must skip,
# retry, auto-resume, converge, and match the uninterrupted trajectory
# bit-for-bit (tools/chaos_train.py; longer variant is the
# @pytest.mark.slow test in tests/test_resilience.py)
chaos:
	$(PY) tools/chaos_train.py

# cross-run SIGKILL chaos: a REAL worker subprocess is SIGKILLed
# mid-save / between steps and relaunched — at the same world and
# RESIZED (elastic restore) — and the stitched trajectory must match an
# unkilled reference with consumed == steps + skipped across lifetimes
# (tools/chaos_kill.py; the multi-cycle variant is @pytest.mark.slow in
# tests/test_elastic.py)
chaos-kill:
	$(PY) tools/chaos_kill.py

# in-run preemption chaos: a REAL pod-member subprocess is SIGKILLed
# while the pod trains — the surviving trainer quiesces and resizes IN
# PLACE (resilience.elastic.elastic_resize, no checkpoint restore
# round-trip: the ckpt root stays empty), then regrows when a
# replacement member registers; a SIGTERM'd worker drains gracefully
# (finish the in-flight step, snapshot, exit 0 within its deadline) and
# resumes bit-exact. Trajectory checked against an unkilled same-data
# reference; consumed == steps + skipped across the whole run
# (tools/chaos_preempt.py; the full run adds a shrink-to-world-1 cycle)
chaos-preempt:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH $(PY) tools/chaos_preempt.py

# the make-verify tier of the preemption chaos: fewer steps, same
# assertions (SIGKILL shrink + regrow with no restore round-trip,
# SIGTERM drain + bit-exact resume), timeout-guarded like the other
# smoke tiers (the budget covers the reference + pod + drain relaunch
# worker processes, each of which compiles its own steps)
preempt-smoke:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH timeout -k 10 540 \
	  $(PY) tools/chaos_preempt.py --smoke

# multi-controller chaos: a REAL 2-process jax.distributed pod (gloo
# collectives) shrinks 8 -> 4 through the membership barrier when a
# member is SIGKILLed, regrows on a replacement, survives a DUAL
# SIGKILL of both trainer processes plus a torn newest checkpoint (the
# relaunch must broadcast-agree on the newest VALID one and land the
# reference trajectory), and a socket-transport fleet owner process is
# SIGKILLed mid-gather (zero wrong answers), then drained out by a
# scale-down under load (tools/chaos_multiproc.py)
chaos-multiproc:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH $(PY) tools/chaos_multiproc.py

# the make-verify tier: fewer steps/requests, same assertions. The
# budget covers 3 pod lifetimes x 2 controller processes (each pays
# jax.distributed init + per-world step compiles) + the owner
# subprocesses of the fleet cycle
multiproc-smoke:
	PYTHONPATH=$(CURDIR):$$PYTHONPATH timeout -k 10 780 \
	  $(PY) tools/chaos_multiproc.py --smoke

# multi-chip compile/execute validation on 8 virtual CPU devices
dryrun:
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

clean:
	rm -rf distributed_embeddings_tpu/cc/*.so __pycache__ */__pycache__
