"""Synthetic model zoo step-time benchmark.

Equivalent of `/root/reference/examples/benchmarks/synthetic_models/main.py`:
trains one synthetic config (tiny ... colossal) with Adagrad on power-law
inputs and reports mean step time.

  python examples/benchmarks/synthetic_models/main.py --model tiny \
      --batch_size 65536 [--platform cpu] [--shrink 0.01]
"""

import argparse
import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax


def parse_args():
  p = argparse.ArgumentParser()
  p.add_argument("--model", default="tiny",
                 choices=["criteo", "tiny", "small", "medium", "large",
                          "jumbo", "colossal"])
  p.add_argument("--batch_size", type=int, default=65536)
  p.add_argument("--steps", type=int, default=20)
  p.add_argument("--warmup_steps", type=int, default=3)
  p.add_argument("--alpha", type=float, default=1.05,
                 help="power-law exponent for ids (0 = uniform)")
  p.add_argument("--lr", type=float, default=0.01)
  p.add_argument("--strategy", default="memory_balanced")
  p.add_argument("--column_slice_threshold", type=int, default=None)
  p.add_argument("--world_size", type=int, default=None)
  p.add_argument("--num_batches", type=int, default=4,
                 help="distinct input batches to rotate through")
  p.add_argument("--shrink", type=float, default=1.0,
                 help="scale table rows (to fit small test machines)")
  p.add_argument("--amp", action="store_true", help="bf16 compute")
  p.add_argument("--platform", default=None)
  return p.parse_args()


def main():
  args = parse_args()
  if args.platform:
    jax.config.update("jax_platforms", args.platform)

  from distributed_embeddings_tpu.models import (
      SYNTHETIC_MODELS,
      SyntheticModel,
      bce_loss,
      expand_tables,
      generate_batch,
      model_size_gib,
  )
  from distributed_embeddings_tpu.parallel import create_mesh
  from distributed_embeddings_tpu.training import (
      make_train_step,
      shard_batch,
      shard_params,
  )

  cfg = SYNTHETIC_MODELS[args.model]
  if args.shrink != 1.0:
    groups = tuple(
        dataclasses.replace(g, num_rows=max(4, int(g.num_rows * args.shrink)))
        for g in cfg.embedding_groups)
    cfg = dataclasses.replace(cfg, embedding_groups=groups)

  devices = jax.devices()
  world = args.world_size or len(devices)
  mesh = create_mesh(world) if world > 1 else None
  tables, tmap, hotness = expand_tables(cfg)
  print(f"model={cfg.name} tables={len(tables)} inputs={len(tmap)} "
        f"size={model_size_gib(cfg):.1f} GiB world={world} "
        f"batch={args.batch_size} platform={devices[0].platform}")

  model = SyntheticModel(config=cfg, world_size=world,
                         strategy=args.strategy,
                         column_slice_threshold=args.column_slice_threshold,
                         # the planner's scatter-regime cost model needs
                         # the expected batch (docs/BENCHMARKS.md)
                         batch_hint=args.batch_size,
                         compute_dtype=jnp.bfloat16 if args.amp
                         else jnp.float32)

  batches = []
  for i in range(args.num_batches):
    numerical, cats, labels = generate_batch(cfg, args.batch_size,
                                             alpha=args.alpha, seed=i)
    cats = [np.minimum(c, tables[t].input_dim - 1).astype(np.int32)
            for c, t in zip(cats, tmap)]
    batches.append((jnp.asarray(numerical),
                    [jnp.asarray(c) for c in cats], jnp.asarray(labels)))

  params = model.init(jax.random.PRNGKey(0), batches[0][0],
                      batches[0][1])["params"]
  optimizer = optax.adagrad(args.lr)
  opt_state = optimizer.init(params)
  params = shard_params(params, mesh)
  opt_state = shard_params(opt_state, mesh)

  def loss_fn(p, numerical, cats, labels):
    return bce_loss(model.apply({"params": p}, numerical, cats), labels)

  step = make_train_step(loss_fn, optimizer, mesh, params, opt_state,
                         batches[0])
  sharded = [shard_batch(b, mesh) for b in batches]

  for i in range(args.warmup_steps):
    params, opt_state, loss = step(params, opt_state,
                                   *sharded[i % len(sharded)])
  jax.block_until_ready(loss)
  t0 = time.perf_counter()
  for i in range(args.steps):
    params, opt_state, loss = step(params, opt_state,
                                   *sharded[i % len(sharded)])
  jax.block_until_ready(loss)
  ms = (time.perf_counter() - t0) / args.steps * 1000
  print(f"step time: {ms:.3f} ms  "
        f"({args.batch_size / ms * 1000:,.0f} samples/sec)  "
        f"loss {float(loss):.5f}")
  return ms


if __name__ == "__main__":
  main()
