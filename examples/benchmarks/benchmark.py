"""Embedding lookup microbenchmark.

Equivalent of `/root/reference/examples/benchmarks/benchmark.py:23-98`: times
the fused variable-hotness (CSR) lookup against the naive dense-padded
gather+reduce, forward / backward / SGD-apply, at vocab 1M x width 128,
batch 16384, hotness <= 500.

On the reference's GPUs the fused CSR kernel wins; on TPU the answer
INVERTS (measured round 5, v5e: padded-dense forward 11.3 ms = 10.8
ns/row at the gather floor vs csr_lookup 92.7 ms — XLA's ragged
segment-sum does not pipeline) — which is why the distributed engine
serves ragged inputs through sentinel-padded buckets rather than CSR.
Timing uses chained two-length differencing with value-varying operands
and a discarded warm chain (the TPU tunnel relay caches byte-identical
executions and has a multi-second cold start on first chained dispatch).

  python examples/benchmarks/benchmark.py [--platform cpu] [--hotness 64]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import numpy as np


def parse_args():
  p = argparse.ArgumentParser()
  p.add_argument("--vocab", type=int, default=1_000_000)
  p.add_argument("--width", type=int, default=128)
  p.add_argument("--batch", type=int, default=16384)
  p.add_argument("--hotness", type=int, default=64,
                 help="max hotness (uniform 1..max per row)")
  p.add_argument("--steps", type=int, default=4,
                 help="chain length (short: the tunnel relay degrades "
                      "long chains; two lengths are differenced)")
  p.add_argument("--combiner", default="sum", choices=["sum", "mean"])
  p.add_argument("--platform", default=None)
  return p.parse_args()


def timeit(fn, params, ids0, vocab, steps=4):
  """Chained two-length differencing (the bench.py pattern): through the
  TPU tunnel, identical repeated executions can be served from a relay
  cache and block_until_ready under-syncs, so every iteration derives its
  id operand from the previous output (never byte-identical) and one
  scalar is fetched at the end; chains stay SHORT (the relay degrades
  >4-step chains) and two lengths are differenced so dispatch/RTT cancel.
  The (ids+0)%vocab rework costs the same on both sides."""
  # donated accumulator consumer: every iteration's operands and outputs
  # are genuinely different device values with a true serial dependency,
  # so no relay layer can cache, reorder, or collapse the chain
  # params stays an ARGUMENT (closing over it would ship the 512 MB
  # table as a jit constant through the tunnel's compile request)
  acc_step = jax.jit(lambda acc, p, i: acc + fn(p, i), donate_argnums=0)
  out = fn(params, ids0)
  acc = jnp.zeros_like(out)
  acc = acc_step(acc, params, ids0)
  float(acc.ravel()[0])
  it = [0]

  def run(k, a):
    t0 = time.perf_counter()
    for _ in range(k):
      it[0] += 1  # value-varying ids as well
      bump = (a.ravel()[0] * 0).astype(jnp.int32) + it[0]
      a = acc_step(a, params, (ids0 + bump) % vocab)
    float(a.ravel()[0])
    return time.perf_counter() - t0, a

  _, acc = run(steps, acc)  # discard: the first timed chain eats the
  # relay's cold-start (measured ~5 s on the first chained dispatch)
  t1, acc = run(steps, acc)
  t2, acc = run(2 * steps, acc)
  return max((t2 - t1) / steps, 1e-9) * 1000


def main():
  args = parse_args()
  if args.platform:
    jax.config.update("jax_platforms", args.platform)
  from distributed_embeddings_tpu.ops import RaggedIds, csr_lookup

  rng = np.random.default_rng(0)
  params = jnp.asarray(
      rng.standard_normal((args.vocab, args.width)), jnp.float32)
  lengths = rng.integers(1, args.hotness + 1, args.batch)
  nnz = int(lengths.sum())
  values = jnp.asarray(rng.integers(0, args.vocab, nnz), jnp.int32)
  row_splits = jnp.asarray(
      np.concatenate([[0], np.cumsum(lengths)]), jnp.int32)
  dense_ids = jnp.asarray(
      rng.integers(0, args.vocab, (args.batch, args.hotness)), jnp.int32)
  print(f"vocab={args.vocab} width={args.width} batch={args.batch} "
        f"avg_hotness={nnz / args.batch:.1f} nnz={nnz} on "
        f"{jax.devices()[0].platform}")

  fused_fwd = jax.jit(
      lambda p, v: csr_lookup(p, v, row_splits, args.combiner))
  naive_fwd = jax.jit(
      lambda p, i: jnp.sum(jnp.take(p, i, axis=0), axis=1)
      if args.combiner == "sum"
      else jnp.mean(jnp.take(p, i, axis=0), axis=1))

  def grad_of(fwd):
    return jax.jit(jax.grad(lambda p, i: jnp.sum(fwd(p, i) ** 2)))

  def sgd_of(fwd):
    g = jax.grad(lambda p, i: jnp.sum(fwd(p, i) ** 2))
    return jax.jit(lambda p, i: p - 0.01 * g(p, i), donate_argnums=0)

  rows = []
  for name, fwd, ids0 in [("fused_csr", fused_fwd, values),
                          ("padded_dense", naive_fwd, dense_ids)]:
    t_f = timeit(fwd, params, ids0, args.vocab, steps=args.steps)
    t_g = timeit(grad_of(fwd), params, ids0, args.vocab, steps=args.steps)
    sgd = sgd_of(fwd)

    it = [0]

    def sgd_chain(k, p0, sgd=sgd, ids0=ids0, it=it):
      t0 = time.perf_counter()
      for _ in range(k):
        it[0] += 1
        bump = (p0.ravel()[0] * 0).astype(jnp.int32) + it[0]
        p0 = sgd(p0, (ids0 + bump) % args.vocab)
      float(p0.ravel()[0])
      return time.perf_counter() - t0, p0

    p = params + 0  # fresh buffer: sgd donates its input
    _, p = sgd_chain(args.steps, p)  # warm chain (cold-start discard)
    d1, p = sgd_chain(args.steps, p)
    d2, p = sgd_chain(2 * args.steps, p)
    t_s = max((d2 - d1) / args.steps, 1e-9) * 1000
    rows.append((name, t_f, t_g, t_s))
    print(f"{name:>14}: forward {t_f:8.3f} ms  grad {t_g:8.3f} ms  "
          f"sgd-step {t_s:8.3f} ms")
  speedup = rows[1][3] / rows[0][3]
  print(f"fused vs padded sgd-step speedup: {speedup:.2f}x")
  print("note: on TPU the padded-dense form IS the fast form (gathers "
        "run ~10 ns/row regardless of padding waste; XLA's ragged "
        "segment-sum lowering does not pipeline) — the OPPOSITE of the "
        "reference's CUDA result, and why the distributed engine "
        "normalizes ragged inputs into sentinel-padded buckets "
        "internally (docs/ARCHITECTURE.md). csr_lookup is the "
        "API-parity/correctness form.")


if __name__ == "__main__":
  main()
