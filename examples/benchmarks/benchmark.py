"""Embedding lookup microbenchmark.

Equivalent of `/root/reference/examples/benchmarks/benchmark.py:23-98`: times
the fused variable-hotness (CSR) lookup against the naive dense-padded
gather+reduce, forward / backward / SGD-apply, at vocab 1M x width 128,
batch 16384, hotness <= 500.

  python examples/benchmarks/benchmark.py [--platform cpu] [--hotness 64]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import numpy as np


def parse_args():
  p = argparse.ArgumentParser()
  p.add_argument("--vocab", type=int, default=1_000_000)
  p.add_argument("--width", type=int, default=128)
  p.add_argument("--batch", type=int, default=16384)
  p.add_argument("--hotness", type=int, default=64,
                 help="max hotness (uniform 1..max per row)")
  p.add_argument("--steps", type=int, default=20)
  p.add_argument("--combiner", default="sum", choices=["sum", "mean"])
  p.add_argument("--platform", default=None)
  return p.parse_args()


def timeit(fn, *args, steps=20):
  out = jax.block_until_ready(fn(*args))  # compile
  t0 = time.perf_counter()
  for _ in range(steps):
    out = fn(*args)
  jax.block_until_ready(out)
  return (time.perf_counter() - t0) / steps * 1000


def main():
  args = parse_args()
  if args.platform:
    jax.config.update("jax_platforms", args.platform)
  from distributed_embeddings_tpu.ops import RaggedIds, csr_lookup

  rng = np.random.default_rng(0)
  params = jnp.asarray(
      rng.standard_normal((args.vocab, args.width)), jnp.float32)
  lengths = rng.integers(1, args.hotness + 1, args.batch)
  nnz = int(lengths.sum())
  values = jnp.asarray(rng.integers(0, args.vocab, nnz), jnp.int32)
  row_splits = jnp.asarray(
      np.concatenate([[0], np.cumsum(lengths)]), jnp.int32)
  dense_ids = jnp.asarray(
      rng.integers(0, args.vocab, (args.batch, args.hotness)), jnp.int32)
  print(f"vocab={args.vocab} width={args.width} batch={args.batch} "
        f"avg_hotness={nnz / args.batch:.1f} nnz={nnz} on "
        f"{jax.devices()[0].platform}")

  fused_fwd = jax.jit(
      lambda p: csr_lookup(p, values, row_splits, args.combiner))
  naive_fwd = jax.jit(
      lambda p: jnp.sum(jnp.take(p, dense_ids, axis=0), axis=1)
      if args.combiner == "sum"
      else jnp.mean(jnp.take(p, dense_ids, axis=0), axis=1))

  def grad_of(fwd):
    return jax.jit(jax.grad(lambda p: jnp.sum(fwd(p) ** 2)))

  def sgd_of(fwd):
    g = jax.grad(lambda p: jnp.sum(fwd(p) ** 2))
    return jax.jit(lambda p: p - 0.01 * g(p), donate_argnums=0)

  rows = []
  for name, fwd in [("fused_csr", fused_fwd), ("padded_dense", naive_fwd)]:
    t_f = timeit(fwd, params, steps=args.steps)
    t_g = timeit(grad_of(fwd), params, steps=args.steps)
    sgd = sgd_of(fwd)
    p = params + 0  # fresh buffer: sgd donates its input
    t0 = time.perf_counter()
    for _ in range(args.steps):
      p = sgd(p)
    jax.block_until_ready(p)
    t_s = (time.perf_counter() - t0) / args.steps * 1000
    rows.append((name, t_f, t_g, t_s))
    print(f"{name:>14}: forward {t_f:8.3f} ms  grad {t_g:8.3f} ms  "
          f"sgd-step {t_s:8.3f} ms")
  speedup = rows[1][3] / rows[0][3]
  print(f"fused vs padded sgd-step speedup: {speedup:.2f}x")


if __name__ == "__main__":
  main()
