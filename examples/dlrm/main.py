"""DLRM training on Criteo (TPU-native).

Equivalent of `/root/reference/examples/dlrm/main.py`: trains DLRM on the
split-binary Criteo dataset (or dummy data) with hybrid model/data parallel
embeddings, warmup+poly-decay SGD, AUC evaluation, and a final global-view
numpy checkpoint.

Usage:
  python examples/dlrm/main.py --dataset dummy --steps 100 --batch_size 4096
  python examples/dlrm/main.py --dataset_path /data/criteo --amp
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_embeddings_tpu.layers import get_weights
from distributed_embeddings_tpu.models import DLRM, bce_loss
from distributed_embeddings_tpu.models.dlrm import dlrm_embedding_plan
from distributed_embeddings_tpu.parallel import create_mesh
from distributed_embeddings_tpu.training import (
    make_eval_step,
    make_train_step,
    shard_batch,
    shard_params,
)
from distributed_embeddings_tpu.utils import (
    DummyDataset,
    RawBinaryCriteoDataset,
    dlrm_lr_schedule,
)

CRITEO_1TB_VOCAB = [
    39884406, 39043, 17289, 7420, 20263, 3, 7120, 1543, 63, 38532951,
    2953546, 403346, 10, 2208, 11938, 155, 4, 976, 14, 39979771,
    25641295, 39664984, 585935, 12972, 108, 36
]


def parse_args():
  p = argparse.ArgumentParser(description=__doc__)
  p.add_argument("--dataset", choices=["dummy", "criteo"], default="dummy")
  p.add_argument("--eval_every", type=int, default=0,
                 help="run the AUC eval every N train steps (0 = only at "
                      "the end, reference cadence is per-epoch)")
  p.add_argument("--dataset_path", default=None,
                 help="split-binary Criteo dir (model_size.json supported)")
  p.add_argument("--batch_size", type=int, default=8192,
                 help="global batch size")
  p.add_argument("--steps", type=int, default=100)
  p.add_argument("--epochs", type=int, default=1)
  p.add_argument("--lr", type=float, default=24.0)
  p.add_argument("--warmup_steps", type=int, default=2750)
  p.add_argument("--decay_start_step", type=int, default=49315)
  p.add_argument("--decay_steps", type=int, default=27772)
  p.add_argument("--embedding_dim", type=int, default=128)
  p.add_argument("--strategy", default="memory_balanced",
                 choices=["basic", "memory_balanced", "memory_optimized"])
  p.add_argument("--column_slice_threshold", type=int, default=None)
  p.add_argument("--amp", action="store_true", help="bf16 compute")
  p.add_argument("--world_size", type=int, default=None,
                 help="mesh size; default = all devices")
  p.add_argument("--eval", action="store_true")
  p.add_argument("--save_checkpoint", default=None,
                 help="path for final np.savez global checkpoint")
  p.add_argument("--sparse", action="store_true",
                 help="fused sparse training path (packed tables, "
                      "row-sparse SGD; the bench.py path)")
  p.add_argument("--micro_batches", type=int, default=1,
                 help="bounded-memory accumulation: run the sparse step "
                      "over N batch slices in a scan, capping "
                      "per-occurrence temporaries at 1/N (one-shot "
                      "numerics preserved; sparse path only)")
  p.add_argument("--checkpoint_dir", default=None,
                 help="full train-state checkpoint dir (sparse path only); "
                      "auto-resumes when it exists")
  p.add_argument("--checkpoint_every", type=int, default=0,
                 help="save the full state every N steps (0 = end only)")
  p.add_argument("--row_slice", type=int, default=None,
                 help="row (vocab) slice threshold in elements")
  p.add_argument("--vocab_scale", type=float, default=1.0,
                 help="scale Criteo vocab sizes (for memory-limited runs)")
  p.add_argument("--platform", default=None,
                 help="force a jax platform (e.g. 'cpu'); this image pins a "
                      "TPU backend via sitecustomize, so env vars are not "
                      "enough")
  return p.parse_args()


def load_vocab(args):
  if args.dataset_path:
    meta = os.path.join(args.dataset_path, "model_size.json")
    if os.path.exists(meta):
      # reference reads table sizes from the dataset's model_size.json
      # (`examples/dlrm/main.py:68-73`)
      with open(meta) as f:
        sizes = list(json.load(f).values())
      return [s + 1 for s in sizes]
  return [max(4, int(v * args.vocab_scale)) for v in CRITEO_1TB_VOCAB]


def auc(labels: np.ndarray, scores: np.ndarray) -> float:
  """Rank-based AUC (Mann-Whitney), no sklearn dependency."""
  order = np.argsort(scores, kind="mergesort")
  ranks = np.empty_like(order, dtype=np.float64)
  ranks[order] = np.arange(1, len(scores) + 1)
  # average ties
  sorted_scores = scores[order]
  i = 0
  while i < len(sorted_scores):
    j = i
    while j + 1 < len(sorted_scores) and sorted_scores[j + 1] == sorted_scores[i]:
      j += 1
    if j > i:
      ranks[order[i:j + 1]] = ranks[order[i:j + 1]].mean()
    i = j + 1
  pos = labels > 0.5
  n_pos, n_neg = pos.sum(), (~pos).sum()
  if n_pos == 0 or n_neg == 0:
    return float("nan")
  return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def main():
  args = parse_args()
  if args.platform:
    jax.config.update("jax_platforms", args.platform)
  devices = jax.devices()
  world = args.world_size or len(devices)
  mesh = create_mesh(world) if world > 1 else None
  vocab = load_vocab(args)
  print(f"devices={len(devices)} world={world} tables={len(vocab)} "
        f"total_rows={sum(vocab):,}")

  model = DLRM(vocab_sizes=vocab,
               embedding_dim=args.embedding_dim,
               world_size=world,
               strategy=args.strategy,
               column_slice_threshold=args.column_slice_threshold,
               row_slice=args.row_slice,
               batch_hint=args.batch_size,
               compute_dtype=jnp.bfloat16 if args.amp else jnp.float32)

  local_bs = args.batch_size // world
  if args.dataset == "dummy":
    train_data = DummyDataset(args.batch_size, 13, vocab,
                              num_batches=args.steps)
    eval_data = DummyDataset(args.batch_size, 13, vocab, num_batches=4,
                             seed=777)
  else:
    train_data = RawBinaryCriteoDataset(
        args.dataset_path, local_bs, numerical_features=13,
        categorical_features=list(range(len(vocab))),
        categorical_feature_sizes=vocab, world_size=world)
    eval_data = RawBinaryCriteoDataset(
        args.dataset_path, local_bs, numerical_features=13,
        categorical_features=list(range(len(vocab))),
        categorical_feature_sizes=vocab, world_size=world, valid=True)

  print("building model/state ...", flush=True)
  _t_setup = time.time()
  numerical, cats, labels = train_data[0]
  batch_example = (jnp.asarray(numerical), [jnp.asarray(c) for c in cats],
                   jnp.asarray(labels))
  schedule = dlrm_lr_schedule(args.lr, args.warmup_steps,
                              args.decay_start_step, args.decay_steps)
  optimizer = optax.sgd(schedule)
  plan = dlrm_embedding_plan(vocab, args.embedding_dim, world,
                             args.strategy, args.column_slice_threshold,
                             row_slice=args.row_slice,
                             batch_hint=args.batch_size)

  if args.sparse:
    # fused sparse path: packed tables with row-sparse SGD, full-state
    # checkpoint/resume (beyond the reference, which checkpoints weights
    # only -- `examples/dlrm/main.py:245-248`)
    from distributed_embeddings_tpu import checkpoint as ckpt
    from distributed_embeddings_tpu.ops.packed_table import sgd_rule
    from distributed_embeddings_tpu.training import (
        init_sparse_state_direct,
        make_sparse_train_step,
    )
    rule = sgd_rule(schedule)
    # init the DENSE params only (dummy embedding activations skip the
    # table creation); the packed class buffers are drawn directly in
    # their physical layout by init_sparse_state_direct — materializing
    # simple-layout tables first would transiently need ~2.5x the class
    # bytes and grinds a near-HBM-sized model to a halt (bench.py:96)
    dummy_acts = [jnp.zeros((2, args.embedding_dim), jnp.float32)
                  for _ in vocab]
    dense_params = model.init(
        jax.random.PRNGKey(0), batch_example[0][:2],
        [c[:2] for c in batch_example[1]], emb_acts=dummy_acts)["params"]
    state = init_sparse_state_direct(plan, rule, dense_params, optimizer,
                                     jax.random.PRNGKey(1))
    state = shard_params(state, mesh)
    if args.checkpoint_dir and os.path.isdir(args.checkpoint_dir):
      state = ckpt.restore(args.checkpoint_dir, plan, rule, state, mesh=mesh)
      print(f"resumed from {args.checkpoint_dir} at step "
            f"{int(jax.device_get(state['step']))}")
    print(f"sparse state ready in {time.time() - _t_setup:.1f}s", flush=True)
    sparse_step = make_sparse_train_step(model, plan, bce_loss, optimizer,
                                         rule, mesh, state, batch_example,
                                         donate=False,
                                         micro_batches=args.micro_batches)

    # One jitted wrapper that takes the cats as a SINGLE [B, n_tables]
    # matrix and splits it on device: feeding 26 separate feature arrays
    # pays one host->device dispatch latency EACH per step (measured
    # ~300 ms/step through this host link vs ~30 ms for 3 arrays), which
    # would bound the pipeline far below the chip's step rate.
    n_tables = len(vocab)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step_fn(carry, numerical, cats_mat, labels):
      cats = [cats_mat[:, i] for i in range(n_tables)]
      return sparse_step(carry, numerical, cats, labels)

    carry = state
  else:
    params = model.init(jax.random.PRNGKey(0), batch_example[0],
                        batch_example[1])["params"]
    opt_state = optimizer.init(params)
    params = shard_params(params, mesh)
    opt_state = shard_params(opt_state, mesh)

    def loss_fn(params, numerical, cats, labels):
      logits = model.apply({"params": params}, numerical, cats)
      return bce_loss(logits, labels)

    dense_step = make_train_step(loss_fn, optimizer, mesh, params,
                                 opt_state, batch_example, donate=False)
    n_tables = len(vocab)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step_fn(carry, numerical, cats_mat, labels):
      cats = [cats_mat[:, i] for i in range(n_tables)]
      params, opt_state, loss = dense_step(*carry, numerical, cats, labels)
      return (params, opt_state), loss

    carry = (params, opt_state)

  _eval_cache = {}

  def run_eval(carry):
    """Rank-wise AUC over the eval split (reference main.py:222-243).
    The jitted eval step is built once and reused across cadenced calls."""
    if "step" not in _eval_cache:
      if args.sparse:
        from distributed_embeddings_tpu.training import make_sparse_eval_step
        raw_eval = make_sparse_eval_step(model, plan, rule, mesh, carry,
                                         batch_example[:2])
        _eval_cache["step"] = lambda st, *xs: jax.nn.sigmoid(
            raw_eval(st, *xs))
      else:
        def pred_fn(params, numerical, cats):
          return jax.nn.sigmoid(model.apply({"params": params}, numerical,
                                            cats))
        dense_eval = make_eval_step(pred_fn, mesh, carry[0],
                                    batch_example[:2])
        _eval_cache["step"] = lambda st, *xs: dense_eval(st[0], *xs)
    eval_fn = _eval_cache["step"]
    all_scores, all_labels = [], []
    for numerical, cats, labels in eval_data:
      sharded = shard_batch(
          (jnp.asarray(numerical), [jnp.asarray(c) for c in cats]), mesh)
      all_scores.append(np.asarray(eval_fn(carry, *sharded)))
      all_labels.append(labels)
    return auc(np.concatenate(all_labels), np.concatenate(all_scores))

  print(f"setup done in {time.time() - _t_setup:.1f}s; first step "
        "compiles ...", flush=True)
  t_start, losses = time.time(), []
  steps_done = 0
  for epoch in range(args.epochs):
    for batch in train_data:
      numerical, cats, labels = batch
      # host->device conversion of batch k+1 overlaps the device compute
      # of step k because steps dispatch asynchronously — as long as
      # nothing here blocks. The loss is therefore kept as a DEVICE
      # scalar and only fetched at log points (fetching every step would
      # sync every step and serialize transfer behind compute), and the
      # cats travel as ONE stacked matrix (see step_fn).
      cats_mat = np.stack([np.asarray(c, np.int32) for c in cats], axis=1)
      sharded = shard_batch(
          (jnp.asarray(numerical), jnp.asarray(cats_mat),
           jnp.asarray(labels)), mesh)
      carry, loss = step_fn(carry, *sharded)
      losses.append(loss)
      steps_done += 1
      if steps_done == 1:
        print(f"first step (compile) {time.time() - t_start:.1f}s",
              flush=True)
      if steps_done % 100 == 0:
        # ONE stacked fetch (a float() per scalar would pay the host
        # link's round-trip latency 100 times); trim the list so a long
        # run doesn't pin an unbounded set of device scalars
        window = np.asarray(jax.device_get(jnp.stack(losses[-100:])))
        losses = [float(x) for x in window]
        rate = steps_done * args.batch_size / (time.time() - t_start)
        print(f"step {steps_done} loss {window.mean():.5f} "
              f"{rate:,.0f} samples/sec")
      if args.eval_every and steps_done % args.eval_every == 0:
        score = run_eval(carry)
        print(f"step {steps_done} eval AUC: {score:.5f}")
      if args.sparse and args.checkpoint_dir and args.checkpoint_every \
          and steps_done % args.checkpoint_every == 0:
        ckpt.save(args.checkpoint_dir, plan, rule, carry)
        print(f"checkpointed step {steps_done} -> {args.checkpoint_dir}")
      if steps_done >= args.steps:
        break
    if steps_done >= args.steps:
      break
  # drain the dispatch queue before reading the clock: the loop above only
  # DISPATCHES steps (that is what lets transfer overlap compute), so the
  # throughput number must wait for the last step to actually finish
  if losses:
    losses = list(np.asarray(jax.device_get(jnp.stack(losses[-10:]))))
  elapsed = time.time() - t_start
  print(f"trained {steps_done} steps in {elapsed:.1f}s "
        f"({steps_done * args.batch_size / max(elapsed, 1e-9):,.0f} samples/sec)"
        f" final loss {np.mean(losses[-10:]):.5f}")

  if args.sparse and args.checkpoint_dir:
    ckpt.save(args.checkpoint_dir, plan, rule, carry)
    print(f"saved full train state -> {args.checkpoint_dir}")

  if args.eval:
    print(f"eval AUC: {run_eval(carry):.5f}")

  if args.save_checkpoint:
    # global-view numpy table checkpoint (reference
    # `examples/dlrm/main.py:245-248`)
    if args.sparse:
      from distributed_embeddings_tpu.training import unpack_sparse_state
      full_params, _ = unpack_sparse_state(plan, rule, carry)
      tables = get_weights(plan, full_params["embeddings"])
    else:
      tables = get_weights(plan, carry[0]["embeddings"])
    np.savez(args.save_checkpoint, *tables)
    print(f"saved {len(tables)} tables to {args.save_checkpoint}")


if __name__ == "__main__":
  main()
